"""Schedule model of compiled HLO: async pairs and their compute shadows.

The double-buffered ring (PR 2), the async fenced checkpoint and every
other latency-hiding claim this repo ships reduce to ONE property of the
*scheduled* instruction stream: each communication op is split into a
``-start``/``-done`` pair and real compute sits between them.  The
collective pass counts those bytes but is blind to WHERE they sit; this
module parses the entry computation's instruction order into a
:class:`ScheduleModel` so the placement itself becomes lintable:

* every async pair (``collective-permute-start/done``,
  ``all-reduce-start/done``, ``all-gather-start/done``,
  ``copy-start/done``, ...) is matched by the start instruction's name
  appearing in the done's operands;
* a start whose done never arrives (or vice versa) is broken scheduling
  and surfaces as an *unpaired* record;
* the instructions between each start and its done are the pair's
  **shadow** — the dot FLOPs and result bytes of compute the scheduler
  actually hid behind the wire.  A start directly followed by its done
  (``shadow_ops == 0``) is a *serialized* pair: the async split bought
  nothing.

:class:`SchedulePass` checks the model against per-program ``overlap``
floors in ``benchmarks/budgets.json``, so "2*(n-1) overlapped
collective-permutes per ring step" is a committed contract, not a claim.
XLA:CPU legalizes collectives synchronously, so the canonical CPU-mesh
programs report an empty model (an info row); the contract is proven on
the canned real-TPU HLO corpus under ``tests/data/hlo/`` (provenance in
its README), the same canned-snippet pattern ``test_hlo_stats.py`` uses.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field

from .framework import Pass
from .hlo_parse import _scan_shape, dot_flops_report, shape_bytes

__all__ = ["AsyncPair", "ScheduleModel", "SchedulePass", "parse_schedule"]

# op families whose -start/-done splits the schedule model pairs up.
# 'copy' covers cross-memory-space prefetch (copy-start/copy-done);
# 'send'/'recv' are omitted on purpose — their channel semantics pair
# across modules, not within one entry computation.
ASYNC_OPS = ("collective-permute", "all-reduce", "all-gather",
             "reduce-scatter", "all-to-all", "collective-broadcast",
             "copy")

# '%name = shape op(...)' — the lhs instruction name (ROOT-prefixed on
# the root), then the shape (balanced scan — tuples nest), then the op
_LHS_RE = re.compile(r"^\s*(?:ROOT\s+)?(%?[\w.\-]+)\s*=\s*")
_OP_NAME_RE = re.compile(r"\s*([a-z][a-z0-9\-]*)\(")
_OPERAND_RE = re.compile(r"%[\w.\-]+")
# structural ops that are free at runtime: their result bytes are not
# compute the scheduler hid behind a wire
_STRUCTURAL_OPS = ("parameter", "constant", "tuple", "get-tuple-element",
                   "bitcast", "after-all", "iota")


@dataclass
class AsyncPair:
    """One matched ``-start``/``-done`` pair in entry-computation order."""

    op: str                 # family, e.g. "collective-permute"
    start_name: str         # lhs name of the -start instruction
    start_index: int        # position in the entry instruction stream
    done_index: int
    bytes: int              # wire payload (the -start's result tuple)
    shadow_flops: int = 0   # dot/conv FLOPs between start and done
    shadow_bytes: int = 0   # result bytes of compute between the pair
    shadow_ops: int = 0     # compute instructions between the pair

    @property
    def serialized(self):
        """True when the start retired immediately: no compute between
        the pair, so the async split hid nothing."""
        return self.shadow_ops == 0

    def to_dict(self):
        return {"op": self.op, "start": self.start_name,
                "window": [self.start_index, self.done_index],
                "bytes": self.bytes, "shadow_flops": self.shadow_flops,
                "shadow_bytes": self.shadow_bytes,
                "shadow_ops": self.shadow_ops,
                "serialized": self.serialized}


@dataclass
class ScheduleModel:
    """The entry computation's async structure, in instruction order."""

    instructions: int = 0
    pairs: list = field(default_factory=list)
    unpaired_starts: list = field(default_factory=list)
    unpaired_dones: list = field(default_factory=list)

    def by_op(self):
        out = {}
        for p in self.pairs:
            out.setdefault(p.op, []).append(p)
        return out

    def serialized_pairs(self):
        return [p for p in self.pairs if p.serialized]

    def summary(self):
        return {"instructions": self.instructions,
                "pairs": len(self.pairs),
                "unpaired": len(self.unpaired_starts)
                + len(self.unpaired_dones),
                "serialized": len(self.serialized_pairs()),
                "shadow_flops": sum(p.shadow_flops for p in self.pairs),
                "shadow_bytes": sum(p.shadow_bytes for p in self.pairs)}


def _entry_lines(compiled_text):
    """The instruction lines of the ENTRY computation, in order.  Fusion
    and while-body computations are separate blocks in the module text;
    only the entry's stream IS the top-level schedule."""
    lines = []
    in_entry = False
    for line in compiled_text.splitlines():
        if not in_entry:
            if line.lstrip().startswith("ENTRY ") and line.rstrip(). \
                    endswith("{"):
                in_entry = True
            continue
        if line.strip() == "}":
            break
        if "=" in line:
            lines.append(line)
    return lines


def _async_split(op_name):
    """('collective-permute', '-start') for async spellings, else
    (op_name, None)."""
    for suffix in ("-start", "-done"):
        if op_name.endswith(suffix):
            base = op_name[:-len(suffix)]
            if base in ASYNC_OPS:
                return base, suffix
    return op_name, None


def parse_schedule(compiled_text):
    """Parse compiled HLO text into a :class:`ScheduleModel`.

    One pass over the entry computation's instruction stream: starts are
    recorded by lhs name; a same-family done whose operands reference a
    pending start closes the pair; everything else is compute whose dot
    FLOPs and result bytes accrue to the shadow of every open pair."""
    model = ScheduleModel()
    open_pairs = {}     # start lhs name -> AsyncPair
    for index, line in enumerate(_entry_lines(compiled_text)):
        lm = _LHS_RE.match(line)
        if lm is None:
            continue
        model.instructions += 1
        lhs = lm.group(1).lstrip("%")
        shape_s, end = _scan_shape(line, lm.end())
        om = _OP_NAME_RE.match(line, end)
        op_name = om.group(1) if om is not None else ""
        base, suffix = _async_split(op_name)
        if suffix == "-start":
            open_pairs[lhs] = AsyncPair(
                op=base, start_name=lhs, start_index=index,
                done_index=-1, bytes=_pair_bytes(base, shape_s))
            continue
        if suffix == "-done":
            operands = [t.lstrip("%")
                        for t in _OPERAND_RE.findall(line[end:])]
            hit = next((n for n in operands
                        if n in open_pairs and open_pairs[n].op == base),
                       None)
            if hit is None:
                model.unpaired_dones.append(
                    {"op": base, "name": lhs, "index": index})
                continue
            pair = open_pairs.pop(hit)
            pair.done_index = index
            model.pairs.append(pair)
            continue
        if op_name in _STRUCTURAL_OPS or not op_name:
            continue
        # plain compute: it shadows every currently-open pair
        if open_pairs:
            flops = dot_flops_report(line)["flops"]
            nbytes = shape_bytes(shape_s)
            for pair in open_pairs.values():
                pair.shadow_flops += flops
                pair.shadow_bytes += nbytes
                pair.shadow_ops += 1
    for pair in open_pairs.values():
        model.unpaired_starts.append(
            {"op": pair.op, "name": pair.start_name,
             "index": pair.start_index})
    model.pairs.sort(key=lambda p: p.start_index)
    return model


def _pair_bytes(op, shape_s):
    """Wire payload of a '-start' result tuple — the same op-specific
    layout rules :func:`~mxnet_tpu.analysis.hlo_parse.collective_stats`
    prices (copy-start carries (dest, src, ctx): count the dest)."""
    from .hlo_parse import _start_bytes

    if op == "copy":
        from .hlo_parse import _split_top_level

        parts = _split_top_level(shape_s)
        return shape_bytes(parts[0]) if parts else 0
    return _start_bytes(op, shape_s)


class SchedulePass(Pass):
    """Async-overlap contract: pairs matched, shadows above the floors.

    Findings:

    * an unpaired ``-start``/``-done`` is always an **error** — the
      schedule references an async op whose other half never ran;
    * a serialized pair (start directly followed by its done) is an
      **error** when the program has an ``overlap`` budget (the budget
      says this program PAYS for latency hiding) and a visible *info*
      row otherwise;
    * ``overlap`` floors per op family::

          {"programs": {"<program>": {"overlap": {
              "collective-permute": {"min_pairs": 6,
                                     "min_shadow_flops": 1,
                                     "max_serialized": 0}}}}}

      fewer matched pairs than ``min_pairs``, any pair whose shadow
      FLOPs sit under ``min_shadow_flops``, or more serialized pairs
      than ``max_serialized`` (default 0 once an overlap budget exists)
      are **errors** naming the op family and the measured values.

    Overlap budgets describe TPU-compiled artifacts; XLA:CPU keeps sync
    collectives, so the canonical CPU-mesh programs carry no ``overlap``
    entries and report an info row (``sync-backend``) — the contract is
    exercised against the canned corpus under ``tests/data/hlo/``.
    """

    name = "schedule"
    requires = ("compiled",)

    def run(self, artifact, context):
        model = parse_schedule(artifact.compiled_text)
        budget = (context.budget_for(artifact.name) or {}).get("overlap")
        findings = []
        for rec in model.unpaired_starts:
            findings.append(self.finding(
                artifact, "error",
                "%s-start %r (entry index %d) has no matching -done in "
                "the entry computation — broken async schedule"
                % (rec["op"], rec["name"], rec["index"]),
                code="unpaired-start", **rec))
        for rec in model.unpaired_dones:
            findings.append(self.finding(
                artifact, "error",
                "%s-done %r (entry index %d) references no open -start "
                "in the entry computation" %
                (rec["op"], rec["name"], rec["index"]),
                code="unpaired-done", **rec))
        serialized = model.serialized_pairs()
        if serialized and budget is None:
            findings.append(self.finding(
                artifact, "info",
                "%d of %d async pair(s) retire immediately (start "
                "directly followed by done — zero overlap window): %s"
                % (len(serialized), len(model.pairs),
                   [p.start_name for p in serialized[:8]]),
                code="serialized-pair",
                pairs=[p.to_dict() for p in serialized[:8]]))
        for op, ceiling in sorted((budget or {}).items()):
            pairs = model.by_op().get(op, [])
            ser = [p for p in pairs if p.serialized]
            min_pairs = ceiling.get("min_pairs", 0)
            if len(pairs) < min_pairs:
                findings.append(self.finding(
                    artifact, "error",
                    "overlap budget promises >= %d async %s pair(s) but "
                    "the schedule carries %d — the latency-hiding "
                    "structure was lost (sync legalization or a "
                    "scheduling regression)" % (min_pairs, op, len(pairs)),
                    code="missing-pairs", op=op, measured=len(pairs),
                    budget=min_pairs))
            if len(ser) > ceiling.get("max_serialized", 0):
                findings.append(self.finding(
                    artifact, "error",
                    "%d async %s pair(s) retire immediately (max %d "
                    "allowed): the -start/-done split hides nothing for "
                    "%s" % (len(ser), op,
                            ceiling.get("max_serialized", 0),
                            [p.start_name for p in ser[:8]]),
                    code="serialized-pair", op=op, measured=len(ser),
                    budget=ceiling.get("max_serialized", 0)))
            floor = ceiling.get("min_shadow_flops", 0)
            thin = [p for p in pairs
                    if not p.serialized and p.shadow_flops < floor]
            if floor and thin:
                findings.append(self.finding(
                    artifact, "error",
                    "%d async %s pair(s) shadow fewer than %d FLOPs of "
                    "compute (min shadow %d) — the wire is no longer "
                    "hidden behind the chunk matmul" %
                    (len(thin), op, floor,
                     min(p.shadow_flops for p in thin)),
                    code="thin-shadow", op=op, floor=floor,
                    pairs=[p.to_dict() for p in thin[:8]]))
        if not findings:
            if not model.pairs:
                findings.append(self.finding(
                    artifact, "info",
                    "no async collective pairs in the entry computation "
                    "(sync backend or collective-free program)",
                    code="sync-backend", **model.summary()))
            else:
                findings.append(self.finding(
                    artifact, "info",
                    "%d async pair(s) all matched, min shadow %d FLOPs "
                    "/ %d bytes" %
                    (len(model.pairs),
                     min(p.shadow_flops for p in model.pairs),
                     min(p.shadow_bytes for p in model.pairs)),
                    code="overlapped", **model.summary()))
        return findings
