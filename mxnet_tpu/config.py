"""Runtime environment-variable configuration registry.

TPU-native analog of the reference's ~25 ``dmlc::GetEnv`` runtime knobs
catalogued in ``docs/how_to/env_var.md:8-94`` (engine threads, memory-pool
reserve, bulk-exec caps, ...).  Most of those knobs configure machinery XLA
subsumes (thread pools, memory planner), so the registry here is smaller but
the *mechanism* is the same: every runtime flag is declared in one place with
a type, default and docstring, read once, and discoverable via
``config.describe()`` instead of scattered ``os.environ`` reads.

Variables keep the ``MXNET_`` prefix for reference compatibility.
"""
from __future__ import annotations

import contextlib
import os

__all__ = ["EnvVar", "register", "get", "describe", "refresh",
           "overrides"]

_REGISTRY = {}


def _parse_bool(s):
    return str(s).lower() in ("1", "true", "yes", "on")


class EnvVar:
    """One declared runtime flag."""

    __slots__ = ("name", "type", "default", "doc", "_value", "_loaded")

    def __init__(self, name, type, default, doc):
        self.name = name
        self.type = type
        self.default = default
        self.doc = doc
        self._value = None
        self._loaded = False

    def get(self):
        if not self._loaded:
            raw = os.environ.get(self.name)
            if raw is None:
                self._value = self.default
            elif self.type is bool:
                self._value = _parse_bool(raw)
            else:
                self._value = self.type(raw)
            self._loaded = True
        return self._value

    def reset(self):
        self._loaded = False


def register(name, type, default, doc):
    """Declare a runtime flag; returns the EnvVar."""
    var = EnvVar(name, type, default, doc)
    _REGISTRY[name] = var
    return var


def get(name):
    """Read a declared flag (cached after first read)."""
    return _REGISTRY[name].get()


def refresh(name=None):
    """Drop the cached value(s) so the next get() re-reads the environment."""
    if name is not None:
        _REGISTRY[name].reset()
    else:
        for var in _REGISTRY.values():
            var.reset()


@contextlib.contextmanager
def overrides(**knobs):
    """Temporarily pin declared flags through the environment.

    ``with config.overrides(MXNET_PALLAS_DECODE="1"):`` sets each env
    var (``None`` unsets it), refreshes the registry cache so the new
    values are live inside the block, and restores BOTH the environment
    and the cache on exit — the save/set/refresh/restore dance that
    benches, the canonical-program drives and tests otherwise each
    hand-roll.  Values are written with ``str()``; booleans should be
    passed as "1"/"0" strings to match how the environment spells them.
    """
    saved = {k: os.environ.get(k) for k in knobs}
    try:
        for k, v in knobs.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        refresh()
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        refresh()


def describe():
    """Human-readable catalog of every declared flag (env_var.md analog)."""
    lines = []
    for name in sorted(_REGISTRY):
        var = _REGISTRY[name]
        lines.append("%s (%s, default=%r)\n    %s"
                     % (name, var.type.__name__, var.default, var.doc))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Declared flags.  Reference counterparts cited where one exists.
# ---------------------------------------------------------------------------
register("MXNET_COMPUTE_DTYPE", str, "",
         "Default compute dtype for compiled train steps ('bfloat16', "
         "'float32', ...). Empty = float32. Master weights stay float32. "
         "TPU-era replacement for the reference's fp16 casting idiom.")
register("MXNET_FUSED_TRAIN_STEP", bool, True,
         "Fuse forward+backward+optimizer into one donated XLA program in "
         "Module when the optimizer supports it (analog of the reference's "
         "bulk-exec segments, graph_executor.cc:678-756).")
register("MXNET_EXEC_BULK_EXEC_INFERENCE", bool, True,
         "Jit-compile whole inference graphs (reference env_var.md: bulk "
         "execution for inference). Off = per-op eager interpretation for "
         "debugging, the NaiveEngine analog.")
register("MXNET_BACKWARD_DO_MIRROR", bool, False,
         "Trade compute for memory by rematerializing activations in the "
         "backward pass via jax.checkpoint (reference env_var.md mirror).")
register("MXNET_ENGINE_TYPE", str, "",
         "Set to 'NaiveEngine' to force eager, per-op execution for "
         "debugging (reference src/engine/engine.cc:13-39).")
register("MXNET_PROFILER_AUTOSTART", bool, False,
         "Start the profiler at import time (reference env_var.md:71-79).")
register("MXNET_PALLAS_ATTENTION", bool, False,
         "Use the Pallas flash-attention kernel for dot_product_attention "
         "on supported shapes (self-attention, block-divisible T, head dim "
         "multiple of 64): O(T) memory instead of the einsum path's O(T^2) "
         "logits.  Differentiable (custom_vjp backward kernels), so "
         "training takes the flash path too.  Falls back to einsum "
         "otherwise.")
register("MXNET_PALLAS_DECODE", bool, False,
         "Use the fused Pallas flash-decoding kernels "
         "(ops/pallas_decode.py) for decode/verify attention over KV "
         "caches: the page-table gather, int8/fp8 dequantization and the "
         "length-masked softmax run in ONE HBM pass over the pool "
         "(PagedAttention's in-kernel gather), with a split-K grid axis "
         "parallelizing over cache length (Flash-Decoding) so small-batch "
         "decode fills the chip.  Applies to paged pools AND dense ring "
         "buffers (identity page table).  Engages on TPU, or anywhere "
         "under MXNET_PALLAS_INTERPRET; unsupported shapes (or a "
         "mesh-sharded cache — Pallas is opaque to GSPMD) fall back to "
         "the three-pass paged_gather+sdpa_decode einsum path, which the "
         "mxlint flop-dtype tripwire reports on the canonical paged "
         "programs so the fallback is never silent.")
register("MXNET_PALLAS_UPDATE", bool, False,
         "Use the fused multi-tensor Pallas optimizer-update kernel "
         "(ops/pallas_update.py) inside the compiled train step: the "
         "donated param/grad/slot trees flatten into dtype-homogeneous "
         "flat slabs (multi-tensor apply) and ONE Pallas pass per slab "
         "does grad rescale + clip + bf16->f32 promotion + the "
         "SGD-momentum/Adam moment update (at the true update count t) "
         "+ the compute-dtype recast — replacing the per-parameter XLA "
         "update fusions, whose cast/rescale/clip/update/recast chain "
         "round-trips every param, grad and slot through HBM "
         "separately.  Engages on TPU, or anywhere under "
         "MXNET_PALLAS_INTERPRET; unsupported optimizers (anything but "
         "SGD/Adam), non-float32/bfloat16 params, mesh-sharded masters "
         "and the eager opt_owner fall back to the existing per-param "
         "path unchanged (the mxlint flop-dtype pass's pallas-fallback "
         "tripwire covers the promise on canonical programs).")
register("MXNET_PALLAS_FUSED", bool, False,
         "Dispatch the LM training path's LN->linear segments (the "
         "pre-norm block's LN->QKV projections and LN->MLP, including "
         "the ReLU prologue and the residual add) to the fused Pallas "
         "epilogue kernel (ops/pallas_fused.py): the affine apply, the "
         "matmul, the bias, the ReLU and the residual run in ONE HBM "
         "pass over the activations, forward AND backward (custom_vjp), "
         "inside the compiled donated train step.  Engages on TPU, or "
         "anywhere under MXNET_PALLAS_INTERPRET; unsupported shapes/"
         "dtypes, mesh-sharded executors and every other caller fall "
         "back to the einsum composition with identical semantics "
         "(ops/fused_lm.py FUSED_PATH records which path traced).")
register("MXNET_PALLAS_TUNE", bool, False,
         "Autotune Pallas kernel block shapes on the live device "
         "(ops/tuning.py): each kernel module's registered candidate "
         "space is swept layout_probe-style (timed probes), and the "
         "winner is persisted in the content-addressed tuning cache "
         "(the MXNET_PROGRAM_CACHE directory) keyed by (device "
         "generation, op, shape-class, dtype) — a later process "
         "resolves the same key from disk with zero probes.  Off "
         "(default) = the modules' hardcoded constants, which remain "
         "the interpret/CPU-mode defaults; cached winners are still "
         "READ when present.")
register("MXNET_MOE_DISPATCH", str, "sort",
         "Capacity-slot assignment algorithm for the sparse MoE "
         "dispatch (ops/moe.py): 'sort' (default) ranks the (token, "
         "rank-k choice) pairs by argsort over a composite "
         "(expert, priority) key and derives each choice's capacity "
         "position from its index within the sorted expert group "
         "(MegaBlocks-style sort/scatter dispatch — no (N*k, E) one-hot "
         "cumsum ever materializes); 'onehot' restores the one-hot "
         "cumsum pack for A/B comparison.  Both produce BIT-IDENTICAL "
         "slot assignments, outputs, grads and drop sets (tier-1 "
         "asserted); the dispatch intermediates they materialize "
         "differ, priced by analysis/cost.py sort/scatter accounting.")
register("MXNET_KV_LAYOUT", str, "",
         "Device minor-to-major layout requested for decode KV cache "
         "buffers at allocation, as a comma-separated major_to_minor "
         "permutation (e.g. '0,1,2' is row-major).  Set from the winning "
         "row of benchmarks/layout_probe.py --kv, which times decode "
         "attention under each candidate pool layout on the bench chip.  "
         "Empty (default) = the backend's native layout.  Backends "
         "without jax.experimental.layout support (the CPU harness) "
         "ignore it with a one-time warning.")
register("MXNET_PALLAS_INTERPRET", bool, False,
         "Run Pallas kernels in interpret mode on non-TPU backends instead "
         "of falling back to einsum (slow; for testing the kernel dispatch "
         "path on CPU).")
register("MXNET_RING_ATTENTION", bool, True,
         "Under a mesh whose 'seq' axis is sharded (and 'model' is not), "
         "dot_product_attention dispatches to explicit-collective ring "
         "attention (parallel/ring.py) inside the executor program: K/V "
         "blocks rotate via ppermute with O(T/n) memory per device, and "
         "the per-hop compute is the Pallas flash kernel on TPU.  Set 0 "
         "to restore the GSPMD einsum path (the partitioner's all-gather "
         "plan) for A/B comparison.")
register("MXNET_RING_DOUBLE_BUFFER", bool, True,
         "Communication schedule for ring attention (parallel/ring.py): "
         "1 (default) double-buffers the ring — each hop's K/V ppermute "
         "(and the backward ring's traveling dK/dV rotation) is issued "
         "BEFORE the hop's flash/streaming kernel, so backends with "
         "async collectives (TPU: collective-permute-start/done) overlap "
         "the wire time with compute.  0 restores the serial issue order "
         "for A/B measurement (benchmarks/bench_long_context.py records "
         "both).  Schedules are bit-identical in outputs and gradients.")
register("MXNET_MOE_TOPK", int, 0,
         "Override the MoEFFN op's num_experts_per_tok attribute at trace "
         "time (top-k routing: each token is dispatched to its k highest-"
         "probability experts, gates renormalized over the chosen k when "
         "k > 1).  0 (default) keeps the per-op attribute; k = 1 is the "
         "classic switch (top-1) routing with the raw chosen probability "
         "as the gate.")
register("MXNET_MOE_DROPLESS", bool, False,
         "Force the sparse MoE dispatch's overflow policy to 'dropless': "
         "per-device capacity stretches to the worst case (every local "
         "choice fits, padding-masked slots carry the slack) so no token "
         "is ever dropped — at the cost of expert-FFN compute/memory that "
         "scales like the dense path's worst case.  0 (default) keeps the "
         "per-op 'overflow' attribute (Switch drop semantics unless the "
         "symbol says otherwise).")
register("MXNET_MOE_CAPACITY", float, 0.0,
         "Override the MoEFFN op's capacity_factor attribute at trace "
         "time: > 0 arms the sparse capacity-slot dispatch with per-"
         "(group, expert) capacity ceil(cf * k * group_tokens / E).  "
         "0 (default) keeps the per-op attribute.  Under an 'expert' mesh "
         "the sparse path is the explicit all-to-all shard_map program "
         "(docs/moe.md).")
register("MXNET_TP_MODE", str, "megatron",
         "Tensor-parallel sharding plan over the 'model' mesh axis: "
         "'megatron' (default) pairs column-parallel with row-parallel "
         "weights from a graph walk (parallel/tp_rules.py) so one psum per "
         "pair replaces per-layer all-gathers; 'naive' restores the "
         "round-3 blanket dim-0 sharding (for A/B comparison — "
         "tests/test_tensor_parallel.py measures the collective-count "
         "difference from compiled HLO).")
register("MXNET_METRIC_SYNC_PERIOD", int, 0,
         "With device-side metric accumulation active, pull the metric "
         "accumulators to the host every N training steps.  0 (default) "
         "syncs only at natural boundaries (epoch end, or whenever a "
         "callback reads the metric), eliminating the per-step "
         "device->host round trip of the classic loop.")
register("MXNET_DEVICE_METRICS", bool, True,
         "Fold loss/accuracy accumulation into the donated train-step "
         "program as extra donated state for metrics that implement the "
         "device protocol (metric.py device_batch).  The training loop "
         "then never materializes per-step outputs on the host; 0 "
         "restores the classic host-side metric.update path.")
register("MXNET_MAX_STEPS_IN_FLIGHT", int, 2,
         "Upper bound on dispatched-but-unfinished training steps in "
         "fit(): the loop rides JAX's async dispatch and blocks on the "
         "step-K-behind result rather than the current one, overlapping "
         "host-side batch prep with device compute while bounding live "
         "device buffers.  1 = fully synchronous loop (the dependency-"
         "engine analog of the reference's NaiveEngine).")
register("MXNET_PREFETCH_DEPTH", int, 2,
         "How many batches DevicePrefetchIter keeps device-resident "
         "ahead of the consumer (the dmlc::ThreadedIter capacity analog, "
         "moved past the host->device DMA).")
register("MXNET_DEVICE_PREFETCH", bool, True,
         "Let fit() wrap the training iterator in a DevicePrefetchIter "
         "when a fused train step is active, so the next batches are "
         "device_put with the executor group's input sharding on a "
         "background thread while the current step runs.  0 = feed "
         "batches from the host thread as the reference does.")
register("MXNET_DECODE_SLOTS", int, 8,
         "Batch width of the continuous-batching serving loop "
         "(decode.DecodeServer): the decode-step program always runs this "
         "many in-flight sequence slots at a fixed shape, so admitting or "
         "retiring a request never retraces.  Free slots refill from the "
         "request queue after every step (Orca-style iteration-level "
         "scheduling).")
register("MXNET_DECODE_DONATE", bool, True,
         "Donate the KV caches (and per-slot lengths) into the jitted "
         "decode-step program so XLA appends in place — zero steady-state "
         "allocation in the token loop.  0 keeps the inputs alive across "
         "the call for debugging (inspect a cache mid-generation).")
register("MXNET_KV_DTYPE", str, "",
         "Storage dtype for the decode KV caches (decode.DecodePredictor): "
         "'int8', 'float8_e4m3fn' ('f8e4m3') or 'float8_e5m2' ('f8e5m2') "
         "quantize K/V in cache_append with per-(token, head) fp32 scales "
         "and dequantize inside sdpa_decode/sdpa_verify, halving or "
         "quartering the bytes every decode step streams from the cache — "
         "decode's bandwidth bound.  Empty (default) stores full-precision "
         "K/V.  The mxlint cache-bytes pass budgets the resulting cache "
         "size and flags an f32 cache in a quantized config.")
register("MXNET_KV_PAGED", bool, False,
         "Store decode KV caches as fixed-size pages in one shared device "
         "pool per attention node instead of a dense ring buffer per slot "
         "(decode.DecodePredictor paged mode + the mxnet_tpu.serve memory "
         "manager): per-slot page tables are traced DATA, so admissions, "
         "copy-on-write prefix forks and retirements never retrace, and "
         "HBM scales with tokens actually live instead of "
         "slots x max-context (vLLM's PagedAttention plan).  Arms prefix "
         "sharing (matching prompts map their leading pages to shared "
         "refcounted pages and prefill only the tail) and chunked prefill.")
register("MXNET_KV_PAGE_TOKENS", int, 16,
         "Tokens per KV page in paged mode.  Smaller pages waste less "
         "memory on the last partial page per sequence and share prefixes "
         "at finer granularity; larger pages mean fewer gather indices and "
         "less page-table overhead.  cache_len must divide by it.")
register("MXNET_KV_POOL_PAGES", int, 0,
         "Total pages in the shared KV pool (page id 0 is reserved as the "
         "scratch page).  0 (default) sizes the pool to fit every slot at "
         "full capacity (slots x cache_len/page_tokens + 1) — safe but no "
         "memory win; production serving sizes it to the live-token "
         "working set and lets admission backpressure (mxnet_tpu.serve."
         "PageAllocator reservations) queue requests that do not fit.")
register("MXNET_PREFILL_CHUNK", int, 0,
         "Chunk width for paged-mode prefill: prompts are admitted in "
         "fixed-size chunks of this many tokens, interleaved with decode "
         "steps, so a long prompt does not stall the whole serving batch "
         "(one traced chunk program per width — still zero retraces).  "
         "0 (default) prefills each prompt's tail in one chunk sized to "
         "the admission window.")
register("MXNET_SPEC_K", int, 0,
         "Tokens drafted per speculative-decoding step (decode.DecodeServer "
         "/ DecodePredictor.generate_speculative).  A proposer drafts k "
         "tokens, ONE fixed-shape verify pass through the target scores "
         "all k+1 positions, and the acceptance-rejection rule keeps the "
         "output distribution exactly the target's — each step commits "
         "1..k+1 tokens for one target forward.  0 (default) disables "
         "speculation; the serving loop then takes the plain one-token "
         "decode step.")
register("MXNET_SPEC_NGRAM", int, 2,
         "Suffix length the model-free n-gram proposer (decode."
         "NGramProposer) matches against each sequence's own history "
         "(prompt-lookup / self-speculation).  Longer suffixes propose "
         "more conservatively: fewer matches, higher acceptance when one "
         "hits.")
register("MXNET_DECODE_MAX_NEW", int, 256,
         "Default cap on generated tokens per request in the serving loop "
         "when the caller gives no explicit max_new_tokens (a sequence "
         "with no EOS must retire eventually so its slot can refill).")
register("MXNET_TRANSFER_GUARD", str, "off",
         "Arm jax.transfer_guard_device_to_host around fit()'s hot loop: "
         "'log' reports and 'disallow' raises on a device->host transfer "
         "inside the training epoch, making the async loop's zero-per-"
         "step-host-syncs invariant a runtime-checked guarantee on real "
         "accelerators (same-device CPU 'transfers' are free and never "
         "trip it; the static half is analysis.HostSyncPass).  'off' "
         "(default) leaves the loop unguarded — required for the classic "
         "host-metric path, which reads outputs every step.")
register("MXNET_ANALYSIS_SUPPRESS", str, "",
         "Comma-separated suppression patterns for static-analysis "
         "findings: 'pass-name[:program[:code]]' with '*' wildcards "
         "(e.g. 'flop-dtype:decode_step:f32-dot').  Applied on top of "
         "the budget file's suppressions list; suppressed findings stay "
         "in reports, marked, so waivers are visible.")
register("MXNET_ANALYSIS_BUDGETS", str, "",
         "Path to the static-analysis budget file consumed by "
         "analysis.load_budgets / tools/mxlint.py.  Empty (default) = "
         "the committed benchmarks/budgets.json.")
register("MXNET_CKPT_DIR", str, "",
         "Directory for elastic fence checkpoints (mxnet_tpu.elastic).  "
         "Set together with MXNET_CKPT_PERIOD to arm fit()-integrated "
         "async fenced checkpointing: at every period-th step fence the "
         "donated params/slots/aux chain is snapshotted on device (cheap "
         "async copies) and written as a committed orbax step directory "
         "by a background writer thread, with a sidecar carrying the loop "
         "state (epoch/step, RNG chain, metric sums, iterator cursor) for "
         "deterministic resume.  Empty (default) = no automatic "
         "checkpointing; an explicit elastic.ElasticController passed to "
         "fit() overrides the environment.")
register("MXNET_CKPT_PERIOD", int, 0,
         "Steps between elastic fence checkpoints (0 = off).  Snapshots "
         "ride the in-flight step machinery: the copy dispatch depends on "
         "the latest dispatched step, so the loop never blocks on the "
         "device to checkpoint.")
register("MXNET_CKPT_ASYNC", bool, True,
         "Write fence checkpoints on a background writer thread (at most "
         "ONE write in flight; a fence landing while a write is busy is "
         "skipped, not queued — the next fence writes).  0 = synchronous "
         "saves on the loop thread, the A/B baseline whose stall the "
         "checkpoint_stall_fraction bench field quantifies (its d2h is "
         "the sanctioned fence transfer, exempt from "
         "MXNET_TRANSFER_GUARD).")
register("MXNET_CKPT_KEEP", int, 2,
         "Committed fence checkpoints to retain (older step directories "
         "are pruned after each commit; 0 = keep all).  Two is the "
         "crash-safe minimum floor: the newest commit plus its "
         "predecessor, in case a torn successor must be discarded.")
register("MXNET_CKPT_RESUME", bool, True,
         "Auto-resume: when the checkpoint directory already holds a "
         "committed step at fit() start, restore it (params, optimizer "
         "slots, RNG chain, metric sums, iterator cursor) and continue "
         "from the recorded epoch/step instead of training from scratch.  "
         "0 = always start fresh (the directory still receives new "
         "checkpoints).")
register("MXNET_ELASTIC_POLL", int, 1,
         "Poll the failure monitor every N step fences (elastic liveness "
         "protocol).  Each poll is num_workers stat/read calls on the "
         "heartbeat directory — no device work.")
register("MXNET_ELASTIC_TIMEOUT", float, 10.0,
         "Heartbeat staleness threshold (seconds) for the elastic "
         "FailureMonitor: a rank whose stamp is older is declared dead "
         "and the mesh shrinks off its data rows at the next fence.")
register("MXNET_ELASTIC_GRACE", float, 30.0,
         "Startup allowance (seconds) for registered-but-not-yet-stamped "
         "workers: within this window of the heartbeat directory's epoch "
         "a missing first stamp does not read as dead.")
register("MXNET_TELEMETRY", bool, True,
         "Arm the unified telemetry subsystem (mxnet_tpu.obs): timed "
         "dispatch wrappers on the compiled programs (the per-program "
         "MFU/roofline table), always-on timeline spans and instant "
         "events (bounded ring buffer), and the lazy static-cost "
         "probers.  Purely host-side — compiled HLO is byte-identical "
         "on or off (tests/test_obs.py pins it).  The step_stats loop "
         "counters predate the subsystem and stay on regardless.")
register("MXNET_TRACE_BUFFER", int, 65536,
         "Capacity (events) of the always-on trace-timeline ring buffer "
         "(mxnet_tpu.obs.timeline).  Oldest events are evicted first, so "
         "an armed timeline costs bounded memory however long the "
         "process lives; profiler.dump_profile exports whatever is "
         "retained as Chrome-trace JSON.")
register("MXNET_METRICS_EXPORT", str, "",
         "Path for the metrics registry's JSON-lines snapshot exporter: "
         "with MXNET_METRICS_EXPORT_PERIOD > 0, a background thread "
         "appends one {ts, metrics} line per period.  Empty (default) = "
         "no file export; the registry is still readable in-process "
         "(obs.registry.snapshot) and over HTTP (MXNET_METRICS_PORT).")
register("MXNET_METRICS_EXPORT_PERIOD", float, 0.0,
         "Seconds between JSON-lines metric snapshots written to "
         "MXNET_METRICS_EXPORT (0 = off).")
register("MXNET_METRICS_PORT", int, 0,
         "Serve the metrics registry over HTTP from decode.DecodeServer "
         "(obs.MetricsServer, 127.0.0.1): /metrics is the Prometheus "
         "text format, /metrics.json the snapshot, /trace the current "
         "timeline as Chrome-trace JSON.  0 (default) = no server.")
register("MXNET_PEAK_FLOPS", float, 0.0,
         "Peak accelerator FLOP/s used as the MFU denominator in the "
         "per-program roofline table (obs.mfu_table / bench.py "
         "mfu_table / tools/mxstat.py).  0 (default) = look the device "
         "kind up in the TPU spec table; unknown devices (the CPU "
         "harness) then report mfu=null while flops/bytes/wall stay "
         "populated.")
register("MXNET_FLEET_SWAP", bool, True,
         "Arm preemption/swap in the paged serving loop "
         "(decode.DecodeServer / serve.swap): when the page pool cannot "
         "admit the queue head for MXNET_FLEET_DECODE_BOUND consecutive "
         "decode iterations, the lowest-priority (then longest-running) "
         "slot's pages move to host RAM as a restorable record, the "
         "waiter admits on the freed pages, and the victim re-queues — "
         "readmitted later by restoring its pages bit-exactly at the "
         "same ring positions, so a long decode can no longer wedge "
         "admission.  0 = classic backpressure only (the queue waits "
         "for retirements).")
register("MXNET_FLEET_DECODE_BOUND", int, 8,
         "Fair-admission bound for the paged serving loop: consecutive "
         "pool-gate-blocked decode iterations tolerated before a "
         "preemption swap-out (MXNET_FLEET_SWAP) makes room for the "
         "queue head.  Bounds the admission-starvation tail a long "
         "wrapped decode can inflict (single host AND fleet p95 TTFT); "
         "equal-priority thrash is bounded to one swap per this many "
         "iterations — round-robin time slicing, every request still "
         "finishes.  0 disables the bound (swap never triggers on "
         "fairness grounds).")
register("MXNET_FLEET_PREFILL_THRESHOLD", float, 0.5,
         "Disaggregation routing threshold (serve.fleet.Router): a "
         "prompt whose best cache-aware chain match covers at least "
         "this fraction of its tokens admits DIRECTLY on the matching "
         "decode host (its chunked prefill computes only the tail); "
         "colder prompts go to a dedicated prefill worker, whose "
         "committed pages migrate to the least-loaded decode host "
         "(DistServe-style prefill/decode split).  Only consulted when "
         "the router has prefill workers.")
register("MXNET_AOT", bool, False,
         "Arm the AOT-serialized program pipeline (mxnet_tpu.programs."
         "aot): DecodeServer.serve_open prepares every paged serving "
         "program (chunk prefill, decode, verify, commit, fork, page "
         "extract/install) through the content-addressed program cache "
         "— a cache hit DESERIALIZES the compiled executable "
         "(milliseconds) instead of trace+lower+compile (seconds to "
         "minutes per host), and a miss compiles once and saves the "
         "executable back for the next host's cold start.  Loaded "
         "programs are byte-identical to the JIT path (same lowering) "
         "and dispatch with ZERO traces; an argument signature the "
         "executable was not compiled for falls back to JIT with a "
         "visible warning.  Covers paged single-host predictors; "
         "mesh-sharded and dense predictors keep the JIT path (logged "
         "at serve_open — serialized executables pin device layouts).  "
         "0 (default) = classic JIT-on-first-call.")
register("MXNET_PROGRAM_CACHE", str, "",
         "Directory of the content-addressed AOT program cache "
         "(mxnet_tpu.programs.aot): <fingerprint>.aotx serialized "
         "executables plus .json sidecars, keyed over (abstract args, "
         "donation map, partition rules, jax version, backend, mesh "
         "shape, model graph digest) — any drift is a key miss, never "
         "a wrong program.  Empty (default) = ~/.cache/mxnet_tpu/"
         "programs.  Shared read-only across fleet hosts; equal keys "
         "prove byte-identical programs (docs/programs.md).")
register("MXNET_HEARTBEAT_DIR", str, "",
         "Shared directory for worker liveness heartbeats (failure "
         "detection, parallel/health.py; reference ps-lite heartbeats). "
         "Read dynamically at KVStore creation, not cached here.")
register("MXNET_IS_RECOVERY", bool, False,
         "Mark this worker as a restart: startup-only barriers are skipped "
         "(reference kvstore_dist.h is_recovery).  Read dynamically at "
         "each startup barrier, not cached here.")
