"""Imperative autograd (tape-based).

Reference: `src/ndarray/autograd.{h,cc}` + `python/mxnet/contrib/autograd.py`
— MarkVariables attaches grad buffers, executed imperative ops are recorded
into an AGNode tape, ComputeGradient builds a graph and drives a backward
executor.  TPU-native: the tape records (op, attrs, inputs, outputs); the
backward pass replays the tape as a pure JAX function of the marked
variables and differentiates it with ``jax.vjp`` — jax AD replaces the
hand-built gradient graph.
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["is_recording", "is_training", "set_is_training", "mark_variables",
           "backward", "compute_gradient", "record", "train_section",
           "test_section", "grad_and_loss", "grad"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []          # list of (opdef, attrs, input_ids, out_ids)
        _state.values = {}        # id -> NDArray (kept alive while recording)
        _state.variables = {}     # id -> (NDArray, grad NDArray)
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_is_training(train_mode):
    prev = _st().training
    _st().training = bool(train_mode)
    return prev


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers (reference: autograd.py:87 MarkVariables)."""
    st = _st()
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, grad, req in zip(variables, gradients, grad_reqs):
        st.variables[id(var)] = (var, grad, req)
        st.values[id(var)] = var


def record_op(opdef, attrs, inputs, outputs, rng=None, aux=()):
    """Called from imperative_invoke while recording."""
    st = _st()
    aux = list(aux)
    for nd in inputs + outputs + aux:
        st.values[id(nd)] = nd
    st.tape.append(
        (opdef, attrs, [id(i) for i in inputs], [id(o) for o in outputs], rng,
         [id(a) for a in aux]))


class record:
    """``with autograd.record():`` — recording + train mode scope."""

    def __init__(self, train_mode=True):
        self._train = train_mode
        self._prev = None
        self._prev_train = None

    def __enter__(self):
        st = _st()
        self._prev = st.recording
        self._prev_train = st.training
        st.recording = True
        st.training = self._train
        if not self._prev:
            st.tape = []
            st.values = {vid: v for vid, v in st.values.items()
                         if vid in st.variables}
        return self

    def __exit__(self, *args):
        st = _st()
        st.recording = self._prev
        st.training = self._prev_train


# reference contrib.autograd API names
class train_section(record):
    def __init__(self):
        super().__init__(train_mode=True)


class test_section(record):
    def __init__(self):
        super().__init__(train_mode=False)


def backward(outputs, out_grads=None, retain_graph=False):
    """Compute gradients of outputs w.r.t. marked variables, accumulate
    into their grad buffers (reference: autograd.py:60 backward)."""
    compute_gradient(outputs, out_grads, retain_graph=retain_graph)


def compute_gradient(outputs, out_grads=None, retain_graph=False):
    import jax
    import jax.numpy as jnp

    st = _st()
    if not st.variables:
        raise MXNetError("no variables marked for gradient")
    var_ids = list(st.variables.keys())
    out_ids = [id(o) for o in outputs]

    # values of non-variable leaves captured as constants
    tape = list(st.tape)

    def replay(var_vals):
        env = {vid: v for vid, v in zip(var_ids, var_vals)}

        def lookup(nid):
            if nid in env:
                return env[nid]
            return st.values[nid].data

        from .registry import OpContext

        for opdef, attrs, in_ids, o_ids, rng, aux_ids in tape:
            ins = [lookup(i) for i in in_ids]
            # aux states replay as constants (non-differentiated)
            auxs = [jax.lax.stop_gradient(lookup(a)) for a in aux_ids]
            octx = OpContext(is_train=True,
                             rng=rng if rng is not None else jax.random.PRNGKey(0))
            outs, _ = opdef.fcompute(attrs, ins, auxs, octx)
            for oid, val in zip(o_ids, outs):
                env[oid] = val
        return [env[o] if o in env else st.values[o].data for o in out_ids]

    var_vals = [st.variables[vid][0].data for vid in var_ids]
    out_vals, vjp_fn = jax.vjp(lambda *vs: replay(list(vs)), *var_vals)
    if out_grads is None:
        cts = [jnp.ones_like(o) for o in out_vals]
    else:
        cts = [g.data for g in out_grads]
    grads = vjp_fn(list(cts))
    grad_nds = []
    for vid, g in zip(var_ids, grads):
        var, grad_buf, req = st.variables[vid]
        if req == "add":
            grad_buf._set_data((grad_buf.data + g).astype(grad_buf.data.dtype))
        elif req != "null":
            grad_buf._set_data(g.astype(grad_buf.data.dtype))
        grad_nds.append(grad_buf)
    if not retain_graph:
        st.tape = []
        # drop recorded intermediates so device buffers are released;
        # keep only the marked variables
        st.values = {vid: st.values[vid] for vid in var_ids if vid in st.values}
    return grad_nds


def grad_and_loss(func, argnum=None):
    """Decorator returning (gradients, loss) (reference: autograd.py:117)."""

    def wrapped(*args):
        import jax

        nds = list(args)
        idx = range(len(nds)) if argnum is None else (
            [argnum] if isinstance(argnum, int) else argnum)

        idx = list(idx)

        def fn(*vals):
            from .ndarray import NDArray

            by_pos = dict(zip(idx, vals))
            full = [NDArray(by_pos[i], nds[i]._ctx) if i in by_pos else nds[i]
                    for i in range(len(nds))]
            out = func(*full)
            return out.data

        vals = [nds[i].data for i in idx]
        loss, vjp_fn = jax.vjp(fn, *vals)
        import jax.numpy as jnp

        grads = vjp_fn(jnp.ones_like(loss))
        from .ndarray import NDArray

        ctx = nds[0]._ctx
        return [NDArray(g, ctx) for g in grads], NDArray(loss, ctx)

    return wrapped


def grad(func, argnum=None):
    def wrapped(*args):
        return grad_and_loss(func, argnum)(*args)[0]

    return wrapped
