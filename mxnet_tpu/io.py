"""Data iterators.

Reference: `src/io/` + `python/mxnet/io.py` — IIterator registry, MNISTIter,
ImageRecordIter, CSVIter, batching/prefetch composition layers.  TPU-native:
host-side numpy pipelines feeding device batches; PrefetchingIter
double-buffers on a worker thread (the dmlc::ThreadedIter analog,
`src/io/iter_prefetcher.h:28`).  The heavy RecordIO/image path lives in
`recordio.py` / `image.py` with a C++ accelerated reader in src/ (native
runtime).
"""
from __future__ import annotations

import gzip
import logging
import os
import struct
import threading
import queue as _queue

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray, array

__all__ = ["DataBatch", "DataIter", "DataDesc", "NDArrayIter", "MNISTIter",
           "CSVIter", "ResizeIter", "PrefetchingIter", "DevicePrefetchIter",
           "ImageRecordIter"]


class DataDesc:
    """Named shape/dtype descriptor (reference: io.py DataDesc namedtuple)."""

    def __init__(self, name, shape, dtype=np.float32, layout="NCHW"):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.layout = layout

    def __iter__(self):
        # unpacks like the (name, shape) tuple the reference uses
        yield self.name
        yield self.shape

    def __getitem__(self, i):
        return (self.name, self.shape)[i]

    def __len__(self):
        return 2

    def __eq__(self, other):
        if isinstance(other, (tuple, list)):
            return (self.name, self.shape) == tuple(other)
        return (self.name, self.shape) == (other.name, other.shape)

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)


class DataBatch:
    """One mini-batch (reference: io.py:66)."""

    def __init__(self, data, label=None, pad=0, index=None, bucket_key=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator base (reference: io.py:92)."""

    # whether reset() depends on the position earlier epochs reached
    # (NDArrayIter roll_over carries the tail cursor across reset).  The
    # elastic cold-resume path replays the prior-epoch drain/reset
    # lifecycle ONLY for iterators flagging this — stateless-reset
    # iterators reproduce every epoch from one reset, so the replay would
    # be pure O(epochs x dataset) startup waste.  Wrappers delegate to
    # their source.
    reset_carries_state = False

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError()

    def getdata(self):
        raise NotImplementedError()

    def getlabel(self):
        raise NotImplementedError()

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError()

    def fast_forward(self, n):
        """Advance ``n`` batches from the current position, as if they had
        been consumed — the elastic-resume cursor restore (a fence
        checkpoint records how many batches the interrupted epoch served;
        see docs/elasticity.md).  The base implementation draws and
        discards, which replays deterministically for EVERY iterator —
        RecordIO readers and bucketed iterators included — since epoch
        order is fixed at reset; seekable iterators override it with an
        O(1) cursor jump.  Background-thread wrappers (PrefetchingIter /
        DevicePrefetchIter) inherit the draining form on purpose: their
        source is already ahead by the read-ahead depth, so the queue is
        the only honest place to count consumed batches from."""
        for _ in range(int(n)):
            self.next()


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.py:130-385)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size."

        if shuffle:
            idx = np.arange(self.num_data)
            np.random.shuffle(idx)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.data = [(k, v[:new_n]) for k, v in self.data]
            self.label = [(k, v[:new_n]) for k, v in self.label]
            self.num_data = new_n

        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.cursor = -batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [array(x[1][self.cursor:self.cursor + self.batch_size])
                    for x in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [array(np.concatenate((x[1][self.cursor:], x[1][:pad]), axis=0))
                for x in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    @property
    def reset_carries_state(self):
        # roll_over's reset() folds the over-run cursor back in; pad and
        # discard always restart the epoch from the top
        return self.last_batch_handle == "roll_over"

    def fast_forward(self, n):
        """O(1) cursor jump: ``n`` batches forward is exactly ``n``
        ``iter_next`` increments (the epoch's sample order is fixed at
        construction)."""
        self.cursor += int(n) * self.batch_size

    def checkpoint_state(self):
        """The seekable cursor as a dict — the primitive ``fast_forward``
        is built on, exposed for custom training loops that snapshot and
        seek the iterator directly (the elastic fit path records a batch
        COUNT instead, because its prefetch wrappers read ahead of the
        consumed position)."""
        return {"cursor": int(self.cursor)}

    def restore_state(self, state):
        self.cursor = int(state["cursor"])


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, numpy) (reference: io.py:456)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them "
                        "or dict with them as values")
    ret = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        ret.append((k, np.asarray(v)))
    return ret


class MNISTIter(DataIter):
    """MNIST idx-format reader (reference: src/io/iter_mnist.cc:61-241).

    If the idx files are absent, generates a deterministic synthetic
    class-conditional digit dataset of the same shape so examples and tests
    run hermetically (clearly a deviation: the reference requires the files).
    """

    def __init__(self, image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
                 batch_size=128, shuffle=True, flat=False, silent=False, seed=0,
                 input_shape=None, num_parts=1, part_index=0, **kwargs):
        super().__init__(batch_size)
        if os.path.exists(image) or os.path.exists(image + ".gz"):
            images = _read_idx(image)
            labels = _read_idx(label)
        else:
            if not silent:
                logging.warning(
                    "MNISTIter: idx files %r not found; substituting a "
                    "deterministic SYNTHETIC dataset (accuracy numbers will "
                    "not be comparable to real MNIST). Pass silent=True to "
                    "suppress.", image)
            images, labels = _synthetic_mnist(seed=seed)
        images = images.astype(np.float32) / 255.0
        if num_parts > 1:
            part = len(images) // num_parts
            images = images[part_index * part:(part_index + 1) * part]
            labels = labels[part_index * part:(part_index + 1) * part]
        if input_shape is not None:
            images = images.reshape((len(images),) + tuple(input_shape))
        elif flat:
            images = images.reshape(len(images), -1)
        elif images.ndim == 3:
            # idx images are (n, H, W); add the channel axis (iter_mnist.cc
            # hardcodes 28x28 — here the file's own dims win)
            images = images.reshape(len(images), 1, *images.shape[1:])
        else:
            images = images.reshape(len(images), 1, 28, 28)
        if shuffle:
            rng = np.random.RandomState(seed)
            idx = rng.permutation(len(images))
            images, labels = images[idx], labels[idx]
        self._inner = NDArrayIter(images, labels.astype(np.float32),
                                  batch_size=batch_size, shuffle=False,
                                  last_batch_handle="discard",
                                  data_name="data", label_name="softmax_label")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()

    def fast_forward(self, n):
        self._inner.fast_forward(n)

    @property
    def reset_carries_state(self):
        return self._inner.reset_carries_state


def _read_idx(path):
    if not os.path.exists(path) and os.path.exists(path + ".gz"):
        opener = lambda: gzip.open(path + ".gz", "rb")
    else:
        opener = lambda: open(path, "rb")
    with opener() as f:
        magic = struct.unpack(">i", f.read(4))[0]
        ndim = magic % 256
        shape = tuple(struct.unpack(">i", f.read(4))[0] for _ in range(ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _synthetic_mnist(n=6000, seed=0):
    """Deterministic class-conditional digit-like dataset (28x28, 10 classes).
    Class prototypes are fixed across seeds so train/val splits share the
    task; the seed only varies the samples drawn."""
    protos = np.random.RandomState(42).uniform(0, 255, size=(10, 28, 28)) \
        .astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.uint8)
    noise = rng.normal(0, 16.0, size=(n, 28, 28)).astype(np.float32)
    images = np.clip(protos[labels] * 0.7 + noise, 0, 255).astype(np.uint8)
    return images, labels


class CSVIter(DataIter):
    """CSV reader (reference: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        else:
            label = np.zeros((len(data),), dtype=np.float32)
        self._inner = NDArrayIter(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            data_name="data", label_name="label")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def fast_forward(self, n):
        self._inner.fast_forward(n)

    @property
    def reset_carries_state(self):
        return self._inner.reset_carries_state


class ResizeIter(DataIter):
    """Resize any iterator to a fixed number of batches per epoch
    (reference: io.py:388)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    @property
    def reset_carries_state(self):
        # without the internal reset the source keeps rolling regardless
        return self.data_iter.reset_carries_state or not self.reset_internal

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class _BackgroundIter(DataIter):
    """Shared machinery for background-thread iterators (the
    dmlc::ThreadedIter analog): a bounded queue, STOP-AWARE puts (a worker
    blocked on a full queue observes close()/reset() instead of deadlocking
    it), and exception propagation — a worker that dies re-raises in the
    consumer on the next ``next()`` rather than hanging it forever.

    Subclasses implement ``_produce()`` (return the next payload or raise
    StopIteration) and ``_reset_source()``, then call ``_restart()`` once
    constructed.
    """

    def __init__(self, batch_size, capacity):
        super().__init__(batch_size)
        self._capacity = max(1, int(capacity))
        self._queue = None
        self._stop = threading.Event()
        self._thread = None
        self._done = False

    # -- worker side -------------------------------------------------------
    def _produce(self):
        raise NotImplementedError()

    def _put(self, item):
        """Queue.put that gives up when the consumer signalled stop."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    def _worker(self):
        while not self._stop.is_set():
            try:
                payload = self._produce()
            except StopIteration:
                self._put(("end", None))
                return
            except BaseException as exc:  # propagate, don't die silently
                self._put(("error", exc))
                return
            if not self._put(("batch", payload)):
                return

    # -- consumer side -----------------------------------------------------
    def _restart(self):
        self._stop = threading.Event()
        self._queue = _queue.Queue(maxsize=self._capacity)
        self._done = False
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def close(self):
        """Stop the worker and join it; safe to call repeatedly.  A closed
        iterator raises StopIteration from next() (no producer remains)."""
        self._stop.set()
        self._done = True
        while self._thread is not None and self._thread.is_alive():
            try:  # drain so a put()-blocked worker sees the stop flag
                self._queue.get_nowait()
            except _queue.Empty:
                pass
            self._thread.join(timeout=0.01)
        self._thread = None

    def _reset_source(self):
        raise NotImplementedError()

    def reset(self):
        self.close()
        self._reset_source()
        self._restart()

    def __del__(self):
        self._stop.set()

    def next(self):
        if self._done:
            raise StopIteration
        kind, payload = self._queue.get()
        if kind == "batch":
            return payload
        self._done = True  # worker exited; don't block on an empty queue
        if kind == "error":
            raise payload
        raise StopIteration


class PrefetchingIter(_BackgroundIter):
    """Background-thread prefetch (reference: io.py:529 + iter_prefetcher.h)."""

    def __init__(self, iters, rename_data=None, rename_label=None, capacity=2):
        if not isinstance(iters, list):
            iters = [iters]
        assert len(iters) > 0
        super().__init__(iters[0].batch_size, capacity)
        self.n_iter = len(iters)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._restart()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r[x.name], str) else r[x.name]
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r[x.name], str) else r[x.name]
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    @property
    def reset_carries_state(self):
        return any(i.reset_carries_state for i in self.iters)

    def _produce(self):
        batches = [i.next() for i in self.iters]
        if self.n_iter == 1:
            return batches[0]
        return DataBatch(data=sum([b.data for b in batches], []),
                         label=sum([b.label for b in batches], []),
                         pad=batches[0].pad)

    def _reset_source(self):
        for i in self.iters:
            i.reset()


class DevicePrefetchIter(_BackgroundIter):
    """Prefetch the next K batches ONTO THE DEVICE(S) while the current
    step runs.

    The background thread ``jax.device_put``s each upcoming batch with the
    executor group's per-input sharding (data axis on 'data', time axis on
    'seq' when sharded — the same rule the compiled step applies), so the
    host→device DMA of step n+1 overlaps step n's compute instead of
    serializing in front of it.  The consumer receives ``DataBatch``es whose
    arrays are already device-resident; the train step's own ``device_put``
    then sees an unchanged sharding and is a no-op.

    ``module`` supplies the placement rule from its bound executor group
    (looked up per batch, so reshape/rebind stay safe); alternatively pass
    ``placement``: a callable ``(kind, name, ndarray) -> ndarray`` with kind
    in {'data', 'label'}.  Depth defaults to ``MXNET_PREFETCH_DEPTH``.
    """

    def __init__(self, data_iter, module=None, depth=None, placement=None):
        if depth is None:
            from . import config as _config

            depth = _config.get("MXNET_PREFETCH_DEPTH")
        super().__init__(data_iter.batch_size, depth)
        self.fallback_batches = 0  # batches passed through unplaced (bucketing)
        if placement is None:
            if module is None:
                raise MXNetError("DevicePrefetchIter needs a bound module "
                                 "or an explicit placement function")
            placement = _module_placement(module)

            def _group():
                g = getattr(module, "_exec_group", None)
                if g is None:  # BucketingModule: the active bucket's group
                    g = getattr(getattr(module, "_active", None),
                                "_exec_group", None)
                return g

            def _names_from_module(kind):
                group = _group()
                if group is None:
                    return [d.name for d in
                            (self.data_iter.provide_data if kind == "data"
                             else self.data_iter.provide_label or [])]
                return group.data_names if kind == "data" \
                    else group.label_names

            self._names = _names_from_module
        else:
            self._names = lambda kind: [d.name for d in
                                        (self.data_iter.provide_data
                                         if kind == "data"
                                         else self.data_iter.provide_label
                                         or [])]
        self.data_iter = data_iter
        self._placement = placement
        self._restart()

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    @property
    def reset_carries_state(self):
        return self.data_iter.reset_carries_state

    def _place_list(self, kind, arrs):
        if not arrs:
            return arrs
        names = self._names(kind)
        return [self._placement(kind, names[i], arr)
                if i < len(names) else arr
                for i, arr in enumerate(arrs)]

    def _produce(self):
        batch = self.data_iter.next()
        data = self._place_list("data", batch.data)
        label = self._place_list("label", batch.label)
        if any(p is a for p, a in zip(data, batch.data or [])) or \
                any(p is a for p, a in zip(label, batch.label or [])):
            # at least one array came back untouched: a shape-varying
            # (bucketed) batch the bound executor doesn't describe — the
            # consumer will place it per-bucket
            self.fallback_batches += 1
        return DataBatch(data=data, label=label,
                         pad=batch.pad, index=batch.index,
                         bucket_key=batch.bucket_key,
                         provide_data=batch.provide_data,
                         provide_label=batch.provide_label)

    def _reset_source(self):
        self.data_iter.reset()


def _module_placement(module):
    """Placement rule from a Module's executor group: cast to the bound
    input dtype, then device_put with the group's input sharding.

    Shape-varying (bucketed) batches fall back cleanly: the bound
    executor — and the input sharding derived from it — describes ONE
    bucket's shapes, so an array from a different bucket (or one the
    executor doesn't know at all) is returned untouched and the consumer
    places it per-bucket at step time, instead of committing it to a
    stale sharding the compiled step would then have to undo (or worse,
    crash on).  ``DevicePrefetchIter`` reports how often this happened in
    ``fallback_batches`` so the ROADMAP "prefetch for bucketed iterators"
    gap is observable, not silent.
    """

    def place(kind, name, arr):
        import jax

        group = getattr(module, "_exec_group", None)
        if group is None:        # BucketingModule: the active bucket's group
            active = getattr(module, "_active", None)
            group = getattr(active, "_exec_group", None)
            if group is None:
                return arr
        dst = group.exec_.arg_dict.get(name)
        v = arr.data if isinstance(arr, NDArray) else np.asarray(arr)
        if dst is not None and tuple(v.shape) != tuple(dst.shape):
            return arr           # different bucket: defer to the consumer
        # dst None = graph-unconsumed input (extra label): still placed,
        # with the group's input sharding, as before — only a SHAPE
        # mismatch marks a bucketed batch
        if dst is not None and v.dtype != dst.data.dtype:
            v = v.astype(dst.data.dtype)
        if group._mesh is not None:
            target = group._input_sharding(name)
        else:
            target = group.contexts[0].jax_device
        return NDArray(jax.device_put(v, target), group.contexts[0])

    return place


def ImageRecordIter(**kwargs):
    """RecordIO image pipeline (reference: src/io/iter_image_recordio.cc).

    Provided by `mxnet_tpu.image` (python + native reader); this forwarding
    keeps the reference's `mx.io.ImageRecordIter` name working.
    """
    from . import image

    return image.ImageRecordIter(**kwargs)


def MXDataIter(*args, **kwargs):
    raise MXNetError("MXDataIter wraps the legacy C iterator handles; use the "
                     "named iterators (MNISTIter, ImageRecordIter, CSVIter) directly")
