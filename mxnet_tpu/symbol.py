"""Symbol — declarative graph composition.

TPU-native re-design of the reference's nnvm::Symbol / `python/mxnet/symbol.py`:
a Symbol is a list of output entries over a DAG of nodes, each node an op
from the registry plus string attributes.  Composition, auto-naming
(`NameManager`), attribute scopes (`AttrScope`, incl. ``ctx_group`` for model
parallelism), shape/type inference, JSON save/load, and ``bind`` →
:class:`mxnet_tpu.executor.Executor` which lowers the whole graph into one
jitted XLA program (the analog of GraphExecutor's bulk-exec segments,
`src/executor/graph_executor.cc:678-756` — except XLA fuses and plans memory
for us).

Missing inputs auto-create variable nodes (``convolution0_weight`` …)
exactly as the reference does; mutable inputs (BatchNorm moving stats)
become auxiliary-state variables (the FMutateInputs analog).
"""
from __future__ import annotations

import json

import numpy as np

from .base import MXNetError, AttrScope, NameManager
from . import registry as _reg

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "is_aux_var")

    def __init__(self, op, name, attrs, inputs):
        self.op = op          # OpDef or None for variables
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        self.inputs = list(inputs)  # list of (node, out_index)
        self.is_aux_var = False

    @property
    def is_variable(self):
        return self.op is None

    def parsed_attrs(self):
        return self.op.parse_attrs(self.attrs)


class Symbol:
    """A (multi-)output symbolic expression."""

    def __init__(self, outputs):
        self._outputs = list(outputs)  # list of (node, index)

    # -- composition -------------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("Cannot find output %s in %s" % (index, names))
            index = names.index(index)
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        return (self[i] for i in range(len(self)))

    # -- graph walk --------------------------------------------------------
    def _topo(self):
        order, seen = [], set()

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for inp, _ in node.inputs:
                visit(inp)
            order.append(node)

        for node, _ in self._outputs:
            visit(node)
        return order

    def list_arguments(self):
        return [n.name for n in self._topo() if n.is_variable and not n.is_aux_var]

    def list_outputs(self):
        names = []
        for node, idx in self._outputs:
            if node.is_variable:
                names.append(node.name)
            else:
                outs = node.op.list_outputs(node.parsed_attrs())
                suffix = outs[idx] if idx < len(outs) else str(idx)
                names.append("%s_%s" % (node.name, suffix))
        return names

    def list_auxiliary_states(self):
        return [n.name for n in self._topo() if n.is_aux_var]

    def get_internals(self):
        entries = []
        for node in self._topo():
            if node.is_variable:
                entries.append((node, 0))
            else:
                n_vis = node.op.n_visible_outputs(node.parsed_attrs())
                entries.extend((node, i) for i in range(n_vis))
        return Symbol(entries)

    def get_children(self):
        nodes = []
        for node, _ in self._outputs:
            nodes.extend(node.inputs)
        return Symbol(nodes) if nodes else None

    # -- attributes --------------------------------------------------------
    def attr(self, key):
        if len(self._outputs) == 1:
            return self._outputs[0][0].attrs.get(key, None)
        return None

    def attr_dict(self):
        ret = {}
        for node in self._topo():
            if node.attrs:
                ret[node.name] = {k: str(v) for k, v in node.attrs.items()}
        return ret

    def _set_attr(self, **kwargs):
        for node, _ in self._outputs:
            node.attrs.update(kwargs)

    # -- arithmetic --------------------------------------------------------
    def _binop(self, other, opname, scalar_opname, rop=False):
        if isinstance(other, Symbol):
            lhs, rhs = (other, self) if rop else (self, other)
            return _create(opname, [lhs, rhs], {})
        if isinstance(other, (int, float)):
            return _create(scalar_opname, [self], {"scalar": str(float(other))})
        raise TypeError(str(type(other)))

    def __add__(self, other):
        return self._binop(other, "_plus", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, "_minus", "_minus_scalar")

    def __rsub__(self, other):
        return self._binop(other, "_minus", "_rminus_scalar", rop=True)

    def __mul__(self, other):
        return self._binop(other, "_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __div__(self, other):
        return self._binop(other, "_div", "_div_scalar")

    __truediv__ = __div__

    def __rdiv__(self, other):
        return self._binop(other, "_div", "_rdiv_scalar", rop=True)

    __rtruediv__ = __rdiv__

    def __pow__(self, other):
        return self._binop(other, "_power", "_power_scalar")

    def __neg__(self):
        return self * (-1.0)

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __repr__(self):
        name = self.name
        return "<Symbol %s>" % (name if name else "Grouped")

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes = self._infer_shape_impl(False, *args, **kwargs)
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        arg_names = self.list_arguments()
        known = {}
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})

        shapes = {}   # (id(node), idx) -> shape
        var_shapes = {}
        aux_shapes = {}

        for node in self._topo():
            if node.is_variable:
                shape = known.get(node.name)
                if shape is None and "__shape__" in node.attrs:
                    from .attrs import parse_tuple
                    shape = parse_tuple(node.attrs["__shape__"])
                if node.is_aux_var:
                    aux_shapes[node.name] = shape
                else:
                    var_shapes[node.name] = shape
                shapes[(id(node), 0)] = shape
            else:
                attrs = node.parsed_attrs()
                n_args = node.op.n_inputs(attrs)
                in_entries = node.inputs[:n_args]
                aux_entries = node.inputs[n_args:]
                in_shapes = [shapes.get((id(n), i)) for n, i in in_entries]
                # explicit infer fns deduce param shapes from the data (first)
                # input; if even that is unknown the graph is under-specified
                data_unknown = (in_shapes and in_shapes[0] is None)
                if any(s is None for s in in_shapes) and \
                        (node.op.infer_shape_fn is None or data_unknown):
                    if partial:
                        for i in range(node.op.n_outputs(attrs)):
                            shapes[(id(node), i)] = None
                        continue
                    unknown = [inode.name for (inode, ii), s
                               in zip(in_entries, in_shapes) if s is None]
                    raise MXNetError(
                        "Cannot infer shape for node %s (op %s): inputs %s have "
                        "unknown shapes. Provide shapes for them (check input "
                        "names match data_names/label_names)."
                        % (node.name, node.op.name, unknown))
                try:
                    new_in, out_sh, aux_sh = node.op.infer_shape(
                        attrs, in_shapes, [shapes.get((id(n), i)) for n, i in aux_entries])
                except MXNetError:
                    if partial:
                        for i in range(node.op.n_outputs(attrs)):
                            shapes[(id(node), i)] = None
                        continue
                    raise
                # write back inferred input/param shapes onto variable nodes
                for (inode, iidx), s in zip(in_entries, new_in):
                    if s is not None:
                        prev = shapes.get((id(inode), iidx))
                        if prev is not None and tuple(prev) != tuple(s):
                            raise MXNetError(
                                "Shape mismatch for %s: %s vs %s"
                                % (inode.name, prev, s))
                        shapes[(id(inode), iidx)] = tuple(s)
                        if inode.is_variable:
                            var_shapes[inode.name] = tuple(s)
                for (anode, aidx), s in zip(aux_entries, aux_sh or []):
                    if s is not None:
                        shapes[(id(anode), aidx)] = tuple(s)
                        aux_shapes[anode.name] = tuple(s)
                for i, s in enumerate(out_sh):
                    shapes[(id(node), i)] = tuple(s) if s is not None else None

        arg_res = [var_shapes.get(n) for n in arg_names]
        out_res = [shapes.get((id(n), i)) for n, i in self._outputs]
        aux_res = [aux_shapes.get(n) for n in self.list_auxiliary_states()]
        if not partial and any(s is None for s in arg_res + out_res):
            if not known:
                return None, None, None
            missing = [n for n, s in zip(arg_names, arg_res) if s is None]
            raise MXNetError("Cannot fully infer shapes; missing: %s" % missing)
        return arg_res, out_res, aux_res

    def infer_type(self, *args, **kwargs):
        """Propagate dtypes through the graph (nnvm InferType analog,
        `graph_executor.cc:426`).

        Unification semantics match the reference: an op's unresolved
        variable inputs adopt the dtype promoted over its known inputs, so
        declaring only ``data=float16`` types every downstream weight
        float16 (the fp16/bf16 training pattern,
        tests/python/train/test_dtype.py).  Ops with special typing (Cast,
        Embedding, argmax/argsort, quantize, BatchNorm statistics) override
        via their OpDef ``infer_type`` hook.  Returns (arg_types,
        out_types, aux_types) as numpy dtypes.
        """
        import numpy as np

        arg_names = self.list_arguments()
        known = {}
        if args:
            for name, dt in zip(arg_names, args):
                if dt is not None:
                    known[name] = np.dtype(dt)
        for k, v in kwargs.items():
            if v is not None:
                known[k] = np.dtype(v)

        def promote(dts):
            out = np.dtype(dts[0])
            for d in dts[1:]:
                d = np.dtype(d)
                if d == out:
                    continue
                try:
                    out = np.promote_types(out, d)
                except TypeError:
                    # custom float (ml_dtypes bfloat16) mixed with another
                    # float: numpy can't promote — widen to float32
                    out = np.dtype(np.float32)
            return out

        def floating(t):
            dt = np.dtype(t)
            # ml_dtypes bfloat16 registers with kind 'V'
            return dt.kind == "f" or dt.name == "bfloat16"

        entry_t = {}       # (node id, out idx) -> dtype
        var_t = {}         # variable name -> dtype (None = unresolved)
        aux_t = {}
        for node in self._topo():
            if node.is_variable:
                dt = known.get(node.name)
                if dt is None and node.attrs.get("__dtype__"):
                    dt = np.dtype(node.attrs["__dtype__"])
                if node.is_aux_var:
                    aux_t[node.name] = dt
                else:
                    var_t[node.name] = dt
                entry_t[(id(node), 0)] = dt
                continue
            attrs = node.parsed_attrs()
            n_args = node.op.n_inputs(attrs)
            in_entries = node.inputs[:n_args]
            aux_entries = node.inputs[n_args:]
            in_types = [entry_t.get((id(s), i)) for s, i in in_entries]
            aux_types = [entry_t.get((id(s), i)) for s, i in aux_entries]

            fn = node.op.infer_type_fn
            if fn is not None:
                new_in, out_types, new_aux = fn(attrs, in_types, aux_types)
            else:
                # unify over *floating* inputs: integer index inputs
                # (take/pick/batch_take) must neither promote the output to
                # float64 nor type an unresolved weight as int.  An integer
                # base only applies when every input is a resolved integer
                # (genuinely integral ops).
                resolved = [t for t in in_types if t is not None]
                floats = [t for t in resolved if floating(t)]
                if floats:
                    base = promote(floats)
                elif resolved and len(resolved) == len(in_types):
                    base = promote(resolved)
                else:
                    base = np.dtype(np.float32)
                new_in = [t if t is not None else base for t in in_types]
                out_types = [base] * node.op.n_outputs(attrs)
                new_aux = [t if t is not None else base for t in aux_types]

            # write resolved dtypes back into unresolved variables
            for (src, i), t in zip(in_entries, new_in):
                if t is None:
                    continue
                entry_t[(id(src), i)] = np.dtype(t)
                if src.is_variable and var_t.get(src.name) is None:
                    var_t[src.name] = np.dtype(t)
            for (src, i), t in zip(aux_entries, new_aux or []):
                if t is None:
                    continue
                entry_t[(id(src), i)] = np.dtype(t)
                if src.is_variable and aux_t.get(src.name) is None:
                    aux_t[src.name] = np.dtype(t)
            for i, t in enumerate(out_types):
                entry_t[(id(node), i)] = np.dtype(t) if t is not None else None

        f32 = np.dtype(np.float32)
        arg_types = [var_t.get(n) or f32 for n in arg_names]
        out_types = [entry_t.get((id(n), i)) or f32
                     for n, i in self._outputs]
        aux_types = [aux_t.get(n) or f32
                     for n in self.list_auxiliary_states()]
        return arg_types, out_types, aux_types

    # -- serialization -----------------------------------------------------
    def tojson(self):
        nodes = self._topo()
        nid = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.is_variable else n.op.name,
                "name": n.name,
                "attrs": {k: str(v) for k, v in n.attrs.items()},
                "inputs": [[nid[id(src)], idx, 0] for src, idx in n.inputs],
                "is_aux": n.is_aux_var,
            })
        heads = [[nid[id(n)], idx, 0] for n, idx in self._outputs]
        return json.dumps({"nodes": jnodes, "heads": heads,
                           "arg_nodes": [i for i, n in enumerate(nodes) if n.is_variable]},
                          indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- binding -----------------------------------------------------------
    def simple_bind(self, ctx, grad_req="write", type_dict=None, group2ctx=None,
                    shared_exec=None, **kwargs):
        from .executor import Executor

        return Executor.simple_bind(self, ctx, grad_req=grad_req,
                                    type_dict=type_dict, group2ctx=group2ctx,
                                    shared_exec=shared_exec, **kwargs)

    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        from .executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx, shared_exec=shared_exec)

    # -- eval convenience --------------------------------------------------
    def eval(self, ctx=None, **kwargs):
        from .context import current_context

        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        return ex.forward()


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, **kwargs):
    """Create a variable symbol (reference: symbol.py:1352)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    attr = AttrScope.current().get(attr)
    attr = dict(attr) if attr else {}
    if shape is not None:
        attr["__shape__"] = str(tuple(shape))
    if lr_mult is not None:
        attr["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attr["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        # normalize so infer_type can np.dtype() it back (np.float16 the
        # class would stringify as "<class 'numpy.float16'>")
        attr["__dtype__"] = dtype if isinstance(dtype, str) \
            else np.dtype(dtype).name
    if init is not None:
        attr["__init__"] = init.dumps() if hasattr(init, "dumps") else str(init)
    attr.update(kwargs)
    node = _Node(None, name, attr, [])
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    """Group symbols into one multi-output symbol (reference: symbol.py:1419)."""
    entries = []
    for s in symbols:
        entries.extend(s._outputs)
    return Symbol(entries)


def load_json(json_str):
    data = json.loads(json_str)
    nodes = []
    for jn in data["nodes"]:
        if jn["op"] == "null":
            node = _Node(None, jn["name"], jn.get("attrs", {}), [])
            node.is_aux_var = jn.get("is_aux", False)
        else:
            op = _reg.get_op(jn["op"])
            inputs = [(nodes[i], idx) for i, idx, _ in jn["inputs"]]
            node = _Node(op, jn["name"], jn.get("attrs", {}), inputs)
        nodes.append(node)
    heads = [(nodes[i], idx) for i, idx, _ in data["heads"]]
    return Symbol(heads)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


# ---------------------------------------------------------------------------
# op-function generation (mx.sym.Convolution etc.)
# ---------------------------------------------------------------------------

def _create(op_name, sym_inputs, attrs, name=None):
    op = _reg.get_op(op_name)
    # fill variadic num_args before parsing
    if op.key_var_num_args and op.key_var_num_args not in attrs:
        attrs = dict(attrs)
        attrs[op.key_var_num_args] = str(len(sym_inputs))
    # merge attr scope (system attrs like ctx_group)
    scope_attrs = AttrScope.current().get(None)
    node_attrs = dict(scope_attrs) if scope_attrs else {}
    node_attrs.update(attrs)
    parsed = op.parse_attrs(node_attrs)
    name = NameManager.current().get(name, op.hint)

    arg_names = op.list_arguments(parsed)
    aux_names = op.list_aux(parsed)
    entries = []
    for s in sym_inputs:
        if len(s._outputs) != 1:
            raise MXNetError("Cannot compose multi-output symbol as one input")
        entries.append(s._outputs[0])
    # user may have passed aux states explicitly as trailing inputs
    n_args = len(arg_names)
    user_aux = entries[n_args:]
    entries = entries[:n_args]
    # auto-create missing argument variables (reference behavior)
    while len(entries) < n_args:
        vname = "%s_%s" % (name, arg_names[len(entries)])
        entries.append(Variable(vname)._outputs[0])
    # aux-state variables
    for i, aux_name in enumerate(aux_names):
        if i < len(user_aux):
            entry = user_aux[i]
            if entry[0].is_variable:
                entry[0].is_aux_var = True
            entries.append(entry)
        else:
            vname = "%s_%s" % (name, aux_name)
            v = Variable(vname)
            v._outputs[0][0].is_aux_var = True
            entries.append(v._outputs[0])

    node = _Node(op, name, node_attrs, entries)
    n_vis = op.n_visible_outputs(parsed)
    return Symbol([(node, i) for i in range(n_vis)])


def _make_sym_func(op_name):
    op = _reg.get_op(op_name)

    def sym_func(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        sym_inputs = list(args)
        attrs = {}
        parsed_probe = None
        # split kwargs into symbol inputs vs attrs
        maybe_names = None
        for k, v in list(kwargs.items()):
            if isinstance(v, Symbol):
                if maybe_names is None:
                    probe = {pk: pv for pk, pv in kwargs.items()
                             if not isinstance(pv, Symbol)}
                    if op.key_var_num_args and op.key_var_num_args not in probe:
                        probe[op.key_var_num_args] = str(len(args) or 1)
                    try:
                        parsed_probe = op.parse_attrs(probe)
                        maybe_names = (op.list_arguments(parsed_probe)
                                       + op.list_aux(parsed_probe))
                    except MXNetError:
                        maybe_names = []
                kwargs.pop(k)
                sym_inputs.append((maybe_names.index(k) if k in maybe_names else 10_000, v))
            else:
                attrs[k] = v
        # order keyword symbol inputs by argument position
        if sym_inputs and isinstance(sym_inputs[-1], tuple):
            positional = [s for s in sym_inputs if isinstance(s, Symbol)]
            keyword = sorted([s for s in sym_inputs if isinstance(s, tuple)],
                             key=lambda t: t[0])
            sym_inputs = positional + [s for _, s in keyword]
        if attr:
            merged = dict(attr)
            merged.update({k: str(v) for k, v in attrs.items()})
            attrs = merged
        attrs = {k: v for k, v in attrs.items()}
        return _create(op_name, sym_inputs, attrs, name=name)

    sym_func.__name__ = op_name
    sym_func.__doc__ = op.doc + "\n\nParameters\n----------\n" + op.schema.doc()
    return sym_func


def _init_symbol_module():
    import sys

    mod = sys.modules[__name__]
    for name in _reg.list_ops():
        if name in ("Group",):
            continue
        setattr(mod, name, _make_sym_func(name))
