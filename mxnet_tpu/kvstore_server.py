"""KVStore server bootstrap (reference: python/mxnet/kvstore_server.py).

The reference launches dedicated parameter-server processes
(`DMLC_ROLE=server`) running a command loop with a pickled optimizer.  On
TPU there is no parameter server: synchronization is XLA collectives inside
the compiled step, and every process is a worker.  This module keeps the
entry point so reference launch scripts don't crash: a 'server' role simply
idles until the workers finish (join barrier), which we implement as a
no-op return.
"""
from __future__ import annotations

import logging
import os

__all__ = ["KVStoreServer"]


class KVStoreServer:
    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.init_logging = False

    def run(self):
        logging.info("mxnet_tpu: parameter-server role is subsumed by XLA "
                     "collectives; server process exiting cleanly")


def _init_kvstore_server_module():
    role = os.environ.get("DMLC_ROLE", "")
    if role == "server":
        from . import kvstore

        server = KVStoreServer(kvstore.create("dist"))
        server.run()
