"""KVStore server bootstrap (reference: python/mxnet/kvstore_server.py).

DESCOPE (documented deviation): the reference launches dedicated
parameter-server processes (`DMLC_ROLE=server`) running a ps-lite command
loop that applies a pickled optimizer to pushed gradients
(`src/kvstore/kvstore_dist_server.h`).  On TPU the parameter server has no
role: gradient synchronization is XLA collectives (psum over ICI/DCN)
inside the compiled train step, every process is a worker, and the
optimizer runs worker-side on the already-reduced gradients — the
`dist_sync` semantics without the extra hop.  This module keeps the
reference's process contract so `tools/launch.py`-style cluster scripts
work unchanged: a process started with DMLC_ROLE=server logs the
explanation and exits cleanly at import (the reference similarly never
returns control to the user script in server processes).
"""
from __future__ import annotations

import logging
import os
import sys

__all__ = ["KVStoreServer"]


class KVStoreServer:
    """Compatibility shim for the reference server-process API."""

    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.init_logging = False

    def run(self):
        logging.info("mxnet_tpu: parameter-server role is subsumed by XLA "
                     "collectives; server process exiting cleanly")


def _init_kvstore_server_module():
    if os.environ.get("DMLC_ROLE", "") == "server":
        from . import kvstore

        KVStoreServer(kvstore.create("dist")).run()
        # the reference's server processes never run the user script body
        sys.exit(0)


_init_kvstore_server_module()
