"""Legacy model API: checkpoint helpers, kvstore policy, FeedForward.

Reference: `python/mxnet/model.py` (946 LoC) — `_create_kvstore`:40,
`save_checkpoint`:319, `load_checkpoint`:349, `FeedForward`:387.
FeedForward here is a thin estimator facade over Module (the reference keeps
a parallel DataParallelExecutorManager implementation; the capabilities are
identical).
"""
from __future__ import annotations

import logging

import numpy as np

from . import io as io_mod
from . import metric as metric_mod
from . import ndarray as nd
from . import symbol as sym_mod
from . import kvstore as kvs_mod
from .base import MXNetError
from .context import cpu

BASE_ESTIMATOR = object


def _create_kvstore(kvstore, num_device, arg_params):
    """KVStore policy (reference: model.py:40-77): no kvstore for 1 device
    unless dist; update_on_kvstore off for huge params."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs_mod.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs_mod.create(kvstore)
            if kvstore == "local":
                max_size = max(np.prod(param.shape) for param in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Save prefix-symbol.json + prefix-####.params (reference: model.py:319)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_checkpoint(prefix, epoch):
    """Load symbol + params (reference: model.py:349)."""
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward(BASE_ESTIMATOR):
    """Legacy estimator API (reference: model.py:387-946)."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform

        self.symbol = symbol
        if ctx is None:
            ctx = [cpu()]
        elif not isinstance(ctx, list):
            ctx = [ctx]
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.kwargs = kwargs.copy()
        self.optimizer = optimizer
        self.initializer = initializer if initializer is not None else Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self._module = None

    def _label_name(self):
        outs = self.symbol.list_outputs()
        label_names = [n for n in self.symbol.list_arguments()
                       if n.endswith("label")]
        return label_names[0] if label_names else "softmax_label"

    def _build_module(self, data):
        from .module import Module

        data_names = [d[0] for d in data.provide_data]
        label_names = [d[0] for d in data.provide_label] or [self._label_name()]
        self._module = Module(self.symbol, data_names=data_names,
                              label_names=label_names, context=self.ctx)
        return self._module

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        """Train (reference: model.py:727)."""
        data = self._init_iter(X, y, is_train=True)
        if eval_data is not None and not isinstance(eval_data, io_mod.DataIter):
            if isinstance(eval_data, tuple):
                eval_data = io_mod.NDArrayIter(eval_data[0], eval_data[1],
                                               self.numpy_batch_size)
            else:
                eval_data = self._init_iter(eval_data, None, is_train=False)
        mod = self._build_module(data)
        optimizer_params = dict(self.kwargs)
        if "learning_rate" not in optimizer_params and \
                isinstance(self.optimizer, str):
            optimizer_params.setdefault("learning_rate", 0.01)
        mod.fit(data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer, optimizer_params=optimizer_params,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback,
                initializer=self.initializer, arg_params=self.arg_params,
                aux_params=self.aux_params, allow_missing=True,
                begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
                monitor=monitor)
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        """Predict (reference: model.py:599)."""
        data = self._init_iter(X, None, is_train=False)
        if self._module is None or not self._module.binded:
            mod = self._build_module(data)
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label or None, for_training=False)
            mod.init_params(initializer=self.initializer,
                            arg_params=self.arg_params, aux_params=self.aux_params,
                            allow_missing=True)
        outputs = self._module.predict(data, num_batch=num_batch, reset=reset)
        if isinstance(outputs, list):
            return [o.asnumpy() for o in outputs]
        return outputs.asnumpy()

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        data = self._init_iter(X, None, is_train=False)
        if self._module is None or not self._module.binded:
            mod = self._build_module(data)
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label or None, for_training=False)
            mod.init_params(initializer=self.initializer,
                            arg_params=self.arg_params, aux_params=self.aux_params,
                            allow_missing=True)
        res = self._module.score(data, eval_metric, num_batch=num_batch,
                                 batch_end_callback=batch_end_callback,
                                 reset=reset)
        return res[0][1]

    def _init_iter(self, X, y, is_train):
        if isinstance(X, io_mod.DataIter):
            return X
        if isinstance(X, (np.ndarray, nd.NDArray)):
            if y is None:
                y = np.zeros(len(X))
            return io_mod.NDArrayIter(X, y, min(self.numpy_batch_size, len(X)),
                                      shuffle=is_train, last_batch_handle="roll_over")
        raise TypeError("X must be DataIter or numpy array")

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        assert epoch is not None
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params, self.aux_params)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        """Train a new model (reference: model.py:883)."""
        from .initializer import Uniform

        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer or Uniform(0.01), **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
