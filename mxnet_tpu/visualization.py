"""Network visualization (reference: python/mxnet/visualization.py)."""
from __future__ import annotations

import json

from .base import MXNetError
from . import symbol as sym_mod

__all__ = ["print_summary", "plot_network"]


def _summary_rows(symbol, shape):
    """Collect one record per compute node: (label, out_shape, nparams, preds).

    Pure data gathering — rendering is a separate concern (`_render_table`).
    """
    shape_dict = {}
    if shape is not None:
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape)
        shape_dict.update(zip(symbol.list_arguments(), arg_shapes))
        shape_dict.update(zip(symbol.list_auxiliary_states(), aux_shapes))
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        shape_dict.update(zip(internals.list_outputs(), out_shapes))

    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    head_ids = {h[0] for h in conf["heads"]}
    rows = []
    for nid, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        nparams = 0
        preds = []
        for src_id, *_ in node.get("inputs", []):
            src = nodes[src_id]
            if src["op"] != "null" or src_id in head_ids:
                preds.append(src["name"])
            elif not src.get("is_aux"):
                pshape = shape_dict.get(src["name"])
                if pshape and not src["name"].endswith(("data", "label")):
                    count = 1
                    for dim in pshape:
                        count *= dim
                    nparams += count
        out_shape = ""
        if shape is not None:
            out_shape = shape_dict.get(node["name"] + "_output", "")
        rows.append(("%s(%s)" % (node["name"], node["op"]),
                     out_shape, nparams, preds))
    return rows


def _render_table(rows, line_length, positions):
    """Format gathered records into the fixed-column summary table."""
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    widths = [positions[0]] + [b - a for a, b in zip(positions, positions[1:])]

    def fmt(cells):
        return "".join(str(c)[:w].ljust(w) for c, w in zip(cells, widths))

    lines = ["_" * line_length,
             fmt(["Layer (type)", "Output Shape", "Param #", "Previous Layer"]),
             "=" * line_length]
    for label, out_shape, nparams, preds in rows:
        lines.append(fmt([label, out_shape, nparams,
                          preds[0] if preds else ""]))
        lines.extend(fmt(["", "", "", p]) for p in preds[1:])
        lines.append("_" * line_length)
    total = sum(r[2] for r in rows)
    lines.append("Total params: %s" % total)
    lines.append("_" * line_length)
    return lines


def print_summary(symbol, shape=None, line_length=120, positions=(0.44, 0.64, 0.74, 1.0)):
    """Tabular per-layer summary with param counts.

    Capability parity with the reference's summary printer
    (python/mxnet/visualization.py:20) — same columns, separators, and
    total-params footer — built as gather-records-then-render rather than
    an incremental truncation printer.
    """
    if not isinstance(symbol, sym_mod.Symbol):
        raise TypeError("symbol must be Symbol")
    for line in _render_table(_summary_rows(symbol, shape),
                              line_length, list(positions)):
        print(line)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs={}, hide_weights=True):
    """Graphviz plot (reference: visualization.py:145); requires graphviz."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError("plot_network requires graphviz (not installed in "
                         "this environment)")
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    dot = Digraph(name=title)
    hidden = set()
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            if hide_weights and (name.endswith("weight") or name.endswith("bias")
                                 or name.endswith("gamma") or name.endswith("beta")
                                 or "moving" in name):
                hidden.add(i)
                continue
            dot.node(name=name, label=name, shape="oval")
        else:
            dot.node(name=name, label="%s\n%s" % (name, op), shape="box")
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for item in node["inputs"]:
            if item[0] in hidden:
                continue
            dot.edge(tail_name=nodes[item[0]]["name"], head_name=node["name"])
    return dot
