"""Network visualization (reference: python/mxnet/visualization.py)."""
from __future__ import annotations

import json

from .base import MXNetError
from . import symbol as sym_mod

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(0.44, 0.64, 0.74, 1.0)):
    """Tabular summary with param counts (reference: visualization.py:20)."""
    if not isinstance(symbol, sym_mod.Symbol):
        raise TypeError("symbol must be Symbol")
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        arg_names = symbol.list_arguments()
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape)
        shape_dict = dict(zip(arg_names, arg_shapes))
        shape_dict.update(dict(zip(symbol.list_auxiliary_states(), aux_shapes)))
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        shape_dict.update(dict(zip(internals.list_outputs(), out_shapes)))

    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)
    total_params = [0]

    def print_layer_summary(node, out_shape):
        op = node["op"]
        pre_node = []
        pre_filter = 0
        if op != "null":
            inputs = node["inputs"]
            for item in inputs:
                input_node = nodes[item[0]]
                input_name = input_node["name"]
                if input_node["op"] != "null" or item[0] in heads:
                    pre_node.append(input_name)
        cur_param = 0
        for inp in node.get("inputs", []):
            input_node = nodes[inp[0]]
            if input_node["op"] == "null" and not input_node.get("is_aux"):
                pshape = shape_dict.get(input_node["name"])
                if pshape and not input_node["name"].endswith(("data", "label")):
                    n = 1
                    for s in pshape:
                        n *= s
                    cur_param += n
        first_connection = pre_node[0] if pre_node else ""
        fields = ["%s(%s)" % (node["name"], op), out_shape, cur_param,
                  first_connection]
        print_row(fields, positions)
        for i in range(1, len(pre_node)):
            fields = ["", "", "", pre_node[i]]
            print_row(fields, positions)
        total_params[0] += cur_param

    heads = set(h[0] for h in conf["heads"])
    for node in nodes:
        if node["op"] == "null":
            continue
        out_shape = shape_dict.get(node["name"] + "_output", "") if show_shape else ""
        print_layer_summary(node, out_shape)
        print("_" * line_length)
    print("Total params: %s" % total_params[0])
    print("_" * line_length)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs={}, hide_weights=True):
    """Graphviz plot (reference: visualization.py:145); requires graphviz."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError("plot_network requires graphviz (not installed in "
                         "this environment)")
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    dot = Digraph(name=title)
    hidden = set()
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            if hide_weights and (name.endswith("weight") or name.endswith("bias")
                                 or name.endswith("gamma") or name.endswith("beta")
                                 or "moving" in name):
                hidden.add(i)
                continue
            dot.node(name=name, label=name, shape="oval")
        else:
            dot.node(name=name, label="%s\n%s" % (name, op), shape="box")
    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        for item in node["inputs"]:
            if item[0] in hidden:
                continue
            dot.edge(tail_name=nodes[item[0]]["name"], head_name=node["name"])
    return dot
