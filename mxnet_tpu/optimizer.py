"""Optimizers (reference: python/mxnet/optimizer.py, 755 LoC).

Each ``update(index, weight, grad, state)`` dispatches to the fused update
ops (`mxnet_tpu/ops/optimizer_ops.py` ↔ reference `src/operator/
optimizer_op.cc`) — one jitted XLA fusion per update, with state tensors
written back in place of the reference's engine-mutated NDArrays.
"""
from __future__ import annotations

import math

import numpy as np

from . import ndarray as nd
from .ndarray import NDArray, zeros
from .base import MXNetError

__all__ = ["Optimizer", "SGD", "NAG", "SGLD", "ccSGD", "DCASGD", "Adam",
           "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Test", "create",
           "get_updater", "register"]


class Optimizer:
    """Base optimizer (reference: optimizer.py:10-135)."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        Optimizer.opt_registry[klass.__name__.lower()] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        self._set_lr_wd_mult_from_sym(sym)

    def _set_lr_wd_mult_from_sym(self, sym):
        self.sym_lr_mult = {}
        self.sym_wd_mult = {}
        if sym is not None:
            attr = sym.attr_dict()
            for name in sym.list_arguments():
                if name in attr:
                    if "__lr_mult__" in attr[name]:
                        self.sym_lr_mult[name] = float(attr[name]["__lr_mult__"])
                    if "__wd_mult__" in attr[name]:
                        self.sym_wd_mult[name] = float(attr[name]["__wd_mult__"])

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi(self, indices, weights, grads, states):
        """Update many parameters in one step.  Subclasses with a fused
        whole-model kernel (SGD, Adam) override this: ONE jitted XLA call
        replaces the reference's per-parameter engine pushes — essential on
        TPU where per-op dispatch latency would dominate the step."""
        for i, w, g, s in zip(indices, weights, grads, states):
            self.update(i, w, g, s)

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = args_lr_mult.copy()

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # reference convention: no weight decay on bias/gamma/beta
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        name = self.idx2name.get(index, index if isinstance(index, str) else None)
        if name is not None and name in self.sym_lr_mult:
            lr *= self.sym_lr_mult[name]
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif name is not None and name in self.lr_mult:
            lr *= self.lr_mult[name]
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index if isinstance(index, str) else None)
        if name is not None and name in self.sym_wd_mult:
            wd *= self.sym_wd_mult[name]
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif name is not None and name in self.wd_mult:
            wd *= self.wd_mult[name]
        return wd

    def _common_kwargs(self):
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    # -- functional form, for the fused (donated, jitted) train step --------
    def fused_kernel(self):
        """Pure-functional form of this optimizer, traceable inside jax.jit.

        Returns ``(make_slots, apply)`` or None when unsupported (Module then
        falls back to the eager update path):

        * ``make_slots(w)``: jnp weight -> tuple of jnp slot arrays
        * ``apply(w, g, slots, lr, wd, rescale, clip, extra)``: all-jnp
          update; ``lr`` arrives already bias-corrected/scheduled
          (host-side, like the eager ``update()``); ``rescale``/``clip``
          and the ``extra`` vector (``fused_extra()`` — momentum/betas/
          epsilon) are runtime scalars so later mutation of
          ``self.momentum`` etc. is honored without recompiling
          (clip <= 0 means no clipping).  Only *structural* choices
          (whether momentum slots exist at all, centered RMSProp) are
          baked at build time.
        """
        return None

    def fused_extra(self):
        """Runtime hyper-vector consumed by ``apply``'s ``extra`` argument.

        Re-read from ``self`` every step, so mutating hyperparameters after
        the fused step compiled keeps fused and eager paths in agreement.
        """
        return np.zeros(0, np.float32)

    def fused_hyper(self, indices):
        """Host-side per-step hyperparams for the fused step: bumps update
        counts exactly as the eager path does (same integer index keys, so
        fused<->eager handoffs see one consistent count) and returns
        ``(lrs, wds, rescale, clip)`` numpy arrays/scalars, one lr/wd per
        entry in ``indices``."""
        for idx in indices:
            self._update_count(idx)
        lrs = np.array([self._get_lr(i) for i in indices], np.float32)
        wds = np.array([self._get_wd(i) for i in indices], np.float32)
        clip = np.float32(self.clip_gradient
                          if self.clip_gradient is not None else -1.0)
        return lrs, wds, np.float32(self.rescale_grad), clip

    def pack_state(self, arrays):
        """Assemble a ``create_state``-shaped value from a flat list of
        state arrays — the inverse of flattening into fused slots.  The
        default maps 0 -> None, 1 -> bare array, n -> tuple; optimizers
        whose create_state is a 1-tuple (RMSProp) override this."""
        if not arrays:
            return None
        if len(arrays) == 1:
            return arrays[0]
        return tuple(arrays)


register = Optimizer.register


@register
class SGD(Optimizer):
    """SGD with momentum, via fused sgd(_mom)_update (reference: :279)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self._fused_fn = None

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = self._common_kwargs()
        if state is not None:
            new_w, new_m = nd.sgd_mom_update(weight, grad, state, lr=lr, wd=wd,
                                             momentum=self.momentum, **kw)
            state._set_data(new_m.data)
        else:
            new_w = nd.sgd_update(weight, grad, lr=lr, wd=wd, **kw)
        weight._set_data(new_w.data)

    def _fused(self):
        import jax
        import jax.numpy as jnp

        if self._fused_fn is not None:
            return self._fused_fn
        momentum = self.momentum
        rescale = self.rescale_grad
        clip = self.clip_gradient

        def fused(ws, gs, ms, lrwd):
            new_ws, new_ms = [], []
            for i, (w, g, m) in enumerate(zip(ws, gs, ms)):
                lr = lrwd[0, i]
                wd = lrwd[1, i]
                g = g * rescale
                if clip is not None:
                    g = jnp.clip(g, -clip, clip)
                if momentum != 0.0:
                    m = momentum * m - lr * (g + wd * w)
                    w = w + m
                    new_ms.append(m)
                else:
                    w = w - lr * (g + wd * w)
                    new_ms.append(m)
                new_ws.append(w)
            return new_ws, new_ms

        # no donation: NDArray facade may hold other refs to the old buffers
        self._fused_fn = jax.jit(fused)
        return self._fused_fn

    def fused_kernel(self):
        import jax.numpy as jnp

        # slot *structure* is compile-time; the momentum value itself rides
        # in `extra` so post-compile mutation stays honored
        has_momentum = self.momentum != 0.0

        def make_slots(w):
            return (jnp.zeros_like(w),) if has_momentum else ()

        def apply(w, g, slots, lr, wd, rescale, clip, extra):
            g = g * rescale
            g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
            if has_momentum:
                momentum = extra[0]
                (m,) = slots
                m = momentum * m - lr * (g + wd * w)
                return w + m, (m,)
            return w - lr * (g + wd * w), ()

        return make_slots, apply

    def fused_extra(self):
        return np.array([self.momentum], np.float32)

    def update_multi(self, indices, weights, grads, states):
        for i in indices:
            self._update_count(i)
        # one (2, n) host array for all lr/wd scalars: a single transfer
        # instead of 2n tiny ones
        lrwd = np.stack([
            np.array([self._get_lr(i) for i in indices], np.float32),
            np.array([self._get_wd(i) for i in indices], np.float32)])
        ms = [s.data if s is not None else w.data
              for s, w in zip(states, weights)]
        new_ws, new_ms = self._fused()([w.data for w in weights],
                                       [g.data for g in grads], ms, lrwd)
        for w, nw in zip(weights, new_ws):
            w._set_data(nw)
        if self.momentum != 0.0:
            for s, nm in zip(states, new_ms):
                s._set_data(nm)


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference: :330)."""

    def fused_kernel(self):
        import jax.numpy as jnp

        has_momentum = self.momentum != 0.0

        def make_slots(w):
            return (jnp.zeros_like(w),) if has_momentum else ()

        def apply(w, g, slots, lr, wd, rescale, clip, extra):
            g = g * rescale
            g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
            g = g + wd * w
            if has_momentum:
                momentum = extra[0]
                (m,) = slots
                m = momentum * m + g
                return w - lr * (g + momentum * m), (m,)
            return w - lr * g, ()

        return make_slots, apply

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        if state is not None:
            mom = state
            mom *= self.momentum
            grad += wd * weight
            mom += grad
            grad += self.momentum * mom
            weight += -lr * grad
        else:
            weight += -lr * (grad + wd * weight)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (reference: :365)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        noise = nd.normal(loc=0.0, scale=math.sqrt(lr), shape=weight.shape,
                          ctx=weight.context)
        weight += -lr / 2 * (grad + wd * weight) + noise


@register
class ccSGD(SGD):
    """Alias of SGD in this framework (reference ccSGD was a C++ fast path)."""


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: :398)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, weight.context), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        mom, previous_weight = state
        delta = -lr * (grad + wd * weight +
                       self.lamda * grad * grad * (weight - previous_weight))
        if mom is not None:
            mom *= self.momentum
            mom += delta
            delta = mom
        previous_weight._set_data(weight.data)
        weight += delta


@register
class Adam(Optimizer):
    """Adam, via fused adam_update (reference: :451)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=weight.dtype),
                zeros(weight.shape, weight.context, dtype=weight.dtype))

    def fused_kernel(self):
        import jax.numpy as jnp

        def make_slots(w):
            return (jnp.zeros_like(w), jnp.zeros_like(w))

        def apply(w, g, slots, lr, wd, rescale, clip, extra):
            beta1, beta2, eps = extra[0], extra[1], extra[2]
            mean, var = slots
            g = g * rescale
            g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
            g = g + wd * w
            mean = beta1 * mean + (1 - beta1) * g
            var = beta2 * var + (1 - beta2) * jnp.square(g)
            return w - lr * mean / (jnp.sqrt(var) + eps), (mean, var)

        return make_slots, apply

    def fused_extra(self):
        return np.array([self.beta1, self.beta2, self.epsilon], np.float32)

    def fused_hyper(self, indices):
        lrs, wds, rescale, clip = super().fused_hyper(indices)
        # fold the bias correction into lr host-side, as eager update() does
        for i, idx in enumerate(indices):
            t = self._index_update_count[idx]
            lrs[i] *= math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        return lrs, wds, rescale, clip

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        mean, var = state
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        new_w, new_mean, new_var = nd.adam_update(
            weight, grad, mean, var, lr=lr, wd=wd, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon, **self._common_kwargs())
        weight._set_data(new_w.data)
        mean._set_data(new_mean.data)
        var._set_data(new_var.data)


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference: :513)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def fused_kernel(self):
        import jax.numpy as jnp

        def make_slots(w):
            return (jnp.zeros_like(w),)

        def apply(w, g, slots, lr, wd, rescale, clip, extra):
            eps = extra[0]
            (h,) = slots
            g = g * rescale
            g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
            h = h + g * g
            return w - lr * (g / jnp.sqrt(h + eps) + wd * w), (h,)

        return make_slots, apply

    def fused_extra(self):
        return np.array([self.float_stable_eps], np.float32)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        history = state
        history += grad * grad
        weight += -lr * (grad / nd.sqrt(history + self.float_stable_eps) + wd * weight)


@register
class RMSProp(Optimizer):
    """RMSProp; centered=True uses Alex Graves' variant (reference: :553)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def pack_state(self, arrays):
        # create_state is a tuple even in the single-slot (uncentered) case
        return tuple(arrays)

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, weight.context),
                    zeros(weight.shape, weight.context),
                    zeros(weight.shape, weight.context))
        return (zeros(weight.shape, weight.context),)

    def fused_kernel(self):
        import jax.numpy as jnp

        centered = self.centered  # structural: decides the slot count

        def make_slots(w):
            n = 3 if centered else 1
            return tuple(jnp.zeros_like(w) for _ in range(n))

        def apply(w, g, slots, lr, wd, rescale, clip, extra):
            rho, mom, eps, cw = extra[0], extra[1], extra[2], extra[3]
            g = g * rescale
            g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
            g = g + wd * w
            if centered:
                n, gbar, delta = slots
                n = rho * n + (1 - rho) * jnp.square(g)
                gbar = rho * gbar + (1 - rho) * g
                delta = mom * delta - lr * g / jnp.sqrt(n - jnp.square(gbar) + eps)
                w = w + delta
                new_slots = (n, gbar, delta)
            else:
                (n,) = slots
                n = rho * n + (1 - rho) * jnp.square(g)
                w = w - lr * g / jnp.sqrt(n + eps)
                new_slots = (n,)
            w = jnp.where(cw > 0, jnp.clip(w, -cw, cw), w)
            return w, new_slots

        return make_slots, apply

    def fused_extra(self):
        cw = self.clip_weights if self.clip_weights else -1.0
        return np.array([self.gamma1, self.gamma2, self.epsilon, cw],
                        np.float32)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = self._common_kwargs()
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if not self.centered:
            (n,) = state
            new_w, new_n = nd.rmsprop_update(
                weight, grad, n, lr=lr, wd=wd, gamma1=self.gamma1,
                epsilon=self.epsilon, **kw)
            n._set_data(new_n.data)
        else:
            n, g, delta = state
            new_w, new_n, new_g, new_delta = nd.rmspropalex_update(
                weight, grad, n, g, delta, lr=lr, wd=wd, gamma1=self.gamma1,
                gamma2=self.gamma2, epsilon=self.epsilon, **kw)
            n._set_data(new_n.data)
            g._set_data(new_g.data)
            delta._set_data(new_delta.data)
        weight._set_data(new_w.data)


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference: :608)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        acc_g, acc_delta = state
        acc_g._set_data((self.rho * acc_g + (1 - self.rho) * grad * grad).data)
        current_delta = (nd.sqrt(acc_delta + self.epsilon) /
                         nd.sqrt(acc_g + self.epsilon)) * grad
        acc_delta._set_data(
            (self.rho * acc_delta + (1 - self.rho) * current_delta * current_delta).data)
        weight += -current_delta - wd * weight


@register
class Ftrl(Optimizer):
    """FTRL (reference: :652)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        lr = self._get_lr(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        dn, n = state
        dn += grad - (nd.sqrt(n + grad * grad) - nd.sqrt(n)) * weight / lr
        n += grad * grad
        w_np = (nd.sign(dn) * self.lamda1 - dn) / \
            ((self.beta + nd.sqrt(n)) / lr + wd) * (nd.abs(dn) > self.lamda1)
        weight._set_data(w_np.data)


@register
class Test(Optimizer):
    """Test optimizer: w += rescale_grad * grad (reference: :700)."""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state._set_data(weight.data)


create = Optimizer.create_optimizer


class Updater:
    """Closure applying an optimizer to (index, grad, weight) pairs —
    worker-side update (reference: optimizer.py:720 get_updater)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def update_multi(self, indices, grads, weights):
        for index, weight in zip(indices, weights):
            if index not in self.states:
                self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update_multi(indices, weights, grads,
                                    [self.states[i] for i in indices])

    def set_states(self, states):
        import pickle

        loaded = pickle.loads(states)
        # fused-step payloads are keyed by param NAME with numpy-tuple
        # values; translate via the optimizer's idx2name so a checkpoint
        # saved on the fused path resumes on the eager one
        name2idx = {n: i for i, n in self.optimizer.idx2name.items()}
        converted = {}
        for key, state in loaded.items():
            idx = name2idx.get(key, key) if isinstance(key, str) else key
            if isinstance(idx, str):
                # an unmapped name key would silently shadow-miss in
                # __call__ (which looks up integer indices) and restart the
                # state from zeros — losing momentum/moments on resume
                import logging

                detail = ("optimizer.idx2name is empty — was the optimizer "
                          "passed to init_optimizer as an instance?"
                          if not name2idx else
                          "known names: %s" % sorted(name2idx))
                logging.warning(
                    "optimizer state key %r has no index mapping (%s); its "
                    "saved state will not be applied", key, detail)
            if isinstance(state, tuple) and all(
                    isinstance(s, np.ndarray) for s in state):
                import jax.numpy as jnp

                state = self.optimizer.pack_state(
                    [NDArray(jnp.asarray(s)) for s in state])
            converted[idx] = state
        self.states = converted

    def get_states(self):
        import pickle

        return pickle.dumps(self.states)


def get_updater(optimizer):
    return Updater(optimizer)
