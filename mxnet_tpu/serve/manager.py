"""Per-slot page tables + the copy-on-write append rule.

The manager is the host brain of paged serving: it owns the
:class:`~mxnet_tpu.serve.allocator.PageAllocator`, the
:class:`~mxnet_tpu.serve.prefix_cache.PrefixCache` and one page-table row
per serving slot, and turns every upcoming device write into a plan the
decode layer executes:

* :meth:`admit` — admission gate: match the prompt against the prefix
  cache, reserve the request's whole worst-case page budget (tail pages +
  generation cap + speculation window + one fork), and map the matched
  shared pages.  Returns ``None`` when the pool cannot cover it — the
  serving loop keeps the request queued (backpressure) and retries after
  retirements free pages; LRU prefix-cache pages are evicted first.
* :meth:`ensure` — called before every append (chunk prefill, decode
  step, speculative verify) with the position range about to be written:
  allocates pages for unmapped table entries and **forks** any mapped
  page whose refcount exceeds 1 (copy-on-write — the first divergent
  write of a slot that shares a prefix).  Returns the (src, dst) page
  copies the caller must run on device BEFORE the step.
* :meth:`free_slot` — retirement: decref every mapped page (pages whose
  only other holder is the prefix cache survive for future prompts),
  release the leftover reservation.  Called the moment a request
  finishes — EOS mid-speculation-window included — so the pages are
  available to the very next admission attempt.

Tables are plain numpy; the decode layer ships them to the device as
DATA every step (a few hundred int32s), which is what keeps one traced
program serving every page mapping — the zero-retrace invariant.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .allocator import PageAllocator
from .prefix_cache import PrefixCache

__all__ = ["PagedKVManager"]


def _pages_for(tokens, page_tokens):
    """Pages needed to hold ``tokens`` tokens."""
    return -(-int(tokens) // int(page_tokens))


class PagedKVManager:
    """Host-side paged-KV bookkeeping for ``slots`` serving slots of
    ``capacity`` tokens each (``capacity % page_tokens == 0``; the table
    ring-mods over ``capacity // page_tokens`` entries, so generation past
    capacity recycles the slot's own oldest page in place — the paged
    counterpart of the dense ring's wrap)."""

    def __init__(self, slots, capacity, page_tokens, pool_pages=0,
                 prefix_cache=True):
        self.page_tokens = int(page_tokens)
        if capacity % self.page_tokens:
            raise MXNetError(
                "paged capacity %d is not a multiple of page_tokens %d"
                % (capacity, self.page_tokens))
        self.capacity = int(capacity)
        self.slots = int(slots)
        self.pages_per_slot = self.capacity // self.page_tokens
        self.pool_pages = self.pool_sizing(slots, capacity, page_tokens,
                                           pool_pages)
        self.allocator = PageAllocator(self.pool_pages)
        self.prefix_cache = PrefixCache(self.page_tokens, self.allocator) \
            if prefix_cache else None
        # 0 = unmapped (the scratch page)
        self.tables = np.zeros((self.slots, self.pages_per_slot), np.int32)
        # bumped on every table mutation: the decode layer keys its
        # device-side copy of the tables on it, so steady-state decode
        # ticks (no page allocated, no fork) re-ship NOTHING
        self.version = 0
        self._reserve = np.zeros(self.slots, np.int64)
        # True where the slot allocated (or forked) the page itself: the
        # slot's appends land strictly PAST any published/matched
        # coverage of such a page, so in-place writes are safe even while
        # the prefix cache (or a matching slot) also references it —
        # only non-owned pages and wrap recycles fork
        self._own = np.zeros((self.slots, self.pages_per_slot), bool)

    @staticmethod
    def pool_sizing(slots, capacity, page_tokens, pool_pages=0):
        """Resolved pool page count for a serving batch: the explicit
        ``pool_pages`` when given, else the capacity-complete default
        (every slot can fill its table, plus the scratch page).  ONE
        rule shared with ``DecodePredictor.serving_avals`` so the
        AOT-prepared program signatures can never drift from the pools
        ``serve_open`` actually allocates."""
        if not pool_pages:
            return int(slots) * (int(capacity) // int(page_tokens)) + 1
        return int(pool_pages)

    # ------------------------------------------------------------------
    def _alloc(self, slot):
        """One page for ``slot``, spending its reservation first, then
        unreserved headroom, then evicting prefix-cache LRU pages."""
        if self._reserve[slot] > 0:
            self._reserve[slot] -= 1
            return self.allocator.alloc(from_reserve=True)
        if self.allocator.available() < 1 and self.prefix_cache is not None:
            self.prefix_cache.evict(1)
        return self.allocator.alloc()

    # admission is two-phase so the serving loop can gate BEFORE touching
    # any slot state:
    def gate(self, prompt, prompt_len, max_new, spec_k=0,
             budget_wrap_forks=True):
        """Reserve the worst-case page budget for a request; returns
        ``(matched_len, pages, reserve_n)`` or ``None`` on backpressure.
        ``pages`` are prefix-cache pages covering [0, matched_len),
        already INCREFED (pinned — the eviction a tight gate triggers
        must not free the very pages this request matched); pass them to
        :meth:`map_slot`, which takes ownership of the pin.  A failed
        gate drops the pins itself.

        ``budget_wrap_forks``: when ``max_new`` is a real cap (the
        serving loop), a generation that will wrap reserves one fork per
        matched shared page up front, so the recycle-time fork can never
        raise mid-decode.  Standalone prefill passes False — its
        generation length is unknown (``max_new`` = capacity, which
        would predict a wrap always) and the rare tight-pool wrap fork
        falls back to :meth:`ensure`'s eviction path instead.
        """
        prompt_len = int(prompt_len)
        matched, pages = (0, [])
        if self.prefix_cache is not None:
            matched, pages = self.prefix_cache.match(
                np.asarray(prompt).reshape(-1)[:prompt_len])
        for page in pages:
            self.allocator.incref(page)
        # pages still to allocate for the prompt itself...
        need_now = _pages_for(prompt_len, self.page_tokens) - len(pages)
        # ... plus one fork if the first tail write lands mid-page in a
        # shared page, plus the decode/speculation growth to capacity
        fork = 1 if matched % self.page_tokens else 0
        total = prompt_len + int(max_new) + int(spec_k) + 1
        if budget_wrap_forks and total > self.capacity and pages:
            fork += len(pages)
        growth = _pages_for(min(total, self.capacity), self.page_tokens) \
            - _pages_for(prompt_len, self.page_tokens)
        need = need_now + fork + growth
        if self.allocator.available() < need and self.prefix_cache is not None:
            self.prefix_cache.evict(need - self.allocator.available())
        if not self.allocator.reserve(need):
            for page in pages:
                self.allocator.decref(page)
            return None
        return matched, pages, need

    def map_slot(self, slot, pages, reserve_n):
        """Bind a gated request to ``slot``: map the matched prefix pages
        (the gate's pin becomes the slot's reference — shared until
        forked) and record the reservation."""
        row = self.tables[slot]
        assert not row.any(), "mapping into a non-empty slot %d" % slot
        for i, page in enumerate(pages):
            row[i] = page
            self._own[slot, i] = False
        self._reserve[slot] = int(reserve_n)
        self.version += 1

    # ------------------------------------------------------------------
    def gate_pages(self, need):
        """Reserve ``need`` pages for a restore (swap-in / migrated
        prefill) — the SAME admission gate a fresh prompt passes, minus
        the prefix-cache match (restored pages arrive with their
        content).  Evicts LRU prefix-cache pages first; False on
        backpressure (nothing changed)."""
        need = int(need)
        if self.allocator.available() < need and self.prefix_cache is not None:
            self.prefix_cache.evict(need - self.allocator.available())
        return self.allocator.reserve(need)

    def restore_slot(self, slot, valid, reserve_n):
        """Bind a restored request to ``slot``: allocate one fresh page
        per True entry of ``valid`` (a (pages_per_slot,) mask of the
        saved table row — ring positions matter for wrapped decodes),
        spending the :meth:`gate_pages` reservation.  All pages are
        slot-OWNED (refcount 1, private copies), so later appends never
        fork.  Returns the new table row (0 = unmapped)."""
        row = self.tables[slot]
        assert not row.any(), "restoring into a non-empty slot %d" % slot
        self._reserve[slot] = int(reserve_n)
        for i in np.flatnonzero(np.asarray(valid).reshape(-1)):
            self._reserve[slot] -= 1
            row[i] = self.allocator.alloc(from_reserve=True)
            self._own[slot, i] = True
        self.version += 1
        return row.copy()

    def slot_page_count(self, slot):
        """Mapped pages of ``slot`` (swap accounting)."""
        return int(np.count_nonzero(self.tables[slot]))

    def ensure(self, slot, lo, hi):
        """Make positions [lo, hi) of ``slot`` writable.

        Allocates unmapped table entries; copy-on-write forks a mapped
        page when the write would collide with another holder's view: a
        shared prefix page about to receive the slot's first divergent
        write (not owned), or a wrap recycle of a page other slots still
        read.  A slot's OWN page appends in place even while shared — its
        writes land past every published coverage — and a wrap recycle
        whose only other holder is the prefix cache releases the (now
        dead) cache entries instead of forking.  Returns the list of
        ``(src_page, dst_page)`` copies the caller must execute on device
        before the append runs.
        """
        copies = []
        if hi <= lo:
            return copies
        row = self.tables[slot]
        m = self.pages_per_slot
        v0 = self.version
        for ti in range(int(lo) // self.page_tokens,
                        (int(hi) - 1) // self.page_tokens + 1):
            idx = ti % m
            page = int(row[idx])
            wrapped = ti >= m
            if page == 0:
                row[idx] = self._alloc(slot)
                self._own[slot, idx] = True
                self.version = v0 + 1
                continue
            if wrapped and self.prefix_cache is not None \
                    and self.allocator.shared(page):
                # wrap recycle: this slot overwrites the page in place,
                # so its cached prompt content is dead — drop the
                # cache's refs rather than fork for a corpse
                self.prefix_cache.release_page(page)
            if not self.allocator.shared(page):
                self._own[slot, idx] = True
                continue
            if self._own[slot, idx] and not wrapped:
                continue        # in-place append past published coverage
            fresh = self._alloc(slot)
            copies.append((page, fresh))
            self.allocator.decref(page)
            row[idx] = fresh
            self._own[slot, idx] = True
            self.allocator.forks += 1
            self.version = v0 + 1
        if copies:
            from .. import obs as _obs

            _obs.registry.counter(
                "mx_cow_forks",
                "copy-on-write page forks planned").inc(len(copies))
            _obs.instant("cow_fork", cat="serve",
                         args={"slot": int(slot), "copies": len(copies)})
        return copies

    def publish(self, slot, prompt, prompt_len):
        """Insert a finished prefill's prompt pages into the prefix
        cache (no-op when the cache is disabled)."""
        if self.prefix_cache is None:
            return
        n = _pages_for(int(prompt_len), self.page_tokens)
        row = self.tables[slot]
        pages = [int(row[i]) for i in range(n)]
        if any(p == 0 for p in pages):
            return      # never published a hole (defensive)
        self.prefix_cache.insert(np.asarray(prompt).reshape(-1),
                                 prompt_len, pages)

    def free_slot(self, slot):
        """Retire ``slot`` NOW: drop its page refs (prefix-cache-held
        pages survive), zero its table row, release its reservation."""
        row = self.tables[slot]
        if row.any():
            self.version += 1
        for i in range(self.pages_per_slot):
            if row[i]:
                self.allocator.decref(int(row[i]))
                row[i] = 0
            self._own[slot, i] = False
        if self._reserve[slot]:
            self.allocator.unreserve(int(self._reserve[slot]))
            self._reserve[slot] = 0

    # ------------------------------------------------------------------
    def stats(self):
        a = self.allocator
        out = {"pool_pages": self.pool_pages,
               "used_pages": a.used_pages,
               "peak_used_pages": a.peak_used,
               "free_pages": a.free_pages,
               "cow_forks": a.forks,
               "kv_hbm_utilization": a.peak_used / max(self.pool_pages - 1,
                                                       1)}
        if self.prefix_cache is not None:
            c = self.prefix_cache
            out.update({"prefix_cache_hit_rate": c.hit_rate,
                        "prefix_cache_hits": c.hits,
                        "prefix_cache_lookups": c.lookups,
                        "prefix_cache_pages": c.pages_held})
        return out
