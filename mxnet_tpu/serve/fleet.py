"""Disaggregated serving fleet — cache-aware routing, prefill/decode
split, and preemption-aware readmission over N per-host ``DecodeServer``s.

One host's serving loop is done (paged + speculative + continuous-batched
+ SLO-instrumented); this module is the millions-of-users rung: a
front-end :class:`Router` in the KVStore tradition (the reference's
``ps-lite`` Postoffice role — ``kvstore_server.py`` is the in-repo
heritage: a front-end process mediating N workers), three policies deep:

* **Cache-aware routing.**  Every host exposes a routing view
  (``DecodeServer.serve_summary`` — served remotely inside
  ``/metrics.json``, read directly in-process): free-page and
  queue-depth load signals plus the prefix-cache **chain summary**
  (content-free token-chain hashes,
  :meth:`~mxnet_tpu.serve.prefix_cache.PrefixCache.summary`).  The
  router replays the same hashes over an incoming prompt
  (:func:`match_chains`) and routes to the host with the LONGEST cached
  chain, tie-broken by load — shared-prefix traffic lands where its
  pages already live and prefills only the tail.  ``round_robin`` is
  the A/B baseline policy (``benchmarks/bench_fleet.py`` measures the
  delta on a bursty multi-tenant trace).
* **Prefill/decode disaggregation** (DistServe; Zhong et al., OSDI
  2024).  Prompts too cold to ride a cache match (below
  ``MXNET_FLEET_PREFILL_THRESHOLD``) go to a dedicated
  :class:`PrefillWorker`, which runs the SAME chunked-prefill program
  into its own pool and ships the committed pages — quantized data +
  per-(token, head) scales + chain keys — as a
  :class:`~mxnet_tpu.serve.swap.SwappedRequest` record.  The target
  decode host admits it through the normal
  :meth:`~mxnet_tpu.serve.manager.PagedKVManager.gate_pages`
  reservation gate and installs the pages with one traced scatter
  (page ids are DATA — zero retraces on either end), then publishes the
  chain keys so later prompts match the migrated prefix.
* **Preemption/swap.**  When a host's pool wedges
  (``MXNET_FLEET_SWAP`` + ``MXNET_FLEET_DECODE_BOUND``), the victim's
  record lands back at the router (``_preempt_cb``) and readmits on the
  least-loaded ALIVE host — swap and migration are one mechanism, so a
  fleet drains around a wedged pool instead of stalling admission
  fleet-wide.

Dead hosts (``FleetHost.alive = False`` — set by an operator or a
failed health poll) are skipped by routing and ticking; see
docs/serving_fleet.md for the failure matrix.  Everything
here is host-side numpy + the serve/swap records; device work happens
inside the per-host serving loops.
"""
from __future__ import annotations

import time
from collections import deque

import numpy as np

from ..base import MXNetError
from .. import obs as _obs
from .prefix_cache import chain_hash
from .swap import SwapStore

__all__ = ["FleetHost", "PrefillWorker", "Router", "http_health",
           "match_chains"]


def http_health(url, timeout=1.0):
    """Poll a remote host's ``/healthz`` endpoint (the
    ``obs.MetricsServer`` liveness probe); False on any error or
    non-200 — a dark host and a dead host read the same to the router."""
    import urllib.request

    if not url.rstrip("/").endswith("/healthz"):
        url = url.rstrip("/") + "/healthz"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status == 200
    except Exception:
        return False


def match_chains(prompt, chains):
    """Estimated cached-chain coverage of ``prompt`` on a host, from its
    content-free chain summary (:meth:`PrefixCache.summary`): walk full
    pages by prefix hash, then the longest exactly-matching partial
    entry.  Page-granular plus exact partials — the host's token-level
    radix matching can only do better, so the estimate is a safe lower
    bound.  Capped at ``len(prompt) - 1`` like the cache itself."""
    toks = np.asarray(prompt, np.int64).reshape(-1)
    if not chains or toks.size == 0:
        return 0
    pt = int(chains["page_tokens"])
    cap = toks.size - 1
    full = set(chains.get("full") or ())
    n = 0
    while (n + 1) * pt <= toks.size \
            and chain_hash(toks[:(n + 1) * pt]) in full:
        n += 1
    matched = n * pt
    rest = toks[matched:]
    ph = chain_hash(toks[:matched])
    best = 0
    for part in chains.get("partial") or ():
        ln = int(part["len"])
        if part["prefix"] == ph and best < ln <= rest.size \
                and chain_hash(rest[:ln]) == part["hash"]:
            best = ln
    return min(matched + best, cap)


class FleetHost:
    """One decode host: a named paged :class:`~mxnet_tpu.decode.
    DecodeServer` plus liveness.  ``summary()`` is the router's poll —
    in-process it reads the server directly; a remote router reads the
    identical payload from the host's ``/metrics.json``
    (``mx_serve_summary``)."""

    def __init__(self, name, server, health=None, health_grace=0):
        self.name = str(name)
        self.server = server
        self.alive = True
        # liveness probe: a callable returning bool, or a base URL whose
        # /healthz the router polls (http_health).  None = no probe —
        # this host's `alive` only flips by operator hand, the pre-HA
        # behavior.  health_grace = consecutive failed polls tolerated
        # BEYOND the first before flipping dark (0 = flip immediately;
        # production URL probes should set >= 1 so one timed-out scrape
        # of a loaded-but-healthy host doesn't requeue its whole batch)
        self.health = health
        self.health_grace = int(health_grace)
        self._health_fails = 0
        server._bind_host_metrics(self.name)

    def healthz(self):
        """One health poll: True/False from the probe, None when this
        host has no probe configured."""
        h = self.health
        if h is None:
            return None
        if callable(h):
            try:
                return bool(h())
            except Exception:
                return False
        return http_health(h)

    def summary(self):
        return self.server.serve_summary()

    def load(self, summary=None):
        """Queued + in-flight requests — the routing tie-breaker."""
        s = summary or self.summary()
        return int(s["active"]) + int(s["queue_depth"])


class PrefillWorker:
    """A dedicated prefill host (DistServe's prefill instance): runs
    chunked prefill into its OWN page pool and emits the committed
    prompt state as a migratable record.  The worker keeps a prefix
    cache too, so a shared-prefix burst that routes cold pays the
    prefix once per WORKER, not once per request."""

    def __init__(self, predictor, name="prefill0"):
        if not getattr(predictor, "_paged", False):
            raise MXNetError("PrefillWorker needs a paged DecodePredictor")
        self._pred = predictor
        self.name = str(name)
        self._state = None
        self.prefills = 0

    def reset(self):
        """Fresh pool + prefix cache (compiled programs survive)."""
        self._state = None

    def prefill(self, prompt, cap, priority=0, submit_ts=None, key=None):
        """Run one prompt's chunked prefill; returns the ``migrate``
        record (pages + scales + chain keys + first token) ready for
        :meth:`DecodeServer.inject` on any decode host."""
        import jax

        from ..decode import DecodeState
        from .swap import SwappedRequest

        pred = self._pred
        if self._state is None:
            self._state = pred.paged_batch_state(1)
        mgr = pred._manager
        prompt = np.asarray(prompt).reshape(-1).astype(np.int64)
        gate = mgr.gate(prompt, prompt.size, 0, budget_wrap_forks=False)
        if gate is None and mgr.prefix_cache is not None:
            mgr.prefix_cache.evict(mgr.pool_pages)
            gate = mgr.gate(prompt, prompt.size, 0,
                            budget_wrap_forks=False)
        if gate is None:
            raise MXNetError(
                "prefill worker pool (%d pages) cannot hold a %d-token "
                "prompt — raise its pool_pages" % (mgr.pool_pages,
                                                   prompt.size))
        matched, pages, reserve_n = gate
        mgr.map_slot(0, pages, reserve_n)
        caches, tok, _ = pred._chunked_fill(
            self._state.caches, 0, prompt, matched,
            key if key is not None else jax.random.PRNGKey(0))
        self._state = DecodeState(caches, self._state.lens,
                                  self._state.tok)
        mgr.publish(0, prompt, prompt.size)
        row = mgr.tables[0].copy()
        first = int(np.asarray(tok)[0, 0])
        data = pred.extract_pages(self._state.caches, row)
        record = SwappedRequest(
            prompt, [first], list(prompt) + [first], cap, priority,
            lens=prompt.size, tok=first, row_valid=row != 0, data=data,
            kind="migrate", publish=True, submit_ts=submit_ts,
            first_ts=time.time(), kv_heads=pred._grouped_kv_heads)
        mgr.free_slot(0)
        self.prefills += 1
        return record


class Router:
    """Front-end over N :class:`FleetHost`\\ s (+ optional
    :class:`PrefillWorker`\\ s).

    ``submit`` queues; ``tick`` routes pending requests and advances
    every live host by one serving iteration; ``drain`` loops to
    completion and returns ``{router_rid: np.int32 tokens}``.  Policies:
    ``cache_aware`` (longest chain match, load tie-break, dead-host
    skip; disaggregates cold prompts through the prefill workers) and
    ``round_robin`` (the monolithic baseline — next live host, no
    disaggregation).  Preempted records re-enter here and readmit on
    the least-loaded live host (restore is host-agnostic — pages are
    raw pool bytes).
    """

    def __init__(self, hosts, prefill_workers=(), policy="cache_aware",
                 threshold=None, health_interval=None):
        from .. import config as _config

        if policy not in ("cache_aware", "round_robin"):
            raise MXNetError("unknown routing policy %r" % (policy,))
        self.hosts = list(hosts)
        if not self.hosts:
            raise MXNetError("Router needs at least one host")
        # tick-time health polling cadence (seconds): in-process callable
        # probes are free and poll every tick; URL probes block up to
        # their HTTP timeout, so a fleet with any URL-probed host rate-
        # limits to once a second by default — a dark host must not
        # throttle every surviving host's serving ticks behind a
        # connect timeout
        if health_interval is None:
            health_interval = 1.0 if any(
                isinstance(h.health, str) for h in self.hosts) else 0.0
        self._health_interval = float(health_interval)
        self._last_health = 0.0
        self.workers = list(prefill_workers)
        self.policy = policy
        self._threshold = float(
            _config.get("MXNET_FLEET_PREFILL_THRESHOLD")
            if threshold is None else threshold)
        self._queue = deque()       # unrouted submissions
        self._restores = deque()    # preempted records awaiting rehoming
        self.swap_store = SwapStore()   # host-RAM bill of parked records
        self._next_rid = 0
        self._rr = 0                # round-robin cursor
        self._wrr = 0               # worker cursor
        self._affinity = {}         # first-page chain hash -> host name
        self._map = {}              # (host_name, host_rid) -> router rid
        self._inflight = {}         # (host_name, host_rid) -> submission
        # entry, kept until completion so a host that goes dark can have
        # its in-flight requests requeued (at-least-once semantics)
        self.results = {}
        self.decisions = []         # (rid, host, matched_est, path)
        self.host_flips = []        # (host, alive) health-driven flips
        self._m_flips = _obs.registry.counter(
            "mx_fleet_host_flips", "health-driven alive flips",
            labels=("host", "to"))
        self._m_routed = _obs.registry.counter(
            "mx_fleet_routed", "requests routed to a decode host",
            labels=("host",))
        self._m_matched = _obs.registry.counter(
            "mx_fleet_router_matched_tokens",
            "prompt tokens the routing-time chain match covered")
        self._m_lookup = _obs.registry.counter(
            "mx_fleet_router_lookup_tokens",
            "prompt tokens scored by the router")
        self._base_matched = self._m_matched.get()
        self._base_lookup = self._m_lookup.get()
        for host in self.hosts:
            host.server._preempt_cb = \
                lambda record, h=host: self._on_preempt(h, record)

    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, priority=0):
        """Queue a prompt with the fleet; returns the router-level rid."""
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append({"rid": rid,
                            "prompt": np.asarray(prompt).reshape(-1),
                            "cap": max_new_tokens, "prio": int(priority),
                            "submit": time.time()})
        return rid

    def _alive(self):
        hosts = [h for h in self.hosts if h.alive]
        if not hosts:
            raise MXNetError("no live decode hosts")
        return hosts

    def _on_preempt(self, host, record):
        self.swap_store.put(record, key=(host.name, record.rid))
        self._restores.append((host.name, record))

    # ------------------------------------------------------------------
    def _score(self, prompt, summaries):
        """(host, summary, matched-token estimate) per live host."""
        out = []
        for host, s in summaries:
            out.append((host, s, match_chains(prompt, s["chains"])))
        return out

    def route(self, entry):
        """Route ONE submission: pick the host (and the prefill path)
        under the active policy and dispatch it.  Returns the chosen
        :class:`FleetHost`."""
        alive = self._alive()
        prompt = entry["prompt"]
        if self.policy == "round_robin":
            host = alive[self._rr % len(alive)]
            self._rr += 1
            matched, path = 0, "direct"
        else:
            summaries = [(h, h.summary()) for h in alive]
            scored = self._score(prompt, summaries)
            best = max(s[2] for s in scored)
            self._m_lookup.inc(max(prompt.size - 1, 0))
            self._m_matched.inc(best)
            matched = best
            if best > 0:
                # longest chain wins; load breaks ties
                host = max(scored,
                           key=lambda s: (s[2], -s[0].load(s[1])))[0]
            else:
                # nothing cached anywhere yet: STICKY affinity by the
                # prompt's first-page chain hash — the first sighting of
                # a chain binds it to the least-loaded live host, and
                # every later cold request of the same chain follows, so
                # a cold burst of one tenant co-locates (its second
                # request finds the first one's pages) while distinct
                # tenants spread by load instead of hash luck
                pt = int(getattr(alive[0].server._pred, "_page_tokens",
                                 0) or 16)
                head = chain_hash(np.asarray(prompt, np.int64)[:pt])
                bound = self._affinity.get(head)
                host = next((h for h in alive if h.name == bound), None)
                if host is None:
                    host = min(scored,
                               key=lambda s: s[0].load(s[1]))[0]
                    self._affinity[head] = host.name
            path = "prefill_worker" if self.workers \
                and best < self._threshold * prompt.size else "direct"
        if path == "prefill_worker":
            worker = self.workers[self._wrr % len(self.workers)]
            self._wrr += 1
            record = worker.prefill(prompt, entry["cap"]
                                    if entry["cap"] is not None
                                    else host.server._max_new,
                                    priority=entry["prio"],
                                    submit_ts=entry["submit"])
            hrid = host.server.inject(record)
        else:
            hrid = host.server.submit(prompt, entry["cap"],
                                      priority=entry["prio"])
            host.server._req[hrid]["submit"] = entry["submit"]
        self._map[(host.name, hrid)] = entry["rid"]
        self._inflight[(host.name, hrid)] = entry
        self._m_routed.labels(host=host.name).inc()
        self.decisions.append((entry["rid"], host.name, int(matched),
                               path))
        _obs.instant("route", cat="fleet",
                     args={"rid": entry["rid"], "host": host.name,
                           "matched": int(matched), "path": path})
        return host

    # ------------------------------------------------------------------
    # health-driven HA: /healthz polling flips `alive` and requeues a
    # dark host's in-flight requests on the survivors
    # ------------------------------------------------------------------
    def poll_health(self):
        """Poll every host that has a health probe and flip ``alive``
        accordingly.  A host going DARK has its in-flight requests
        (queued-on-host and mid-decode alike) requeued at the router —
        they re-route to live hosts and restart from the prompt
        (at-least-once: generated-so-far tokens on the dark host are
        lost, tokens are only ever delivered once because the dead
        host's result mapping is dropped).  A host whose probe recovers
        flips back alive and rejoins routing.  Returns the
        ``[(host, alive, requeued)]`` flips this poll made."""
        flips = []
        for host in self.hosts:
            ok = host.healthz()
            if ok is None:
                continue
            if ok:
                host._health_fails = 0
            else:
                host._health_fails += 1
            if host.alive and not ok \
                    and host._health_fails > host.health_grace:
                host.alive = False
                n = self._requeue_host(host.name)
                flips.append((host.name, False, n))
                self.host_flips.append((host.name, False))
                self._m_flips.labels(host=host.name, to="down").inc()
                _obs.instant("host_down", cat="fleet",
                             args={"host": host.name, "requeued": n})
            elif not host.alive and ok:
                host.alive = True
                flips.append((host.name, True, 0))
                self.host_flips.append((host.name, True))
                self._m_flips.labels(host=host.name, to="up").inc()
                _obs.instant("host_up", cat="fleet",
                             args={"host": host.name})
        return flips

    def _requeue_host(self, name):
        """Requeue every in-flight request of a dark host (original
        submission entries, original submit timestamps — TTFT stays
        honest) and drop its result mappings plus any cold-affinity
        bindings, so chains rebind to a live host."""
        n = 0
        requeued = set()
        for key in [k for k in self._map if k[0] == name]:
            self._map.pop(key)
            entry = self._inflight.pop(key, None)
            if entry is not None:
                self._queue.append(entry)
                requeued.add(key[1])
                n += 1
        # a record the dark host preempted but that has not rehomed yet
        # would otherwise be injected as an ORPHAN (its mapping is gone,
        # its results unconsumable) while the requeued original also
        # runs — consume the restore copy and its swap-store bill here
        if requeued:
            kept = deque()
            while self._restores:
                src, record = self._restores.popleft()
                if src == name and record.rid in requeued:
                    self.swap_store.pop((src, record.rid))
                    continue
                kept.append((src, record))
            self._restores = kept
        for head in [h for h, bound in self._affinity.items()
                     if bound == name]:
            del self._affinity[head]
        return n

    # ------------------------------------------------------------------
    def tick(self):
        """One fleet iteration: poll health (flipping ``alive`` and
        requeuing a dark host's work; URL-probed fleets rate-limit the
        poll — see ``health_interval``), route every pending submission
        and preempted record, then advance each live host by one
        serving iteration and collect finished results."""
        now = time.time()
        if now - self._last_health >= self._health_interval:
            self._last_health = now
            self.poll_health()
        if (self._queue or self._restores) \
                and not any(h.alive for h in self.hosts):
            # fail loudly BEFORE popping anything: the queued entries
            # and preempted records stay held, so a caller that catches
            # this can wait for a health recovery and resume with
            # nothing lost (previously the popped entry was dropped)
            raise MXNetError("no live decode hosts")
        while self._queue:
            self.route(self._queue.popleft())
        while self._restores:
            src_name, record = self._restores.popleft()
            # readmit on the least-loaded live host — no prefill, no
            # cache match needed: pages restore as raw pool bytes
            host = min(self._alive(), key=lambda h: h.load())
            rr = self._map.pop((src_name, record.rid), None)
            entry = self._inflight.pop((src_name, record.rid), None)
            self.swap_store.pop((src_name, record.rid))
            hrid = host.server.inject(record)
            if rr is not None:
                self._map[(host.name, hrid)] = rr
            if entry is not None:
                self._inflight[(host.name, hrid)] = entry
            _obs.instant("rehome", cat="fleet",
                         args={"from": src_name, "host": host.name,
                               "pages": record.n_pages})
        for host in self.hosts:
            if host.alive and host.server.has_work:
                host.server.serve_tick()
                done = host.server.serve_results(clear=True)
                for hrid, toks in done.items():
                    rr = self._map.pop((host.name, hrid), None)
                    self._inflight.pop((host.name, hrid), None)
                    if rr is not None:
                        self.results[rr] = toks

    @property
    def has_work(self):
        return bool(self._queue or self._restores
                    or any(h.alive and h.server.has_work
                           for h in self.hosts))

    def drain(self):
        """Tick until the fleet is idle; returns (and keeps) the
        accumulated ``{router_rid: tokens}``."""
        while self.has_work:
            self.tick()
        return self.results

    def reset(self):
        """Cold-start every host session and worker pool (fresh pools,
        managers, prefix caches; compiled programs survive) and clear
        the router's routing log — the between-drains reset the A/B
        bench uses."""
        for host in self.hosts:
            host.server.serve_reset()
            host.server._queue.clear()
        for worker in self.workers:
            worker.reset()
        self._queue.clear()
        self._restores.clear()
        self._map.clear()
        self._inflight.clear()
        self._affinity.clear()
        self.results = {}
        self.decisions = []
        self.host_flips = []
        self._base_matched = self._m_matched.get()
        self._base_lookup = self._m_lookup.get()
        # cold-start THIS router's TTFT samples too, or stats() after a
        # timed drain would blend in warmup-compile outliers
        fam = _obs.registry.get("mx_fleet_ttft")
        if fam is not None:
            for host in self.hosts:
                fam.reset_series(host.name)

    # ------------------------------------------------------------------
    def stats(self):
        """Fleet snapshot derived from the mx_fleet_* registry families
        (no parallel bookkeeping): per-host routed counts, migrated /
        swapped pages, aggregate TTFT percentiles over this router's
        hosts, and the routing-time cache-hit estimate."""
        reg = _obs.registry
        names = {h.name for h in self.hosts}

        def per_host(metric):
            fam = reg.get(metric)
            out = {}
            if fam is None:
                return out
            for values, s in fam.series():
                labels = dict(zip(fam.label_names, values))
                if labels.get("host") in names:
                    out[labels["host"]] = s.value
            return out

        ttft = []
        fam = reg.get("mx_fleet_ttft")
        if fam is not None:
            for values, s in fam.series():
                labels = dict(zip(fam.label_names, values))
                if labels.get("host") in names:
                    ttft.extend(s.samples)
        ttft.sort()
        lookup = self._m_lookup.get() - self._base_lookup
        matched = self._m_matched.get() - self._base_matched
        out = {
            "policy": self.policy,
            "hosts": sorted(names),
            "alive_hosts": sorted(h.name for h in self.hosts if h.alive),
            "host_flips": list(self.host_flips),
            "routed_by_host": per_host("mx_fleet_routed"),
            "migrated_pages_by_host": per_host("mx_fleet_migrated_pages"),
            "swapped_pages_by_host": per_host("mx_fleet_swapped_pages"),
            "swap_outs": sum(h.server.swap_outs for h in self.hosts),
            "swap_ins": sum(h.server.swap_ins for h in self.hosts),
            "worker_prefills": sum(w.prefills for w in self.workers),
            "router_cache_hit_rate": matched / max(lookup, 1),
            "requests_completed": len(self.results),
        }
        if ttft:
            out["ttft_p50_s"] = float(np.percentile(ttft, 50))
            out["ttft_p95_s"] = float(np.percentile(ttft, 95))
        return out
