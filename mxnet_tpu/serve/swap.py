"""Host-RAM page swap — the shippable state of a paged request.

When a decode host's page pool runs dry, the serving loop preempts its
lowest-priority slot: the slot's pages (quantized data + per-(token,
head) scales, gathered through ``DecodePredictor.extract_pages`` in ONE
traced program) move to host RAM as a :class:`SwappedRequest`, the pages
return to the pool, and the request re-queues — at the host, or at the
fleet router (``serve.fleet``), which may readmit it on ANY host: page
contents are raw pool bytes, so restore is host-agnostic.  Readmission
allocates fresh pages through the normal
:meth:`~mxnet_tpu.serve.manager.PagedKVManager.gate_pages` reservation
gate and scatters the saved bytes back (``install_pages``, also one
traced program) at the SAME ring positions, so a wrapped long decode
resumes bit-identically (tier-1 asserts bit parity and token identity
with a never-swapped run).

The same record is the wire format of **prefill/decode disaggregation**
(DistServe, Zhong et al. 2024): a dedicated prefill worker runs chunked
prefill into its own pool, extracts the committed prompt pages, and the
record — ``kind="migrate"``, carrying the chain keys via ``publish`` —
installs into the target decode host exactly like a swap-in, plus one
prefix-cache publication so later prompts match the migrated chain.

Nothing here touches jax: the record is numpy + ints; the decode layer
executes the extract/install plans.
"""
from __future__ import annotations

import numpy as np

__all__ = ["SwappedRequest", "SwapStore"]


class SwappedRequest:
    """One preempted (or migrated) request's complete restorable state.

    ``data`` is the per-attention-node pytree of page contents in
    table-row order ((M, page_tokens, E) numpy per plane); ``row_valid``
    the (M,) mask of mapped ring positions; ``lens``/``tok`` the
    committed length and pending token; ``delivered`` the tokens already
    emitted to the caller (generation resumes counting toward ``cap``).

    ``kv_heads`` records the grouped-query K/V layout of the saved pages
    (None = MHA): a GQA source's page planes are H_kv head slices wide —
    G× fewer bytes on the swap/migration wire — and the readmitting host
    must run the SAME grouped layout (checked at install; raw pool bytes
    carry no head structure of their own).
    """

    __slots__ = ("prompt", "delivered", "history", "cap", "priority",
                 "lens", "tok", "row_valid", "data", "kind", "publish",
                 "submit_ts", "first_ts", "rid", "kv_heads")

    def __init__(self, prompt, delivered, history, cap, priority, lens,
                 tok, row_valid, data, kind="swap", publish=False,
                 submit_ts=None, first_ts=None, rid=None, kv_heads=None):
        self.prompt = np.asarray(prompt).reshape(-1).astype(np.int64)
        self.delivered = list(delivered)
        self.history = list(history)
        self.cap = int(cap)
        self.priority = int(priority)
        self.lens = int(lens)
        self.tok = int(tok)
        self.row_valid = np.asarray(row_valid).reshape(-1).astype(bool)
        self.data = data
        self.kind = kind            # "swap" | "migrate"
        self.publish = bool(publish)
        self.submit_ts = submit_ts
        self.first_ts = first_ts
        self.rid = rid              # the router-/host-level id it keeps
        self.kv_heads = int(kv_heads) if kv_heads else None

    @property
    def n_pages(self):
        return int(self.row_valid.sum())

    def nbytes(self):
        """Host-RAM footprint of the saved pages (swap accounting)."""
        import jax.tree_util as jtu

        return int(sum(np.asarray(leaf).nbytes
                       for leaf in jtu.tree_leaves(self.data)))


class SwapStore:
    """Bounded bookkeeping of swapped-out requests (host RAM).

    The serving loop / router parks :class:`SwappedRequest` records here
    between preemption and readmission; ``swapped_bytes`` is the live
    host-RAM bill, mirrored to the ``mx_fleet_swap_bytes`` gauge.
    """

    def __init__(self):
        self._by_rid = {}

    def put(self, record, key=None):
        """Park a record under ``key`` (default its rid; a fleet router
        keys by (host, rid) — host rids are per-server counters and may
        collide across hosts)."""
        self._by_rid[record.rid if key is None else key] = record
        self._note()
        return record

    def pop(self, key):
        rec = self._by_rid.pop(key, None)
        self._note()
        return rec

    def __len__(self):
        return len(self._by_rid)

    def swapped_bytes(self):
        return sum(rec.nbytes() for rec in self._by_rid.values())

    def _note(self):
        from .. import obs as _obs

        _obs.registry.gauge(
            "mx_fleet_swap_bytes",
            "host-RAM bytes held by swapped-out requests").set(
                self.swapped_bytes())
