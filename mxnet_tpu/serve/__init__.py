"""KV-memory management for the serving path — pages, refcounts, prefixes.

The dense-ring serving plan (PR 4/6) allocates one ``(cache_len,)`` -wide
ring buffer per slot, so HBM scales with ``slots x max-context`` and
mixed-length traffic strands most of it — the fragmentation PagedAttention
identified (Kwon et al., *Efficient Memory Management for Large Language
Model Serving with PagedAttention*, SOSP 2023).  This package is the host
half of the paged plan (``MXNET_KV_PAGED``; the device kernels live in
``ops.attention.paged_gather/paged_append/paged_copy``):

* :class:`~mxnet_tpu.serve.allocator.PageAllocator` — a refcounted free
  list over one GLOBAL page-id space (page 0 reserved as the scratch
  page), with admission **reservations** so a request admitted into the
  batch can always finish: exhaustion surfaces as queue backpressure, not
  a mid-decode crash.
* :class:`~mxnet_tpu.serve.prefix_cache.PrefixCache` — copy-on-write
  prefix sharing keyed on token-hash chains (RadixAttention's insight,
  Zheng et al. 2024, at page granularity): matching prompts map their
  leading pages to shared refcounted pages, prefill computes only the
  tail, and the million-users-one-system-prompt case prefills the prompt
  once.  Entries are evictable LRU when the pool runs dry.
* :class:`~mxnet_tpu.serve.manager.PagedKVManager` — per-slot page
  tables (host numpy, passed to the traced programs as DATA — the
  zero-retrace invariant), the append-path ownership rule (a write into a
  page with refcount > 1 forks it first — copy-on-write), and
  slot-lifetime bookkeeping (map/ensure/free, utilization stats).

``decode.DecodePredictor(paged=True)`` and ``decode.DecodeServer`` drive
all three; nothing here touches jax — the manager only *decides* and the
decode layer executes the resulting fork/append plans on device.

Above the single host sit the fleet layers (docs/serving_fleet.md):

* :mod:`~mxnet_tpu.serve.swap` — restorable page records: preemption
  swap-out to host RAM and the page-migration wire format of
  prefill/decode disaggregation (one extract + one install program,
  page ids as data — zero retraces);
* :mod:`~mxnet_tpu.serve.fleet` — the front-end :class:`Router` over N
  per-host ``DecodeServer``\\ s: cache-aware routing on prefix-chain
  summaries, dedicated :class:`PrefillWorker`\\ s shipping committed
  pages DistServe-style, and preemption rehoming.
"""
from __future__ import annotations

from .allocator import PageAllocator
from .prefix_cache import PrefixCache, chain_hash
from .manager import PagedKVManager
from .swap import SwapStore, SwappedRequest

__all__ = ["PageAllocator", "PrefixCache", "PagedKVManager",
           "SwapStore", "SwappedRequest", "chain_hash"]


def __getattr__(name):
    # fleet imports obs (and through it config/metrics); keep the base
    # package import light by resolving the router layer lazily
    if name in ("FleetHost", "PrefillWorker", "Router", "match_chains"):
        from . import fleet

        return getattr(fleet, name)
    raise AttributeError(name)
