"""Refcounted page allocator with admission reservations.

One global page-id space covers every attention node's pool (the pools all
have the same page count, so a single id addresses the page in each of
them — the vLLM block-table convention).  Page 0 is reserved as the
scratch page: unmapped table entries point at it and masked writes are
redirected into it, so it is never allocated.

Two invariants the serving loop leans on:

* **Refcounts are ownership.**  ``ref == 1`` means exactly one holder
  (a slot, or the prefix cache) — writable in place.  ``ref > 1`` means
  shared — the manager forks (copy-on-write) before any append touches
  it.  ``decref`` to zero returns the page to the free list.
* **Reservations are admission control.**  ``reserve(n)`` succeeds only
  while ``available()`` (free minus already-reserved) covers ``n``; a
  request is admitted only after its whole worst-case page budget
  (prompt tail + generation cap + speculation window + one fork) is
  reserved, so decode can never strand a half-served request — pool
  pressure shows up as requests WAITING in the queue (backpressure), and
  the queue drains as retirements free pages.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["PageAllocator"]


class PageAllocator:
    """Free list + refcounts + reservations over ``num_pages`` page ids
    (ids 1..num_pages-1 allocatable; id 0 is the scratch page)."""

    def __init__(self, num_pages):
        self.num_pages = int(num_pages)
        if self.num_pages < 2:
            raise MXNetError("page pool needs >= 2 pages (page 0 is the "
                             "scratch page); got %d" % self.num_pages)
        # pop() hands out ascending ids (nicer to read in tests/dumps)
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._ref = np.zeros(self.num_pages, np.int64)
        self._reserved = 0
        self.peak_used = 0
        self.forks = 0          # COW fork count (manager bumps it)
        self.frees = 0          # pages returned to the free list

    @property
    def free_pages(self):
        return len(self._free)

    @property
    def used_pages(self):
        return self.num_pages - 1 - len(self._free)

    def available(self):
        """Pages an admission gate may still claim: free minus reserved."""
        return len(self._free) - self._reserved

    def reserve(self, n):
        """Claim ``n`` future allocations; False (and no change) if the
        unreserved free pool cannot cover them."""
        n = int(n)
        if self.available() < n:
            return False
        self._reserved += n
        return True

    def unreserve(self, n):
        self._reserved -= int(n)
        assert self._reserved >= 0, "unreserve below zero"

    def alloc(self, from_reserve=False):
        """One fresh page at refcount 1.  ``from_reserve`` spends a prior
        :meth:`reserve` claim; otherwise the page must be unreserved
        headroom.  Raises :class:`MXNetError` on exhaustion — the caller
        (manager) evicts prefix-cache pages and retries before letting
        this surface."""
        if not self._free or (not from_reserve and self.available() < 1):
            raise MXNetError(
                "KV page pool exhausted (%d pages, %d free, %d reserved) — "
                "raise MXNET_KV_POOL_PAGES or admit fewer concurrent "
                "requests" % (self.num_pages, len(self._free),
                              self._reserved))
        if from_reserve:
            self._reserved -= 1
            assert self._reserved >= 0, "allocating from an empty reserve"
        page = self._free.pop()
        self._ref[page] = 1
        self.peak_used = max(self.peak_used, self.used_pages)
        return page

    def incref(self, page):
        assert self._ref[page] > 0, "incref of a free page"
        self._ref[page] += 1

    def decref(self, page):
        """Drop one reference; returns True when the page was freed."""
        assert self._ref[page] > 0, "decref of a free page"
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(int(page))
            self.frees += 1
            return True
        return False

    def refcount(self, page):
        return int(self._ref[page])

    def shared(self, page):
        """True when more than one holder references the page — a write
        must copy-on-write fork it first."""
        return self._ref[page] > 1
