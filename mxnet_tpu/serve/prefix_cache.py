"""Copy-on-write prefix cache keyed on token chains.

Matching prompts share K/V pages instead of re-prefilling them: the cache
maps the tokens of each page-aligned prompt prefix to the page already
holding its K/V.  Keys are the literal token tuples (collision-free; the
chains are short at serving scale and the page granularity keeps the dict
small) in two granularities:

* **full-page entries** — key = ``tokens[:i*page_tokens]`` for each full
  page a finished prefill produced; a new prompt matches the longest
  chain of full pages it starts with;
* **partial-page entries** — key = (full-page prefix, the final partial
  page's tokens); they let a prompt whose divergence point is mid-page
  still share the page holding the common tokens.  The sharer maps the
  page read-only — its first append into it copy-on-write forks it
  (refcount > 1, see ``manager.PagedKVManager.ensure``), which is also
  why entries stay valid while live slots keep generating "into" them.

Matching inside the final page is **token-level radix**: after the exact
full-page chain, the cache takes the longest common token prefix between
the remaining prompt and any stored continuation of that chain — a
partial entry OR the last page of a one-page-deeper full chain.  A
prompt that diverges *mid-page* still shares the page up to the
divergence point (the length mask hides the tail; the slot's first
write there copy-on-write forks), where the older exact-content rule
matched nothing.

Matches are capped at ``len(prompt) - 1`` tokens so at least one position
always prefills — the sampled first token needs a freshly computed
distribution (the vLLM full-hit rule).

:func:`chain_hash` digests token chains for the
``/metrics.json`` **chain summary** (:meth:`PrefixCache.summary`) the
fleet router scores hosts against (``serve.fleet``): full-page chains
export as prefix hashes, partial entries as (prefix hash, length,
content hash) — compact, content-free, and computable on both ends.

The cache holds one refcount per cached page, so retirement of the slot
that produced a page does not free it; :meth:`evict` walks LRU order and
drops entries until enough pages actually return to the free list (pages
still mapped by live slots just lose their cache ref).
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np

__all__ = ["PrefixCache", "chain_hash"]


def chain_hash(tokens):
    """Stable 16-hex-char digest of a token chain — the wire spelling of
    a cached prefix in the router-facing chain summary.  Both ends (the
    host's :meth:`PrefixCache.summary` and the router's prompt scoring)
    hash through here, so a match estimate is an exact set lookup."""
    arr = np.ascontiguousarray(np.asarray(tokens, np.int64).reshape(-1))
    return hashlib.blake2b(arr.tobytes(), digest_size=8).hexdigest()


class PrefixCache:
    """Token-chain -> page map with LRU eviction and hit accounting."""

    def __init__(self, page_tokens, allocator):
        self._pt = int(page_tokens)
        self._alloc = allocator
        # key -> page id; full keys are token tuples, partial keys are
        # (full-prefix tuple, partial-tokens tuple).  One OrderedDict so
        # eviction is a single LRU walk.
        self._entries = OrderedDict()
        # full-prefix tuple -> {content tuple: key}: every stored
        # continuation of a full-page chain — partial entries (content =
        # the partial tokens) AND the final page of one-page-deeper full
        # chains (content = that page's pt tokens).  The radix frontier:
        # match() takes the longest common token prefix of the remaining
        # prompt against these contents.
        self._children = {}
        # page id -> set of keys holding it (wrap recycling invalidates
        # a page's entries through this reverse map)
        self._by_page = {}
        self.lookup_tokens = 0
        self.matched_tokens = 0
        self.lookups = 0
        self.hits = 0           # lookups that matched at least one page
        self.radix_hits = 0     # frontier matches that diverged MID-page
        # content-mutation stamp: summary() caches against it, so the
        # router's per-submission polls re-hash nothing while the cache
        # is unchanged (the PagedKVManager.version pattern)
        self._content_version = 0
        self._summary_cache = None

    @property
    def pages_held(self):
        return len(self._entries)

    @property
    def hit_rate(self):
        """Matched prompt tokens / looked-up prompt tokens — the fraction
        of prefill work the cache removed."""
        return self.matched_tokens / max(self.lookup_tokens, 1)

    @staticmethod
    def _tokens(prompt):
        return tuple(int(t) for t in np.asarray(prompt).reshape(-1))

    def _touch(self, key):
        self._entries.move_to_end(key)

    # ------------------------------------------------------------------
    def match(self, prompt):
        """Longest cached prefix of ``prompt`` (1-D int tokens).

        Returns ``(matched_len, pages)``: ``pages`` covers positions
        [0, matched_len) in order — all full pages plus at most one
        partially-read page; ``matched_len <= len(prompt) - 1`` always.
        The caller maps the pages (increfs them) or drops the result;
        the cache itself keeps its own refs either way.
        """
        toks = self._tokens(prompt)
        cap = max(len(toks) - 1, 0)
        self.lookups += 1
        self.lookup_tokens += max(cap, 0)
        pages = []
        n_full = 0
        while (n_full + 1) * self._pt <= len(toks):
            key = toks[:(n_full + 1) * self._pt]
            page = self._entries.get(key)
            if page is None:
                break
            self._touch(key)
            pages.append(page)
            n_full += 1
        matched = n_full * self._pt
        # radix extension at the frontier: the longest common TOKEN
        # prefix between the remaining prompt and any stored
        # continuation of the matched chain — a partial entry, or the
        # final page of a one-page-deeper full chain (whose exact match
        # the walk above already ruled out).  Divergence mid-page still
        # shares the page up to the divergence point; the length mask
        # hides the tail and the first write there forks (COW).
        rest = toks[matched:]
        best_lcp, best_key, best_content = 0, None, None
        for content, key in self._children.get(toks[:matched],
                                               {}).items():
            lcp = 0
            for a, b in zip(content, rest):
                if a != b:
                    break
                lcp += 1
            if lcp > best_lcp:
                best_lcp, best_key, best_content = lcp, key, content
        if best_lcp > 0:
            self._touch(best_key)
            pages.append(self._entries[best_key])
            matched += best_lcp
            if best_lcp < len(best_content):
                self.radix_hits += 1
        if matched > cap:
            # never match the whole prompt: the last token must prefill so
            # the first sampled token has a distribution.  Trimming tokens
            # may drop the final page entirely (it held only trimmed ones).
            matched = cap
            if matched <= (len(pages) - 1) * self._pt:
                pages.pop()
        if matched > 0:
            self.hits += 1
        self.matched_tokens += matched
        return matched, pages

    # ------------------------------------------------------------------
    def insert(self, prompt, prompt_len, pages):
        """Publish a finished prefill's prompt pages.

        ``pages`` are the slot's table entries covering positions
        [0, prompt_len).  Each NEW key increfs its page (the cache's own
        reference); keys already cached keep their existing page
        (first-in wins — the duplicate page stays slot-owned only).
        """
        toks = self._tokens(prompt)[:int(prompt_len)]
        n_full = len(toks) // self._pt
        for i in range(n_full):
            key = toks[:(i + 1) * self._pt]
            if key in self._entries:
                self._touch(key)
                continue
            page = pages[i]
            self._alloc.incref(page)
            self._entries[key] = page
            self._by_page.setdefault(page, set()).add(key)
            self._children.setdefault(toks[:i * self._pt],
                                      {})[key[i * self._pt:]] = key
            self._content_version += 1
        tail = toks[n_full * self._pt:]
        if tail and n_full < len(pages):
            full_key = toks[:n_full * self._pt]
            key = (full_key, tail)
            if key in self._entries:
                self._touch(key)
            else:
                page = pages[n_full]
                self._alloc.incref(page)
                self._entries[key] = page
                self._by_page.setdefault(page, set()).add(key)
                self._children.setdefault(full_key, {})[tail] = key
                self._content_version += 1

    # ------------------------------------------------------------------
    def evict(self, need_pages):
        """Drop LRU entries until ``need_pages`` pages actually freed (or
        no more are evictable).  Entries whose page is still referenced
        outside the cache (a live slot maps it) are SKIPPED: dropping
        them would lose future sharing while freeing nothing — mere
        backpressure must not drain the cache.  Returns the number
        freed."""
        freed = 0
        if need_pages <= 0:
            return 0
        for key in list(self._entries):         # LRU order
            page = self._entries.get(key)
            if page is None:
                continue
            if self._alloc.refcount(page) > 1:
                continue                        # live holder beyond us
            self._drop(key)
            if self._alloc.decref(page):
                freed += 1
            if freed >= need_pages:
                break
        return freed

    def release_page(self, page):
        """Invalidate every entry holding ``page`` and drop the cache's
        refs — the wrap-recycle path: a slot is about to overwrite the
        page in place, so its cached content is dead.  Returns the number
        of entries dropped."""
        keys = list(self._by_page.get(page, ()))
        for key in keys:
            self._drop(key)
            self._alloc.decref(page)
        return len(keys)

    def _drop(self, key):
        self._content_version += 1
        page = self._entries.pop(key)
        held = self._by_page.get(page)
        if held is not None:
            held.discard(key)
            if not held:
                del self._by_page[page]
        if len(key) == 2 and isinstance(key[0], tuple) \
                and isinstance(key[1], tuple):
            parent, content = key[0], key[1]
        else:
            parent, content = key[:len(key) - self._pt], key[-self._pt:]
        kids = self._children.get(parent)
        if kids is not None:
            kids.pop(content, None)
            if not kids:
                del self._children[parent]

    def clear(self):
        """Decref every cached page and empty the cache."""
        for key, page in list(self._entries.items()):
            self._alloc.decref(page)
        self._entries.clear()
        self._children.clear()
        self._by_page.clear()
        self._content_version += 1

    # ------------------------------------------------------------------
    def summary(self):
        """Content-free digest of the cached chains for router scoring
        (served in ``/metrics.json``): full-page chains as prefix hashes
        (:func:`chain_hash`), partial entries as (parent-prefix hash,
        partial length, content hash).  The fleet router replays the
        same hashes over an incoming prompt to estimate each host's
        longest cached chain without ever shipping token content.
        Cached against the content version — a routing burst polling an
        unchanged cache re-hashes nothing (treat the result as
        read-only)."""
        if self._summary_cache is not None \
                and self._summary_cache[0] == self._content_version:
            return self._summary_cache[1]
        full, partial = [], []
        for key in self._entries:
            if len(key) == 2 and isinstance(key[0], tuple) \
                    and isinstance(key[1], tuple):
                partial.append({"prefix": chain_hash(key[0]),
                                "len": len(key[1]),
                                "hash": chain_hash(key[1])})
            else:
                full.append(chain_hash(key))
        out = {"page_tokens": self._pt, "full": full, "partial": partial}
        self._summary_cache = (self._content_version, out)
        return out
