"""Copy-on-write prefix cache keyed on token chains.

Matching prompts share K/V pages instead of re-prefilling them: the cache
maps the tokens of each page-aligned prompt prefix to the page already
holding its K/V.  Keys are the literal token tuples (collision-free; the
chains are short at serving scale and the page granularity keeps the dict
small) in two granularities:

* **full-page entries** — key = ``tokens[:i*page_tokens]`` for each full
  page a finished prefill produced; a new prompt matches the longest
  chain of full pages it starts with;
* **partial-page entries** — key = (full-page prefix, the final partial
  page's tokens); they let a prompt whose divergence point is mid-page
  still share the page holding the common tokens.  The sharer maps the
  page read-only — its first append into it copy-on-write forks it
  (refcount > 1, see ``manager.PagedKVManager.ensure``), which is also
  why entries stay valid while live slots keep generating "into" them.

Matches are capped at ``len(prompt) - 1`` tokens so at least one position
always prefills — the sampled first token needs a freshly computed
distribution (the vLLM full-hit rule).

The cache holds one refcount per cached page, so retirement of the slot
that produced a page does not free it; :meth:`evict` walks LRU order and
drops entries until enough pages actually return to the free list (pages
still mapped by live slots just lose their cache ref).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

__all__ = ["PrefixCache"]


class PrefixCache:
    """Token-chain -> page map with LRU eviction and hit accounting."""

    def __init__(self, page_tokens, allocator):
        self._pt = int(page_tokens)
        self._alloc = allocator
        # key -> page id; full keys are token tuples, partial keys are
        # (full-prefix tuple, partial-tokens tuple).  One OrderedDict so
        # eviction is a single LRU walk.
        self._entries = OrderedDict()
        # full-prefix tuple -> {partial tuple: key} for partial matching
        self._partials = {}
        # page id -> set of keys holding it (wrap recycling invalidates
        # a page's entries through this reverse map)
        self._by_page = {}
        self.lookup_tokens = 0
        self.matched_tokens = 0
        self.lookups = 0
        self.hits = 0           # lookups that matched at least one page

    @property
    def pages_held(self):
        return len(self._entries)

    @property
    def hit_rate(self):
        """Matched prompt tokens / looked-up prompt tokens — the fraction
        of prefill work the cache removed."""
        return self.matched_tokens / max(self.lookup_tokens, 1)

    @staticmethod
    def _tokens(prompt):
        return tuple(int(t) for t in np.asarray(prompt).reshape(-1))

    def _touch(self, key):
        self._entries.move_to_end(key)

    # ------------------------------------------------------------------
    def match(self, prompt):
        """Longest cached prefix of ``prompt`` (1-D int tokens).

        Returns ``(matched_len, pages)``: ``pages`` covers positions
        [0, matched_len) in order — all full pages plus at most one
        partially-read page; ``matched_len <= len(prompt) - 1`` always.
        The caller maps the pages (increfs them) or drops the result;
        the cache itself keeps its own refs either way.
        """
        toks = self._tokens(prompt)
        cap = max(len(toks) - 1, 0)
        self.lookups += 1
        self.lookup_tokens += max(cap, 0)
        pages = []
        n_full = 0
        while (n_full + 1) * self._pt <= len(toks):
            key = toks[:(n_full + 1) * self._pt]
            page = self._entries.get(key)
            if page is None:
                break
            self._touch(key)
            pages.append(page)
            n_full += 1
        matched = n_full * self._pt
        # partial extension: the longest stored partial-page content that
        # prefixes the remaining tokens
        rest = toks[matched:]
        best = None
        for part, key in self._partials.get(toks[:matched], {}).items():
            if len(part) <= len(rest) and rest[:len(part)] == part \
                    and (best is None or len(part) > len(best)):
                best = part
        if best is not None:
            key = (toks[:matched], best)
            self._touch(key)
            pages.append(self._entries[key])
            matched += len(best)
        if matched > cap:
            # never match the whole prompt: the last token must prefill so
            # the first sampled token has a distribution.  Trimming tokens
            # may drop the final page entirely (it held only trimmed ones).
            matched = cap
            if matched <= (len(pages) - 1) * self._pt:
                pages.pop()
        if matched > 0:
            self.hits += 1
        self.matched_tokens += matched
        return matched, pages

    # ------------------------------------------------------------------
    def insert(self, prompt, prompt_len, pages):
        """Publish a finished prefill's prompt pages.

        ``pages`` are the slot's table entries covering positions
        [0, prompt_len).  Each NEW key increfs its page (the cache's own
        reference); keys already cached keep their existing page
        (first-in wins — the duplicate page stays slot-owned only).
        """
        toks = self._tokens(prompt)[:int(prompt_len)]
        n_full = len(toks) // self._pt
        for i in range(n_full):
            key = toks[:(i + 1) * self._pt]
            if key in self._entries:
                self._touch(key)
                continue
            page = pages[i]
            self._alloc.incref(page)
            self._entries[key] = page
            self._by_page.setdefault(page, set()).add(key)
        tail = toks[n_full * self._pt:]
        if tail and n_full < len(pages):
            full_key = toks[:n_full * self._pt]
            key = (full_key, tail)
            if key in self._entries:
                self._touch(key)
            else:
                page = pages[n_full]
                self._alloc.incref(page)
                self._entries[key] = page
                self._by_page.setdefault(page, set()).add(key)
                self._partials.setdefault(full_key, {})[tail] = key

    # ------------------------------------------------------------------
    def evict(self, need_pages):
        """Drop LRU entries until ``need_pages`` pages actually freed (or
        no more are evictable).  Entries whose page is still referenced
        outside the cache (a live slot maps it) are SKIPPED: dropping
        them would lose future sharing while freeing nothing — mere
        backpressure must not drain the cache.  Returns the number
        freed."""
        freed = 0
        if need_pages <= 0:
            return 0
        for key in list(self._entries):         # LRU order
            page = self._entries.get(key)
            if page is None:
                continue
            if self._alloc.refcount(page) > 1:
                continue                        # live holder beyond us
            self._drop(key)
            if self._alloc.decref(page):
                freed += 1
            if freed >= need_pages:
                break
        return freed

    def release_page(self, page):
        """Invalidate every entry holding ``page`` and drop the cache's
        refs — the wrap-recycle path: a slot is about to overwrite the
        page in place, so its cached content is dead.  Returns the number
        of entries dropped."""
        keys = list(self._by_page.get(page, ()))
        for key in keys:
            self._drop(key)
            self._alloc.decref(page)
        return len(keys)

    def _drop(self, key):
        page = self._entries.pop(key)
        held = self._by_page.get(page)
        if held is not None:
            held.discard(key)
            if not held:
                del self._by_page[page]
        if isinstance(key, tuple) and len(key) == 2 \
                and isinstance(key[0], tuple) and isinstance(key[1], tuple) \
                and key[0] in self._partials:
            self._partials[key[0]].pop(key[1], None)
            if not self._partials[key[0]]:
                del self._partials[key[0]]

    def clear(self):
        """Decref every cached page and empty the cache."""
        for key, page in list(self._entries.items()):
            self._alloc.decref(page)
        self._entries.clear()
        self._partials.clear()
        self._by_page.clear()
