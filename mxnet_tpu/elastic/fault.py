"""Deterministic fault injection for the elastic subsystem's tests.

Real failures are wall-clock events (a preempted VM stops stamping, a
process dies mid-write).  The tier-1 suite runs on one box with a noisy
shared clock, so every failure mode is reproduced *deterministically* at
a chosen global step instead: the :class:`FaultInjector` rides the
elastic controller's per-step hook and fires registered actions — raise
:class:`WorkerKilled` (the kill -9 analog: the exception escapes
``fit()`` with whatever the writer thread managed to commit), backdate a
rank's heartbeat stamp (stale-heartbeat death, no sleeping), write a
fresh stamp (worker return → regrow), or drop a torn step directory into
a checkpoint dir (crash mid-save).
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["FaultInjector", "WorkerKilled"]


class WorkerKilled(RuntimeError):
    """The injected analog of the training process dying at a step."""


class FaultInjector:
    """Fire registered fault actions at exact global step numbers.

    Actions run on the loop thread at the TOP of the controller's
    per-step hook — before that step's fence checkpoint or monitor poll —
    so "kill at step N" means the checkpoint at N never happens, exactly
    like a real death."""

    def __init__(self):
        self._actions = {}
        self.fired = []

    def at_step(self, step, fn):
        """Run ``fn()`` when global step ``step`` is reached."""
        self._actions.setdefault(int(step), []).append(fn)
        return self

    def kill_at(self, step):
        """Simulate the worker dying at ``step`` (raises WorkerKilled)."""
        def _kill():
            raise WorkerKilled("fault injection: killed at step %d" % step)
        return self.at_step(step, _kill)

    def stale_heartbeat_at(self, step, directory, rank, age=1e6):
        """Backdate ``rank``'s stamp at ``step`` so the next monitor poll
        reads it as ``age`` seconds stale — deterministic death, no
        waiting for a timeout to elapse."""
        def _stale():
            path = os.path.join(directory, "worker-%d.heartbeat" % rank)
            tmp = "%s.tmp.inject" % path
            with open(tmp, "w") as f:
                json.dump({"rank": rank, "time": time.time() - age,
                           "pid": -1}, f)
            os.replace(tmp, path)
        return self.at_step(step, _stale)

    def revive_heartbeat_at(self, step, directory, rank):
        """Write a fresh stamp for ``rank`` at ``step`` (worker return)."""
        def _revive():
            from ..parallel.health import Heartbeat

            Heartbeat(directory, rank).beat()
        return self.at_step(step, _revive)

    def fire(self, global_step):
        """Controller hook: run (and consume) the actions for this step."""
        for fn in self._actions.pop(int(global_step), ()):
            self.fired.append(global_step)
            fn()

    @staticmethod
    def torn_checkpoint(directory, step):
        """Create an UNCOMMITTED step directory — the debris of a crash
        mid-save (no commit marker, no orbax finalize metadata).
        ``checkpoint.latest_step`` must skip it."""
        path = os.path.join(os.path.abspath(directory), str(step))
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "shard-0.partial"), "w") as f:
            f.write("torn mid-write\n")
        return path
