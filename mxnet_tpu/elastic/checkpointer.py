"""Async fenced checkpointing — snapshot on device, write off the hot loop.

Check-Freq's observation (Mohan et al., FAST '21): checkpoint stalls
vanish when the *snapshot* (cheap, must be consistent) is decoupled from
the *write* (slow, needs no loop participation).  Here the snapshot is a
set of ``jnp.copy`` dispatches against the fused step's donated
params/slots/aux chain — they sequence after the latest dispatched step
and before the next one, so the state they capture is exactly
"after step N" without any host sync — and the write is an orbax save on
a background thread that materializes those copies (the d2h) and lands a
committed step directory (``checkpoint.save_state_tree`` + sidecar +
``commit_step``, in that order, so a crash anywhere leaves the previous
checkpoint as the resume point).

At most one write is in flight: a fence arriving while the writer is busy
is *skipped* (counted in ``skipped_busy``), never queued — checkpoints
are periodic, the next fence writes.  ``MXNET_CKPT_ASYNC=0`` runs the
writer inline on the loop thread (the A/B baseline for the
``checkpoint_stall_fraction`` bench field); its d2h is the sanctioned
fence transfer, wrapped in an explicit ``transfer_guard`` allow scope so
``MXNET_TRANSFER_GUARD=disallow`` stays armable around the rest of the
loop.
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time

from .. import checkpoint as ckpt_mod
from ..base import MXNetError

__all__ = ["Checkpointer", "SIDECAR"]

SIDECAR = "elastic.json"

log = logging.getLogger(__name__)


def _metric_device_copy(module):
    """Device-side copies of the fused step's metric accumulator state
    (per metric leaf: ``[[sums...], [counts...]]``), or None.  Copies are
    async dispatches; the writer thread materializes them."""
    import jax.numpy as jnp

    fused = getattr(module, "_fused_step", None)
    acc = getattr(fused, "_metric_acc", None) if fused is not None else None
    if acc is None or acc.state is None:
        return None
    return [[[jnp.copy(s) for s in sums], [jnp.copy(c) for c in counts]]
            for sums, counts in acc.state]


class Checkpointer:
    """Periodic fenced checkpoints of a training module into one
    directory of committed orbax step dirs (step = global step number),
    each with an ``elastic.json`` sidecar carrying the loop state for
    deterministic resume."""

    def __init__(self, directory, period=None, async_write=None, keep=None,
                 resume=None):
        from .. import config as _config

        self.directory = os.path.abspath(directory)
        self.period = int(_config.get("MXNET_CKPT_PERIOD")
                          if period is None else period)
        self.async_write = bool(_config.get("MXNET_CKPT_ASYNC")
                                if async_write is None else async_write)
        self.keep = int(_config.get("MXNET_CKPT_KEEP")
                        if keep is None else keep)
        self.resume = bool(_config.get("MXNET_CKPT_RESUME")
                           if resume is None else resume)
        os.makedirs(self.directory, exist_ok=True)
        self._thread = None
        self._error = None
        self.writes = 0          # committed checkpoints
        self.skipped_busy = 0    # fences skipped because a write was in flight
        self.steps_during_write = 0  # steps dispatched while a write ran

    # ------------------------------------------------------------------
    def writing(self):
        """Whether a background write is currently in flight."""
        return self._thread is not None and self._thread.is_alive()

    def note_step(self):
        """Called once per training step by the controller: counts steps
        that overlapped an in-flight write (the overlap the async design
        exists to produce — asserted by the bench/tests)."""
        if self.writing():
            self.steps_during_write += 1

    def _raise_pending(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise MXNetError("elastic checkpoint write failed: %s" % (err,))

    # ------------------------------------------------------------------
    def snapshot(self, module, meta):
        """Take a fence checkpoint of ``module`` (loop thread).

        ``meta`` is the controller-assembled sidecar dict (epoch,
        nbatch_done, global_step, metric host sums, iterator record).
        Returns True when a write was started/performed, False when
        skipped because one is already in flight.  Only the device-copy
        dispatches and (async) thread start run here — the loop never
        blocks on d2h or disk."""
        from .. import profiler as _prof
        from .. import random as _rnd

        t0 = time.perf_counter()
        try:
            self._raise_pending()
            if self.writing():
                self.skipped_busy += 1
                return False
            job = {
                "state": self._device_snapshot(module),
                "meta": dict(meta),
                # the key chain is thread-local: capture the ARRAY here on
                # the loop thread; the writer only materializes it
                "rng": _rnd._key(),
                "metric_device": _metric_device_copy(module),
            }
            from .. import obs as _obs

            _obs.instant("ckpt_fence", cat="elastic",
                         args={"step": int(meta.get("global_step", -1))})
            if self.async_write:
                self._thread = threading.Thread(
                    target=self._write_guarded, args=(job,), daemon=True,
                    name="mxtpu-ckpt-writer")
                self._thread.start()
            else:
                self._write_allowed(job)
            return True
        finally:
            _prof.record_ckpt_stall(time.perf_counter() - t0)

    def _device_snapshot(self, module):
        """Consistent device-side copies of params/aux (+fused optimizer
        slots).  With the fused step owning state these copy the master
        store — the arrays the NEXT step will donate, so the copies must
        (and do) dispatch before it.  On the eager path the executor
        buffers are copied; optimizer slots then live in the eager
        updater and are not fenced (resume re-seeds fresh moments — the
        fused path is the deterministic-resume path)."""
        import jax.numpy as jnp

        fused = getattr(module, "_fused_step", None)
        if fused is not None and module._opt_owner == "fused" \
                and not module._step_stale:
            state = {"params": {n: jnp.copy(v)
                                for n, v in fused.params.items()},
                     "aux": {n: jnp.copy(v) for n, v in fused.aux.items()}}
            if fused.slots:
                state["slots"] = {n: [jnp.copy(s) for s in v]
                                  for n, v in fused.slots.items()}
            return state
        exe = module._exec_group.exec_
        return {"params": {n: jnp.copy(exe.arg_dict[n].data)
                           for n in module._exec_group.param_names},
                "aux": {n: jnp.copy(exe.aux_dict[n].data)
                        for n in module._exec_group.aux_names}}

    # ------------------------------------------------------------------
    def _write_guarded(self, job):
        try:
            self._write(job)
        except Exception as exc:  # surfaced on the loop thread next fence
            log.warning("elastic checkpoint write failed: %s", exc)
            self._error = exc

    def _write_allowed(self, job):
        """Inline (synchronous) write on the loop thread: its d2h is the
        sanctioned fence transfer — explicitly allow-listed so an armed
        MXNET_TRANSFER_GUARD=disallow loop can still checkpoint."""
        import jax

        with jax.transfer_guard_device_to_host("allow"):
            self._write(job)

    def _write(self, job):
        import numpy as np

        from .. import profiler as _prof

        t0 = time.perf_counter()
        step = int(job["meta"]["global_step"])
        # 1. shards land under an orbax tmp dir, atomically renamed to
        #    directory/<step> when complete (this materializes the copies)
        path = ckpt_mod.save_state_tree(self.directory, step, job["state"])
        # 2. sidecar: loop state for deterministic resume
        sidecar = dict(job["meta"])
        rng = np.asarray(job["rng"])
        sidecar["rng_key"] = rng.tolist()
        sidecar["rng_dtype"] = str(rng.dtype)
        dev = job["metric_device"]
        if dev is not None:
            sidecar["metric_device"] = [
                [[float(np.asarray(s)) for s in sums],
                 [float(np.asarray(c)) for c in counts]]
                for sums, counts in dev]
        tmp = os.path.join(path, SIDECAR + ".tmp")
        with open(tmp, "w") as f:
            json.dump(sidecar, f)
        os.replace(tmp, os.path.join(path, SIDECAR))
        # 3. the commit marker is the LAST write: latest_step only ever
        #    resumes from steps that got this far
        ckpt_mod.commit_step(path)
        self.writes += 1
        ms = (time.perf_counter() - t0) * 1e3
        _prof.record_ckpt_write(ms)
        # the commit instant lands from the WRITER thread — the timeline
        # is thread-aware, so the overlap with loop steps is visible
        from .. import obs as _obs

        _obs.instant("ckpt_commit", cat="elastic",
                     args={"step": step, "ms": round(ms, 3)})
        self._prune()

    def _prune(self):
        entries = os.listdir(self.directory)
        committed = sorted(s for s in (int(d) for d in entries
                                       if d.isdigit())
                           if ckpt_mod.is_committed(self.directory, s))
        if not committed:
            return
        newest = committed[-1]
        if self.keep > 0:
            for s in committed[:-self.keep]:
                shutil.rmtree(os.path.join(self.directory, str(s)),
                              ignore_errors=True)
        # torn debris below the newest commit is provably dead (the one
        # in-flight write is always the newest step): crash leftovers —
        # uncommitted step dirs and orbax tmp dirs — must not accumulate
        # shard payloads forever in a long-lived checkpoint directory
        for name in entries:
            if name.isdigit():
                s = int(name)
                dead = s < newest and not ckpt_mod.is_committed(
                    self.directory, s)
            else:
                head = name.split(".", 1)[0]
                dead = ".orbax-checkpoint-tmp" in name and \
                    head.isdigit() and int(head) < newest
            if dead:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # ------------------------------------------------------------------
    def wait(self):
        """Join any in-flight write (epoch/fit end, pre-restore barrier)."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._raise_pending()

    def latest(self):
        """Highest committed step that also carries the elastic sidecar."""
        if not os.path.isdir(self.directory):
            return None
        steps = [int(d) for d in os.listdir(self.directory)
                 if d.isdigit() and ckpt_mod.is_committed(self.directory, d)
                 and os.path.exists(os.path.join(self.directory, d, SIDECAR))]
        return max(steps) if steps else None

    def peek(self):
        """The latest committed fence's sidecar meta WITHOUT touching the
        module (attach() sanity-checks epoch compatibility before the
        destructive restore), or None."""
        self.wait()
        step = self.latest()
        if step is None:
            return None
        with open(os.path.join(self.directory, str(step), SIDECAR)) as f:
            return json.load(f)

    def restore(self, module):
        """Restore the latest committed fence checkpoint into ``module``
        (params/aux/slots re-sharded to its live mesh, RNG chain reset to
        the fence value) and return the sidecar meta dict — or None when
        the directory holds no committed elastic checkpoint."""
        import numpy as np

        self.wait()
        step = self.latest()
        if step is None:
            return None
        ckpt_mod.load_sharded(self.directory, step, module)
        with open(os.path.join(self.directory, str(step), SIDECAR)) as f:
            meta = json.load(f)
        self._restore_rng(meta)
        self._restore_optimizer(module, meta)
        return meta

    @staticmethod
    def _restore_optimizer(module, meta):
        """Update counts back to the fence values: Adam's bias correction
        and lr schedules read them, so replayed step t must really be
        step t (the slots themselves rode the orbax tree)."""
        opt = getattr(module, "_optimizer", None)
        rec = meta.get("optimizer")
        if opt is None or not rec:
            return
        opt.begin_num_update = int(rec["begin_num_update"])
        opt.num_update = int(rec["num_update"])
        opt._index_update_count = {
            int(k): int(v)
            for k, v in rec.get("index_update_count", {}).items()}

    @staticmethod
    def _restore_rng(meta):
        import jax.numpy as jnp
        import numpy as np

        from .. import random as _rnd

        key = meta.get("rng_key")
        if key is None:
            return
        _rnd._state.key = jnp.asarray(
            np.asarray(key, dtype=meta.get("rng_dtype", "uint32")))
