"""The elastic controller — fit()'s fault-tolerance sidecar.

``fit`` drives it through four hooks (all no-ops without a controller):
``attach`` once before the epoch loop (auto-resume from the latest
committed fence), ``on_epoch_start`` per epoch (mid-epoch fast-forward +
metric restore when resuming), ``on_step`` per dispatched step (fault
injection, periodic fence checkpoint, failure-monitor poll — raising
:class:`ReconfigureSignal` after draining in-flight steps when liveness
changed), and ``handle_reconfigure`` when that signal unwinds the epoch
(re-form the mesh on the survivors, restore the last fence, hand back the
resume epoch).
"""
from __future__ import annotations

import logging

from ..base import MXNetError

__all__ = ["ElasticController", "ReconfigureSignal", "from_env"]

log = logging.getLogger(__name__)


class ReconfigureSignal(Exception):
    """Raised out of the epoch body when the failure monitor reports a
    liveness transition; carries the
    :class:`~mxnet_tpu.parallel.health.ReconfigEvent`.  In-flight steps
    are drained BEFORE this is raised, so nothing is outstanding when the
    mesh re-forms."""

    def __init__(self, event):
        super().__init__(str(event))
        self.event = event


def _metric_leaves(metric):
    from ..metric import DeviceMetricAccumulator

    return DeviceMetricAccumulator._flatten(metric)


class ElasticController:
    """Compose a :class:`~mxnet_tpu.elastic.Checkpointer`, an optional
    :class:`~mxnet_tpu.parallel.health.FailureMonitor` and an optional
    :class:`~mxnet_tpu.elastic.FaultInjector` into the fit loop."""

    def __init__(self, checkpointer=None, monitor=None, injector=None,
                 poll_every=None):
        from .. import config as _config

        self.checkpointer = checkpointer
        self.monitor = monitor
        self.injector = injector
        self.poll_every = max(1, int(_config.get("MXNET_ELASTIC_POLL")
                                     if poll_every is None else poll_every))
        self.global_step = 0
        self.recoveries = 0
        self._resume_meta = None
        self._replay_epochs = 0   # cold resume: prior-epoch iterator replay
        self._metric = None
        self._full_contexts = None
        self._full_mesh_config = None

    # ------------------------------------------------------------------
    # fit wiring
    # ------------------------------------------------------------------
    def attach(self, module, eval_metric, begin_epoch):
        """Bind to the fitting module; auto-resume from the latest
        committed fence when the checkpointer allows it.  Returns the
        (possibly advanced) begin epoch."""
        from .. import profiler as _prof

        if getattr(module, "_exec_group", None) is None:
            raise MXNetError("elastic training needs a bound Module-style "
                             "driver (executor-group state is what the "
                             "fence snapshots)")
        self._metric = eval_metric
        # the FULL roster: regrow re-forms over these even after a shrink
        self._full_contexts = list(module._context)
        self._full_mesh_config = module._mesh_config
        ck = self.checkpointer
        if ck is None:
            return begin_epoch
        if not ck.resume and ck.latest() is not None:
            # refusing to mix lineages: with resume off, this run's
            # low-numbered fences would lose every restore/prune decision
            # to the previous run's higher step numbers — a mid-fit
            # recovery would silently splice the OLD run's params/RNG in
            raise MXNetError(
                "MXNET_CKPT_RESUME=0 but %s already holds committed "
                "checkpoints from a previous run; point MXNET_CKPT_DIR "
                "at a fresh directory (or clear this one) to start over"
                % ck.directory)
        if ck.resume:
            peeked = ck.peek()
            if peeked is not None and int(peeked["epoch"]) < begin_epoch:
                # restoring a mid-epoch-2 fence into a begin_epoch=5 run
                # would graft params/RNG onto an epoch no uninterrupted
                # run could pair them with — refuse rather than corrupt
                raise MXNetError(
                    "checkpoint in %s is at epoch %d, behind the "
                    "requested begin_epoch %d; clear the directory or "
                    "lower begin_epoch" % (ck.directory,
                                           int(peeked["epoch"]),
                                           begin_epoch))
            meta = ck.restore(module)
            if meta is not None:
                self.global_step = int(meta["global_step"])
                self._resume_meta = meta
                # cold resume: the training iterator is freshly built, so
                # its prior-epoch lifecycle must be replayed (roll_over
                # reset carries state) — unlike a mid-fit reconfigure,
                # whose iterator lived through those epochs already
                self._replay_epochs = int(meta["epoch"])
                self.recoveries += 1
                _prof.bump_recovery()
                from .. import obs as _obs

                _obs.instant("elastic_resume", cat="elastic",
                             args={"step": self.global_step,
                                   "epoch": int(meta["epoch"])})
                log.info("elastic resume: step %d (epoch %d, %d batches "
                         "into it) from %s", self.global_step,
                         meta["epoch"], meta["nbatch_done"], ck.directory)
                return max(begin_epoch, int(meta["epoch"]))
        if ck.latest() is None:
            # an initial fence so a failure before the first periodic one
            # still has a restore point (fresh params, step 0)
            ck.snapshot(module, self._meta(module, begin_epoch, 0))
        return begin_epoch

    def on_epoch_start(self, module, epoch, train_data, eval_metric):
        """Mid-epoch resume: restore metric sums to the fence values and
        fast-forward the (freshly reset) iterator.  Returns the batch
        index the epoch continues from (0 normally)."""
        meta, self._resume_meta = self._resume_meta, None
        if meta is None or int(meta["epoch"]) != epoch:
            return 0
        self._restore_metric(eval_metric, meta)
        # cold resume only, stateful-reset iterators only: replay the
        # fresh iterator's prior-epoch LIFECYCLE — reset() may depend on
        # the position earlier epochs reached (NDArrayIter roll_over
        # carries the tail cursor across reset), so each prior epoch is
        # drained and reset exactly as the uninterrupted run did before
        # the mid-epoch cursor is restored.  Stateless-reset iterators
        # (`reset_carries_state` False — pad/discard, RecordIO readers)
        # reproduce the same position from one reset + fast_forward, so
        # they skip the O(epochs x dataset) drain.  A mid-fit
        # reconfigure skips it too: its iterator lived through those
        # epochs already.
        replay, self._replay_epochs = self._replay_epochs, 0
        if not getattr(train_data, "reset_carries_state", False):
            replay = 0
        for _ in range(replay):
            try:
                while True:
                    train_data.next()
            except StopIteration:
                pass
            train_data.reset()
        # the fence's iterator-cursor record: batches the interrupted
        # epoch had consumed (== nbatch_done; kept under its own key so
        # richer iterator state can ride the same record later)
        n = int((meta.get("iterator") or {}).get("batches_done",
                                                 meta["nbatch_done"]))
        if n:
            if hasattr(train_data, "fast_forward"):
                train_data.fast_forward(n)
            else:
                for _ in range(n):
                    train_data.next()
        return n

    def on_step(self, module, epoch, nbatch, fences):
        """Once per dispatched step, on the loop thread."""
        self.global_step += 1
        step = self.global_step
        if self.injector is not None:
            # faults fire BEFORE this step's fence work: "killed at N"
            # means N's checkpoint never happened, like a real death
            self.injector.fire(step)
        ck = self.checkpointer
        if ck is not None:
            ck.note_step()
            if ck.period and step % ck.period == 0:
                ck.snapshot(module, self._meta(module, epoch, nbatch + 1))
        if self.monitor is not None and step % self.poll_every == 0:
            event = self.monitor.poll()
            if event is not None:
                self._drain(fences)
                raise ReconfigureSignal(event)

    def handle_reconfigure(self, module, signal, eval_metric):
        """Re-form the mesh on the survivors and restore the last fence.
        Returns the epoch to resume from."""
        from .. import profiler as _prof
        from ..parallel import mesh as mesh_mod

        if self.monitor is None:
            raise MXNetError("reconfiguration without a failure monitor")
        ck = self.checkpointer
        if ck is not None:
            ck.wait()
        event = signal.event
        num_workers = self.monitor.num_workers
        survivors = [r for r in range(num_workers)
                     if r not in set(event.dead)]
        devs, cfg = mesh_mod.survivor_submesh(
            self._full_contexts, num_workers, survivors,
            self._full_mesh_config)
        log.warning("elastic %s: dead=%s -> re-forming mesh on %d/%d "
                    "devices (data axis %d)", event.kind, event.dead,
                    len(devs), len(self._full_contexts), cfg.data)
        from .. import obs as _obs

        _obs.instant("elastic_" + event.kind, cat="elastic",
                     args={"dead": list(event.dead),
                           "devices": len(devs),
                           "data_axis": int(cfg.data)})
        module.reconfigure(devs, cfg if len(devs) > 1 else None)
        # the rebuilt fused step needs the metric re-armed
        module._bind_metric(eval_metric)
        self.recoveries += 1
        _prof.bump_recovery()
        if ck is None:
            raise MXNetError("reconfiguration without a checkpointer: the "
                             "re-formed mesh has no state to resume from")
        meta = ck.restore(module)
        if meta is None:
            raise MXNetError("no committed fence checkpoint in %s to "
                             "resume the re-formed mesh from"
                             % ck.directory)
        self.global_step = int(meta["global_step"])
        self._resume_meta = meta
        # the abandoned epoch's mid-stream reset() leaves stateful-reset
        # iterators (roll_over) at the fresh-construction position, NOT
        # at the epoch's true start — replay the lifecycle for them just
        # like a cold resume (stateless iterators skip it either way)
        self._replay_epochs = int(meta["epoch"])
        return int(meta["epoch"])

    def finish(self):
        """fit() teardown: join any in-flight write."""
        if self.checkpointer is not None:
            self.checkpointer.wait()

    # ------------------------------------------------------------------
    @staticmethod
    def _drain(fences):
        """Block until every dispatched step has completed (steps chain
        through donated params, so the newest fence covers all)."""
        if fences:
            import jax

            from .. import profiler as _prof
            import time

            t0 = time.perf_counter()
            jax.block_until_ready(fences[-1])
            _prof.record_host_wait(time.perf_counter() - t0)
            fences.clear()

    def _meta(self, module, epoch, nbatch_done):
        meta = {"epoch": int(epoch), "nbatch_done": int(nbatch_done),
                "global_step": int(self.global_step),
                "iterator": {"batches_done": int(nbatch_done)}}
        opt = getattr(module, "_optimizer", None)
        if opt is not None:
            # the optimizer's update counts drive Adam bias correction and
            # lr schedules: a mid-stream replay with t reset to 0 would
            # NOT be bit-identical
            meta["optimizer"] = {
                "num_update": int(opt.num_update),
                "begin_num_update": int(opt.begin_num_update),
                "index_update_count": {
                    str(k): int(v)
                    for k, v in opt._index_update_count.items()}}
        if self._metric is not None:
            # raw sums, NOT the draining properties — reading sum_metric
            # would force the device accumulator d2h onto the hot loop;
            # the device half rides the snapshot as array copies instead
            meta["metric_host"] = [
                {"sums": [float(s) for s in m._sums],
                 "counts": [float(c) for c in m._counts]}
                for m in _metric_leaves(self._metric)]
        return meta

    @staticmethod
    def _restore_metric(metric, meta):
        host = meta.get("metric_host")
        if host is None or metric is None:
            return
        leaves = _metric_leaves(metric)
        if len(leaves) != len(host):
            log.warning("checkpointed metric has %d leaves, live metric "
                        "%d; skipping metric restore", len(host),
                        len(leaves))
            return
        dev = meta.get("metric_device") or [None] * len(leaves)
        for m, h, d in zip(leaves, host, dev):
            sums = [float(x) for x in h["sums"]]
            counts = [float(x) for x in h["counts"]]
            if d:
                # fold the fence's pending device sums exactly as a drain
                # would have (same additions, same order)
                sums = [s + float(ds) for s, ds in zip(sums, d[0])]
                counts = [c + float(dc) for c, dc in zip(counts, d[1])]
            m._sums = sums
            m._counts = [int(c) if float(c).is_integer() else c
                         for c in counts]


def from_env():
    """An :class:`ElasticController` from the environment knobs, or None.

    ``MXNET_CKPT_DIR`` + ``MXNET_CKPT_PERIOD`` arm fit-integrated fenced
    checkpointing with auto-resume; liveness monitoring stays explicit
    (construct a FailureMonitor and pass a controller) because only the
    launcher knows the worker roster."""
    from .. import config as _config

    directory = _config.get("MXNET_CKPT_DIR")
    if not directory or not int(_config.get("MXNET_CKPT_PERIOD")):
        return None
    from .checkpointer import Checkpointer

    return ElasticController(checkpointer=Checkpointer(directory))
