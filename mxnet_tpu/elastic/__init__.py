"""Elastic training: async fenced checkpointing, liveness-driven mesh
shrink/regrow, and deterministic resume.

The reference's recovery contract is "ps-lite heartbeats detect the dead
worker, a human resumes from the last epoch checkpoint"
(``parallel/health.py``; SURVEY §5).  At production scale preemptions are
routine, so this subsystem makes recovery automatic, cheap and exact:

* **Async fenced checkpointing** (:class:`Checkpointer`): at a step fence
  the donated params/slots/aux chain is snapshotted with device-side
  copies — async dispatches that ride the in-flight step machinery, so
  ``fit()`` keeps dispatching — and a background writer thread lands the
  shards as a committed orbax step directory (at most one write in
  flight; crash-safe commit ordering via ``checkpoint.commit_step``).
* **Deterministic resume**: the checkpoint carries epoch/step, the RNG
  key chain, metric accumulator sums and the iterator cursor, so a
  killed-and-restarted ``fit()`` replays to bit-identical params vs an
  uninterrupted run (Check-Freq's decoupled-snapshot plan, taken to
  exact-replay).
* **Liveness protocol** (:class:`~mxnet_tpu.parallel.health.FailureMonitor`
  + :class:`ElasticController`): a heartbeat-declared dead rank raises a
  reconfiguration at the next fence; the loop drains in-flight steps,
  re-forms the mesh on the survivors' devices (the 'data' axis shrinks,
  per-replica batch rescales, global batch unchanged), restores the last
  fence checkpoint re-sharded onto the new mesh, and continues — regrow
  runs the same path when the worker returns.

Wiring: pass an :class:`ElasticController` to ``fit(..., elastic=...)``,
or set ``MXNET_CKPT_DIR`` + ``MXNET_CKPT_PERIOD`` and ``fit`` arms one
itself (:func:`from_env`).  :class:`FaultInjector` drives all of it
deterministically in tests (kill at step N, stale heartbeat, torn
write).  See docs/elasticity.md.
"""
from ..parallel.health import FailureMonitor, ReconfigEvent
from .checkpointer import Checkpointer
from .controller import ElasticController, ReconfigureSignal, from_env
from .fault import FaultInjector, WorkerKilled

__all__ = ["Checkpointer", "ElasticController", "ReconfigureSignal",
           "FailureMonitor", "ReconfigEvent", "FaultInjector",
           "WorkerKilled", "from_env"]
