"""Contrib namespace (reference: python/mxnet/contrib/).

`contrib.autograd` re-exports the core tape (the reference keeps autograd in
contrib at v0.9.5); detection/CTC ops register via `mxnet_tpu.contrib.ops`.
"""
from .. import autograd  # contrib.autograd API lives in core autograd
from . import tensorboard
