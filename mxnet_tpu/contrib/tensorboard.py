"""TensorBoard logging bridge (reference: python/mxnet/contrib/tensorboard.py)."""
from __future__ import annotations


class LogMetricsCallback(object):
    """Log metrics to a TensorBoard event file each batch (requires a
    SummaryWriter implementation, e.g. torch.utils.tensorboard)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        try:
            from torch.utils.tensorboard import SummaryWriter

            self.summary_writer = SummaryWriter(logging_dir)
        except ImportError:
            raise ImportError("LogMetricsCallback requires a SummaryWriter "
                              "backend (torch.utils.tensorboard)")
        self.step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        name_value = param.eval_metric.get_name_value()
        for name, value in name_value:
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)
