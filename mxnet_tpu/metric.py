"""Evaluation metrics.

API-parity surface of the reference's ``python/mxnet/metric.py`` (class
names, constructor signatures, ``get``/``get_name_value`` protocol), built
around a different core: every metric reduces one (label, pred) pair to a
``(statistic_sum, instance_count)`` tuple in a single vectorized numpy
expression (``_batch``), and the base class owns pairing, accumulation and
reporting.  No per-row Python loops — metric cost stays negligible next to
the compiled step even for large batches.

Device-side accumulation: metrics that additionally implement
``device_batch`` (the jax.numpy mirror of ``_batch``) can accumulate INSIDE
the donated train-step program — the per-step device→host output transfer
of the classic loop disappears, and the host only syncs the two-scalar
accumulator at ``MXNET_METRIC_SYNC_PERIOD`` boundaries.  The reference
routed metric reads through the same dependency engine as ops; here the
accumulator is literally part of the step's donated state.  See
``DeviceMetricAccumulator`` for the protocol the module drivers use.
"""
from __future__ import annotations

import numpy as np

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "Perplexity",
           "MAE", "MSE", "RMSE", "CrossEntropy", "Loss", "Torch", "Caffe",
           "CustomMetric", "CompositeEvalMetric", "np_metric", "create",
           "DeviceMetricAccumulator", "select_outputs"]


def select_outputs(metric, outputs):
    """The output heads ``metric`` consumes: ``metric.output_indices`` when
    set, else all of them.  The module drivers route every metric through
    this so unnamed heads are never materialized on the host."""
    idxs = getattr(metric, "output_indices", None)
    if idxs is None:
        return outputs
    return [outputs[i] for i in idxs]


def _host(x):
    """Materialize an NDArray / jax array / numpy array on the host.

    Every call on a non-numpy input is a device→host transfer; the profiler
    counts them so the bench can report ``host_syncs_per_step`` (the number
    device-side accumulation exists to drive to ~0)."""
    if isinstance(x, np.ndarray):
        return x
    from . import profiler as _prof

    _prof.bump_metric_d2h()
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return np.asarray(x)


def check_label_shapes(labels, preds, shape=0):
    la = len(labels) if shape == 0 else labels.shape
    pr = len(preds) if shape == 0 else preds.shape
    if la != pr:
        raise ValueError(
            "Shape of labels %s does not match shape of predictions %s"
            % (la, pr))


class EvalMetric:
    """Accumulating metric base.  Subclasses implement ``_batch(label,
    pred) -> (sum, count)`` over host arrays; everything else lives here.

    Subclasses may ALSO implement ``device_batch(label, pred)`` — the same
    reduction written in jax.numpy over device arrays — to opt into
    device-side accumulation inside compiled train steps.  A bound device
    accumulator is drained lazily: ``get()``/``get_name_value()`` (and the
    ``sum_metric``/``num_inst`` views) first fold any pending device state
    into the host sums, so callbacks keep working unchanged — reading the
    metric IS the sync point.
    """

    # jax.numpy mirror of _batch; None = host-only metric
    device_batch = None

    # Which output heads this metric consumes (e.g. ``metric.output_indices
    # = [0]`` on a multi-head Group symbol).  None = all heads.  The module
    # drivers slice the output list BEFORE handing it over, so unused heads
    # are never materialized on the host.
    output_indices = None

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self._device_sync = None    # drain pending device state -> host
        self._device_reset = None   # zero device state without draining
        self.reset()

    def reset(self):
        hook = getattr(self, "_device_reset", None)
        if hook is not None:
            hook()
        n = 1 if self.num is None else self.num
        self._sums = [0.0] * n
        self._counts = [0] * n

    def _drain_device(self):
        hook = getattr(self, "_device_sync", None)
        if hook is not None:
            hook()

    # ------------------------------------------------------------------
    # device-accumulation protocol (DeviceMetricAccumulator drives this)
    # ------------------------------------------------------------------
    def device_supported(self):
        """Whether this metric can accumulate inside a compiled step."""
        return self.device_batch is not None

    def device_update(self, sums, counts, labels, preds):
        """Traceable mirror of ``update``: fold one batch of device arrays
        into per-slot accumulator lists IN PLACE.  Default pairing matches
        ``update`` (zip labels with preds); metrics with different pairing
        semantics (Loss) override this instead of ``device_batch``."""
        if self.device_batch is None:
            raise NotImplementedError("%s has no device_batch"
                                      % type(self).__name__)
        check_label_shapes(labels, preds)
        for slot, (label, pred) in enumerate(zip(labels, preds)):
            s, n = self.device_batch(label, pred)
            idx = 0 if self.num is None else slot
            sums[idx] = sums[idx] + s
            counts[idx] = counts[idx] + n

    # reference-compatible attribute views (Module/callbacks poke these)
    @property
    def sum_metric(self):
        self._drain_device()
        return self._sums[0] if self.num is None else self._sums

    @sum_metric.setter
    def sum_metric(self, v):
        if self.num is None:
            self._sums[0] = v
        else:
            self._sums = list(v)

    @property
    def num_inst(self):
        self._drain_device()
        return self._counts[0] if self.num is None else self._counts

    @num_inst.setter
    def num_inst(self, v):
        if self.num is None:
            self._counts[0] = v
        else:
            self._counts = list(v)

    def _batch(self, label, pred):
        raise NotImplementedError()

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for slot, (label, pred) in enumerate(zip(labels, preds)):
            s, n = self._batch(_host(label), _host(pred))
            idx = 0 if self.num is None else slot
            self._sums[idx] += s
            self._counts[idx] += n

    def get(self):
        self._drain_device()

        def ratio(s, n):
            return s / n if n != 0 else float("nan")

        if self.num is None:
            return (self.name, ratio(self._sums[0], self._counts[0]))
        return (["%s_%d" % (self.name, i) for i in range(self.num)],
                [ratio(s, n) for s, n in zip(self._sums, self._counts)])

    def get_name_value(self):
        names, values = self.get()
        if not isinstance(names, list):
            names, values = [names], [values]
        return list(zip(names, values))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


class Accuracy(EvalMetric):
    """Fraction of correctly classified instances."""

    def __init__(self, axis=1):
        super().__init__("accuracy")
        self.axis = axis

    def _batch(self, label, pred):
        hard = pred if pred.shape == label.shape \
            else np.argmax(pred, axis=self.axis)
        check_label_shapes(label, hard, shape=1)
        eq = hard.astype("int64").ravel() == label.astype("int64").ravel()
        return int(eq.sum()), eq.size

    def device_batch(self, label, pred):
        import jax.numpy as jnp

        hard = pred if pred.shape == label.shape \
            else jnp.argmax(pred, axis=self.axis)
        check_label_shapes(label, hard, shape=1)
        eq = hard.astype(jnp.int32).ravel() == label.astype(jnp.int32).ravel()
        return eq.sum(), eq.size


class TopKAccuracy(EvalMetric):
    """Label-in-top-k accuracy.  Uses an O(n) partial partition of the
    class axis rather than a full sort."""

    def __init__(self, top_k=1):
        assert top_k > 1, "use Accuracy for top_k <= 1"
        super().__init__("top_k_accuracy_%d" % top_k)
        self.top_k = top_k

    def _batch(self, label, pred):
        assert pred.ndim <= 2, "predictions must be at most (batch, classes)"
        if pred.ndim == 1:  # already-hard class ids
            eq = pred.astype("int64") == label.astype("int64").ravel()
            return int(eq.sum()), eq.size
        k = min(self.top_k, pred.shape[1])
        topk = np.argpartition(pred, -k, axis=1)[:, -k:]
        hits = (topk == label.astype("int64")[:, None]).any(axis=1)
        return int(hits.sum()), hits.size

    def device_batch(self, label, pred):
        import jax
        import jax.numpy as jnp

        assert pred.ndim <= 2, "predictions must be at most (batch, classes)"
        if pred.ndim == 1:
            eq = pred.astype(jnp.int32) == label.astype(jnp.int32).ravel()
            return eq.sum(), eq.size
        k = min(self.top_k, pred.shape[1])
        _, topk = jax.lax.top_k(pred, k)
        hits = (topk == label.astype(jnp.int32)[:, None]).any(axis=1)
        return hits.sum(), hits.size


class F1(EvalMetric):
    """Binary F1 from a vectorized confusion-matrix count per batch."""

    def __init__(self):
        super().__init__("f1")

    def _batch(self, label, pred):
        y = label.astype("int64").ravel()
        if np.unique(y).size > 2:
            raise ValueError("F1 currently only supports binary classification.")
        yhat = np.argmax(pred, axis=1).ravel()
        tp = int(np.sum((yhat == 1) & (y == 1)))
        fp = int(np.sum((yhat == 1) & (y == 0)))
        fn = int(np.sum((yhat == 0) & (y == 1)))
        precision = tp / (tp + fp) if tp + fp else 0.0
        recall = tp / (tp + fn) if tp + fn else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if precision + recall else 0.0
        return f1, 1


class Perplexity(EvalMetric):
    """exp(mean negative log-prob of the target tokens)."""

    def __init__(self, ignore_label, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def _batch(self, label, pred):
        flat = pred.reshape(-1, pred.shape[self.axis])
        ids = label.astype("int64").ravel()
        assert ids.size == flat.shape[0], \
            "shape mismatch: %s vs. %s" % (label.shape, pred.shape)
        p = np.take_along_axis(flat, ids[:, None], axis=1)[:, 0]
        keep = np.ones_like(p, dtype=bool) if self.ignore_label is None \
            else ids != self.ignore_label
        nll = -np.log(np.maximum(p[keep], 1e-10)).sum()
        count = int(keep.sum())
        return float(np.exp(nll / count)) if count else float("nan"), 1

    def device_batch(self, label, pred):
        import jax.numpy as jnp

        flat = pred.reshape(-1, pred.shape[self.axis])
        ids = label.astype(jnp.int32).ravel()
        p = jnp.take_along_axis(flat, ids[:, None], axis=1)[:, 0]
        keep = jnp.ones_like(p, dtype=bool) if self.ignore_label is None \
            else ids != self.ignore_label
        nll = -(jnp.log(jnp.maximum(p, 1e-10)) * keep).sum()
        count = keep.sum()
        stat = jnp.where(count > 0, jnp.exp(nll / jnp.maximum(count, 1)),
                         jnp.nan)
        return stat, 1


class _Regression(EvalMetric):
    """Shared shape handling for elementwise regression errors."""

    def _batch(self, label, pred):
        if label.ndim == 1:
            label = label[:, None]
        return float(self._error(label, pred)), 1

    def device_batch(self, label, pred):
        import jax.numpy as jnp

        if label.ndim == 1:
            label = label[:, None]
        return self._error_ops(jnp)(label, pred), 1


class MAE(_Regression):
    def __init__(self):
        super().__init__("mae")

    @staticmethod
    def _error(label, pred):
        return np.mean(np.abs(label - pred))

    @staticmethod
    def _error_ops(xp):
        return lambda label, pred: xp.mean(xp.abs(label - pred))


class MSE(_Regression):
    def __init__(self):
        super().__init__("mse")

    @staticmethod
    def _error(label, pred):
        return np.mean(np.square(label - pred))

    @staticmethod
    def _error_ops(xp):
        return lambda label, pred: xp.mean(xp.square(label - pred))


class RMSE(_Regression):
    def __init__(self):
        super().__init__("rmse")

    @staticmethod
    def _error(label, pred):
        return np.sqrt(np.mean(np.square(label - pred)))

    @staticmethod
    def _error_ops(xp):
        return lambda label, pred: xp.sqrt(xp.mean(xp.square(label - pred)))


class CrossEntropy(EvalMetric):
    """Mean negative log predicted probability of the true class."""

    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def _batch(self, label, pred):
        ids = label.astype("int64").ravel()
        assert ids.size == pred.shape[0]
        p = np.take_along_axis(pred, ids[:, None], axis=1)[:, 0]
        return float(-np.log(p + self.eps).sum()), ids.size

    def device_batch(self, label, pred):
        import jax.numpy as jnp

        ids = label.astype(jnp.int32).ravel()
        assert ids.size == pred.shape[0]
        p = jnp.take_along_axis(pred, ids[:, None], axis=1)[:, 0]
        return -jnp.log(p + self.eps).sum(), ids.size


class Loss(EvalMetric):
    """Mean of raw outputs (MakeLoss-style nets); ignores labels."""

    def __init__(self):
        super().__init__("loss")

    def update(self, _, preds):
        for pred in preds:
            arr = _host(pred)
            self._sums[0] += float(arr.sum())
            self._counts[0] += arr.size

    def device_supported(self):
        return True

    def device_update(self, sums, counts, labels, preds):
        # same pairing as update(): every output head, labels ignored
        for pred in preds:
            sums[0] = sums[0] + pred.sum()
            counts[0] = counts[0] + pred.size


class Torch(Loss):
    def __init__(self, name="torch"):
        EvalMetric.__init__(self, name)


class Caffe(Loss):
    def __init__(self, name="caffe"):
        EvalMetric.__init__(self, name)


class CustomMetric(EvalMetric):
    """Wrap a ``feval(label, pred)`` numpy function.  feval may return a
    scalar (counted as one instance) or a (sum, count) pair."""

    def __init__(self, feval, name=None, allow_extra_outputs=False):
        if name is None:
            name = feval.__name__
            if "<" in name:
                name = "custom(%s)" % name
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            result = self._feval(_host(label), _host(pred))
            s, n = result if isinstance(result, tuple) else (result, 1)
            self._sums[0] += s
            self._counts[0] += n


class CompositeEvalMetric(EvalMetric):
    """Fan one update out to several child metrics."""

    def __init__(self, metrics=None, **kwargs):
        super().__init__("composite", **kwargs)
        self.metrics = []
        for m in metrics or []:
            self.add(m)

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str)
                            else metric)

    def get_metric(self, index):
        if not 0 <= index < len(self.metrics):
            raise ValueError("Metric index {} is out of range 0 and {}"
                             .format(index, len(self.metrics)))
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            # per-child head selection, mirroring the device accumulator's
            # per-leaf select_outputs so host and device paths agree
            m.update(labels, select_outputs(m, preds))

    def device_supported(self):
        # composite-level output_indices is applied by the drivers BEFORE
        # the update call on the host path; the flattened device
        # accumulator can't reproduce that nesting, so such composites
        # stay on the host path
        return bool(self.metrics) and self.output_indices is None and \
            all(m.device_supported() for m in self.metrics)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        pairs = [m.get() for m in self.metrics]
        return [p[0] for p in pairs], [p[1] for p in pairs]


class DeviceMetricAccumulator:
    """Bridge between an ``EvalMetric`` and donated on-device accumulator
    state inside a compiled train step.

    The owner (``CompiledTrainStep`` / ``PipelineModule``) threads
    ``self.state`` — a pytree of per-slot ``(sum, count)`` scalars — through
    its jitted program as extra DONATED state, calling :meth:`update` inside
    the trace.  :meth:`install` binds drain/reset hooks onto the metric so
    reading it (``get``/``get_name_value``/``sum_metric``) lazily folds the
    device accumulators into the host sums — reading the metric is the sync
    point, exactly the reference's engine-mediated ``WaitToRead`` on a
    metric variable.
    """

    def __init__(self, metric):
        self.metric = metric
        self._leaves = self._flatten(metric)
        bad = [type(m).__name__ for m in self._leaves
               if not m.device_supported()]
        if bad or not self._leaves:
            raise ValueError("metric(s) %s cannot accumulate on device"
                             % (bad or metric))
        self.state = None
        self.dirty = False  # anything accumulated since the last drain?

    @staticmethod
    def _flatten(metric):
        if isinstance(metric, CompositeEvalMetric):
            out = []
            for m in metric.metrics:
                out.extend(DeviceMetricAccumulator._flatten(m))
            return out
        return [metric]

    @staticmethod
    def supported(metric):
        """Whether every leaf of ``metric`` implements the device protocol."""
        try:
            return bool(metric.device_supported())
        except Exception:
            return False

    def _zeros(self):
        import jax.numpy as jnp

        # strong dtypes (x64-aware) so the scalars stay donatable
        fdt = jnp.asarray(0.0).dtype
        idt = jnp.asarray(0).dtype
        state = []
        for m in self._leaves:
            n = 1 if m.num is None else m.num
            state.append((tuple(jnp.zeros((), fdt) for _ in range(n)),
                          tuple(jnp.zeros((), idt) for _ in range(n))))
        return tuple(state)

    # ------------------------------------------------------------------
    def update(self, state, labels, preds):
        """Traceable: fold one batch (device arrays) into the state pytree."""
        new = []
        for (sums, counts), m in zip(state, self._leaves):
            s, c = list(sums), list(counts)
            m.device_update(s, c, labels, select_outputs(m, preds))
            new.append((tuple(s), tuple(c)))
        return tuple(new)

    # ------------------------------------------------------------------
    def install(self):
        """Arm device accumulation: zero state + bind the metric hooks."""
        if self.state is None:
            self.state = self._zeros()
        for m in self._leaves:
            m._device_sync = self.drain
            m._device_reset = self.reset_device

    def uninstall(self):
        """Drain what's pending and detach the hooks (fused→eager handoff,
        monitor installation, end of fit)."""
        self.drain()
        for m in self._leaves:
            m._device_sync = None
            m._device_reset = None
        self.state = None

    def commit(self, state):
        """Store the step program's returned accumulator state."""
        self.state = state
        self.dirty = True

    def maybe_drain(self, num_steps):
        """Periodic-drain policy: sync every ``MXNET_METRIC_SYNC_PERIOD``
        steps (0 = only at boundaries).  The module drivers call this from
        ``update_metric`` once per step."""
        from . import config as _config

        period = _config.get("MXNET_METRIC_SYNC_PERIOD")
        if period and num_steps % int(period) == 0:
            self.drain()

    def drain(self):
        """Fold pending device accumulators into the host metric sums and
        zero the device state — the loop's only metric device→host sync.
        A clean accumulator (nothing since the last drain) costs nothing."""
        if self.state is None or not self.dirty:
            return
        import jax

        from . import profiler as _prof

        state, self.state = self.state, None  # re-entrancy guard
        self.dirty = False
        host = jax.device_get(state)  # ONE batched transfer, not per-scalar
        moved = 0
        for (sums, counts), m in zip(host, self._leaves):
            for idx, (s, c) in enumerate(zip(sums, counts)):
                m._sums[idx] += float(s)
                m._counts[idx] += int(c)
                moved += 2
        _prof.bump_metric_d2h(moved)
        _prof.bump_metric_sync()
        self.state = self._zeros()

    def reset_device(self):
        """Zero the device accumulators WITHOUT folding (metric.reset)."""
        if self.state is not None:
            self.state = self._zeros()
        self.dirty = False


def np_metric(name=None, allow_extra_outputs=False):
    """Decorator turning a numpy feval into a CustomMetric."""

    def wrap(numpy_feval):
        return CustomMetric(numpy_feval, name or numpy_feval.__name__,
                            allow_extra_outputs)

    return wrap


_BY_NAME = {
    "acc": Accuracy, "accuracy": Accuracy,
    "ce": CrossEntropy, "cross-entropy": CrossEntropy,
    "f1": F1, "mae": MAE, "mse": MSE, "rmse": RMSE,
    "top_k_accuracy": TopKAccuracy, "topkaccuracy": TopKAccuracy,
    "loss": Loss, "torch": Torch, "caffe": Caffe, "perplexity": Perplexity,
}


def create(metric, **kwargs):
    """Resolve a metric from a name, callable, list, or instance."""
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, list):
        out = CompositeEvalMetric()
        for m in metric:
            out.add(create(m, **kwargs))
        return out
    klass = _BY_NAME.get(str(metric).lower())
    if klass is None:
        raise ValueError("Metric must be either callable or in {}"
                         .format(sorted(_BY_NAME)))
    return klass(**kwargs)
