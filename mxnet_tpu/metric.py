"""Evaluation metrics.

API-parity surface of the reference's ``python/mxnet/metric.py`` (class
names, constructor signatures, ``get``/``get_name_value`` protocol), built
around a different core: every metric reduces one (label, pred) pair to a
``(statistic_sum, instance_count)`` tuple in a single vectorized numpy
expression (``_batch``), and the base class owns pairing, accumulation and
reporting.  No per-row Python loops — metric cost stays negligible next to
the compiled step even for large batches.
"""
from __future__ import annotations

import numpy as np

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "Perplexity",
           "MAE", "MSE", "RMSE", "CrossEntropy", "Loss", "Torch", "Caffe",
           "CustomMetric", "CompositeEvalMetric", "np_metric", "create"]


def _host(x):
    """Materialize an NDArray / jax array / numpy array on the host."""
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return np.asarray(x)


def check_label_shapes(labels, preds, shape=0):
    la = len(labels) if shape == 0 else labels.shape
    pr = len(preds) if shape == 0 else preds.shape
    if la != pr:
        raise ValueError(
            "Shape of labels %s does not match shape of predictions %s"
            % (la, pr))


class EvalMetric:
    """Accumulating metric base.  Subclasses implement ``_batch(label,
    pred) -> (sum, count)`` over host arrays; everything else lives here."""

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self.reset()

    def reset(self):
        n = 1 if self.num is None else self.num
        self._sums = [0.0] * n
        self._counts = [0] * n

    # reference-compatible attribute views (Module/callbacks poke these)
    @property
    def sum_metric(self):
        return self._sums[0] if self.num is None else self._sums

    @sum_metric.setter
    def sum_metric(self, v):
        if self.num is None:
            self._sums[0] = v
        else:
            self._sums = list(v)

    @property
    def num_inst(self):
        return self._counts[0] if self.num is None else self._counts

    @num_inst.setter
    def num_inst(self, v):
        if self.num is None:
            self._counts[0] = v
        else:
            self._counts = list(v)

    def _batch(self, label, pred):
        raise NotImplementedError()

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for slot, (label, pred) in enumerate(zip(labels, preds)):
            s, n = self._batch(_host(label), _host(pred))
            idx = 0 if self.num is None else slot
            self._sums[idx] += s
            self._counts[idx] += n

    def get(self):
        def ratio(s, n):
            return s / n if n != 0 else float("nan")

        if self.num is None:
            return (self.name, ratio(self._sums[0], self._counts[0]))
        return (["%s_%d" % (self.name, i) for i in range(self.num)],
                [ratio(s, n) for s, n in zip(self._sums, self._counts)])

    def get_name_value(self):
        names, values = self.get()
        if not isinstance(names, list):
            names, values = [names], [values]
        return list(zip(names, values))

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


class Accuracy(EvalMetric):
    """Fraction of correctly classified instances."""

    def __init__(self, axis=1):
        super().__init__("accuracy")
        self.axis = axis

    def _batch(self, label, pred):
        hard = pred if pred.shape == label.shape \
            else np.argmax(pred, axis=self.axis)
        check_label_shapes(label, hard, shape=1)
        eq = hard.astype("int64").ravel() == label.astype("int64").ravel()
        return int(eq.sum()), eq.size


class TopKAccuracy(EvalMetric):
    """Label-in-top-k accuracy.  Uses an O(n) partial partition of the
    class axis rather than a full sort."""

    def __init__(self, top_k=1):
        assert top_k > 1, "use Accuracy for top_k <= 1"
        super().__init__("top_k_accuracy_%d" % top_k)
        self.top_k = top_k

    def _batch(self, label, pred):
        assert pred.ndim <= 2, "predictions must be at most (batch, classes)"
        if pred.ndim == 1:  # already-hard class ids
            eq = pred.astype("int64") == label.astype("int64").ravel()
            return int(eq.sum()), eq.size
        k = min(self.top_k, pred.shape[1])
        topk = np.argpartition(pred, -k, axis=1)[:, -k:]
        hits = (topk == label.astype("int64")[:, None]).any(axis=1)
        return int(hits.sum()), hits.size


class F1(EvalMetric):
    """Binary F1 from a vectorized confusion-matrix count per batch."""

    def __init__(self):
        super().__init__("f1")

    def _batch(self, label, pred):
        y = label.astype("int64").ravel()
        if np.unique(y).size > 2:
            raise ValueError("F1 currently only supports binary classification.")
        yhat = np.argmax(pred, axis=1).ravel()
        tp = int(np.sum((yhat == 1) & (y == 1)))
        fp = int(np.sum((yhat == 1) & (y == 0)))
        fn = int(np.sum((yhat == 0) & (y == 1)))
        precision = tp / (tp + fp) if tp + fp else 0.0
        recall = tp / (tp + fn) if tp + fn else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if precision + recall else 0.0
        return f1, 1


class Perplexity(EvalMetric):
    """exp(mean negative log-prob of the target tokens)."""

    def __init__(self, ignore_label, axis=-1):
        super().__init__("Perplexity")
        self.ignore_label = ignore_label
        self.axis = axis

    def _batch(self, label, pred):
        flat = pred.reshape(-1, pred.shape[self.axis])
        ids = label.astype("int64").ravel()
        assert ids.size == flat.shape[0], \
            "shape mismatch: %s vs. %s" % (label.shape, pred.shape)
        p = np.take_along_axis(flat, ids[:, None], axis=1)[:, 0]
        keep = np.ones_like(p, dtype=bool) if self.ignore_label is None \
            else ids != self.ignore_label
        nll = -np.log(np.maximum(p[keep], 1e-10)).sum()
        count = int(keep.sum())
        return float(np.exp(nll / count)) if count else float("nan"), 1


class _Regression(EvalMetric):
    """Shared shape handling for elementwise regression errors."""

    def _batch(self, label, pred):
        if label.ndim == 1:
            label = label[:, None]
        return float(self._error(label, pred)), 1


class MAE(_Regression):
    def __init__(self):
        super().__init__("mae")

    @staticmethod
    def _error(label, pred):
        return np.mean(np.abs(label - pred))


class MSE(_Regression):
    def __init__(self):
        super().__init__("mse")

    @staticmethod
    def _error(label, pred):
        return np.mean(np.square(label - pred))


class RMSE(_Regression):
    def __init__(self):
        super().__init__("rmse")

    @staticmethod
    def _error(label, pred):
        return np.sqrt(np.mean(np.square(label - pred)))


class CrossEntropy(EvalMetric):
    """Mean negative log predicted probability of the true class."""

    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def _batch(self, label, pred):
        ids = label.astype("int64").ravel()
        assert ids.size == pred.shape[0]
        p = np.take_along_axis(pred, ids[:, None], axis=1)[:, 0]
        return float(-np.log(p + self.eps).sum()), ids.size


class Loss(EvalMetric):
    """Mean of raw outputs (MakeLoss-style nets); ignores labels."""

    def __init__(self):
        super().__init__("loss")

    def update(self, _, preds):
        for pred in preds:
            arr = _host(pred)
            self._sums[0] += float(arr.sum())
            self._counts[0] += arr.size


class Torch(Loss):
    def __init__(self, name="torch"):
        EvalMetric.__init__(self, name)


class Caffe(Loss):
    def __init__(self, name="caffe"):
        EvalMetric.__init__(self, name)


class CustomMetric(EvalMetric):
    """Wrap a ``feval(label, pred)`` numpy function.  feval may return a
    scalar (counted as one instance) or a (sum, count) pair."""

    def __init__(self, feval, name=None, allow_extra_outputs=False):
        if name is None:
            name = feval.__name__
            if "<" in name:
                name = "custom(%s)" % name
        super().__init__(name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            result = self._feval(_host(label), _host(pred))
            s, n = result if isinstance(result, tuple) else (result, 1)
            self._sums[0] += s
            self._counts[0] += n


class CompositeEvalMetric(EvalMetric):
    """Fan one update out to several child metrics."""

    def __init__(self, metrics=None, **kwargs):
        super().__init__("composite", **kwargs)
        self.metrics = []
        for m in metrics or []:
            self.add(m)

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str)
                            else metric)

    def get_metric(self, index):
        if not 0 <= index < len(self.metrics):
            raise ValueError("Metric index {} is out of range 0 and {}"
                             .format(index, len(self.metrics)))
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        pairs = [m.get() for m in self.metrics]
        return [p[0] for p in pairs], [p[1] for p in pairs]


def np_metric(name=None, allow_extra_outputs=False):
    """Decorator turning a numpy feval into a CustomMetric."""

    def wrap(numpy_feval):
        return CustomMetric(numpy_feval, name or numpy_feval.__name__,
                            allow_extra_outputs)

    return wrap


_BY_NAME = {
    "acc": Accuracy, "accuracy": Accuracy,
    "ce": CrossEntropy, "cross-entropy": CrossEntropy,
    "f1": F1, "mae": MAE, "mse": MSE, "rmse": RMSE,
    "top_k_accuracy": TopKAccuracy, "topkaccuracy": TopKAccuracy,
    "loss": Loss, "torch": Torch, "caffe": Caffe, "perplexity": Perplexity,
}


def create(metric, **kwargs):
    """Resolve a metric from a name, callable, list, or instance."""
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, list):
        out = CompositeEvalMetric()
        for m in metric:
            out.add(create(m, **kwargs))
        return out
    klass = _BY_NAME.get(str(metric).lower())
    if klass is None:
        raise ValueError("Metric must be either callable or in {}"
                         .format(sorted(_BY_NAME)))
    return klass(**kwargs)
