"""Device context.

Reference: `/root/reference/python/mxnet/context.py` and
`include/mxnet/base.h` (Context struct).  TPU-native redesign: a Context is a
named handle onto a JAX device.  ``mx.cpu(i)`` maps to host (XLA-CPU)
devices; ``mx.tpu(i)`` maps to TPU chips.  ``mx.gpu(i)`` is accepted as an
alias for ``tpu`` so reference-era scripts run unchanged — on this framework
the accelerator is a TPU.

Device ids beyond the number of physical devices wrap around (the reference
uses fake `mx.cpu(N)` contexts to test model parallelism on one box —
tests/python/unittest/test_multi_device_exec.py:20 — and we keep that trick:
distinct contexts remain distinct keys for placement even when they share
hardware).
"""
from __future__ import annotations

import threading

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_devices"]

_devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
_devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}


class Context:
    """A device context (reference: python/mxnet/context.py:8-88)."""

    _state = threading.local()
    devtype2str = _devtype2str
    devstr2type = _devstr2type

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = _devstr2type[device_type]
            self.device_id = device_id

    @property
    def device_type(self):
        return _devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    # -- JAX mapping ------------------------------------------------------
    @property
    def jax_device(self):
        """The concrete ``jax.Device`` this context maps onto.

        Contexts address this process's devices: under multi-process
        (jax.distributed) only local devices are addressable, so the lookup
        is over ``local_devices`` — matching the reference, where each
        worker's ``mx.gpu(i)`` is a local ordinal.
        """
        import jax

        multiproc = jax.process_count() > 1
        if self.device_type in ("cpu", "cpu_pinned"):
            try:
                devs = jax.local_devices(backend="cpu") if multiproc \
                    else jax.devices("cpu")
            except RuntimeError:
                devs = jax.local_devices() if multiproc else jax.devices()
        else:  # gpu / tpu → accelerator platform, fall back to default
            devs = _accelerator_devices(local=multiproc)
        return devs[self.device_id % len(devs)]

    def __enter__(self):
        if not hasattr(Context._state, "stack"):
            Context._state.stack = []
        Context._state.stack.append(self)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        Context._state.stack.pop()


def _accelerator_devices(local=False):
    """TPU devices, else whatever the default platform offers (CPU in tests)."""
    import jax

    lister = jax.local_devices if local else jax.devices
    for plat in ("tpu", "axon"):
        try:
            devs = (lister(backend=plat) if local else lister(plat))
            if devs:
                return devs
        except RuntimeError:
            continue
    return lister()


def cpu(device_id=0):
    """Return a CPU context (reference: context.py:90)."""
    return Context("cpu", device_id)


def gpu(device_id=0):
    """Accelerator context; on this framework 'gpu' means a TPU chip."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """Return a TPU context."""
    return Context("tpu", device_id)


def num_devices(device_type="tpu"):
    import jax

    if device_type in ("cpu", "cpu_pinned"):
        try:
            return len(jax.devices("cpu"))
        except RuntimeError:
            return len(jax.devices())
    return len(_accelerator_devices())


def current_context():
    """The default context (reference: context.py:103)."""
    if not hasattr(Context._state, "stack") or not Context._state.stack:
        return Context("cpu", 0)
    return Context._state.stack[-1]
