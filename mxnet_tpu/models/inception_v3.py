"""Inception-v3 symbol generator.

Reference capability: example/image-classification/symbols/inception-v3.py
(Szegedy et al. 2015, "Rethinking the Inception Architecture").  Written
from the paper's architecture: factorized 7x7 (1x7/7x1) towers, grid
reductions, BN after every conv.  299x299 input.
"""
from __future__ import annotations

from .. import symbol as sym

BN_EPS = 2e-5
BN_MOM = 0.9


def _conv(data, nf, kernel, stride=(1, 1), pad=(0, 0), name=None):
    c = sym.Convolution(data, num_filter=nf, kernel=kernel, stride=stride,
                        pad=pad, no_bias=True, name="%s_conv" % name)
    b = sym.BatchNorm(c, fix_gamma=True, eps=BN_EPS, momentum=BN_MOM,
                      name="%s_bn" % name)
    return sym.Activation(b, act_type="relu", name="%s_relu" % name)


def _pool(data, kind, kernel=(3, 3), stride=(1, 1), pad=(1, 1)):
    return sym.Pooling(data, kernel=kernel, stride=stride, pad=pad,
                       pool_type=kind)


def _block_a(data, proj, name):
    """35x35 block: 1x1 / 5x5 / double-3x3 / pool towers."""
    t1 = _conv(data, 64, (1, 1), name=name + "_t1")
    t2 = _conv(data, 48, (1, 1), name=name + "_t2a")
    t2 = _conv(t2, 64, (5, 5), pad=(2, 2), name=name + "_t2b")
    t3 = _conv(data, 64, (1, 1), name=name + "_t3a")
    t3 = _conv(t3, 96, (3, 3), pad=(1, 1), name=name + "_t3b")
    t3 = _conv(t3, 96, (3, 3), pad=(1, 1), name=name + "_t3c")
    t4 = _conv(_pool(data, "avg"), proj, (1, 1), name=name + "_t4")
    return sym.Concat(t1, t2, t3, t4, name=name)


def _reduction_a(data, name):
    t1 = _conv(data, 384, (3, 3), stride=(2, 2), name=name + "_t1")
    t2 = _conv(data, 64, (1, 1), name=name + "_t2a")
    t2 = _conv(t2, 96, (3, 3), pad=(1, 1), name=name + "_t2b")
    t2 = _conv(t2, 96, (3, 3), stride=(2, 2), name=name + "_t2c")
    t3 = _pool(data, "max", stride=(2, 2), pad=(0, 0))
    return sym.Concat(t1, t2, t3, name=name)


def _block_b(data, c7, name):
    """17x17 block with factorized 7x7 (1x7 + 7x1) towers."""
    t1 = _conv(data, 192, (1, 1), name=name + "_t1")
    t2 = _conv(data, c7, (1, 1), name=name + "_t2a")
    t2 = _conv(t2, c7, (1, 7), pad=(0, 3), name=name + "_t2b")
    t2 = _conv(t2, 192, (7, 1), pad=(3, 0), name=name + "_t2c")
    t3 = _conv(data, c7, (1, 1), name=name + "_t3a")
    t3 = _conv(t3, c7, (7, 1), pad=(3, 0), name=name + "_t3b")
    t3 = _conv(t3, c7, (1, 7), pad=(0, 3), name=name + "_t3c")
    t3 = _conv(t3, c7, (7, 1), pad=(3, 0), name=name + "_t3d")
    t3 = _conv(t3, 192, (1, 7), pad=(0, 3), name=name + "_t3e")
    t4 = _conv(_pool(data, "avg"), 192, (1, 1), name=name + "_t4")
    return sym.Concat(t1, t2, t3, t4, name=name)


def _reduction_b(data, name):
    t1 = _conv(data, 192, (1, 1), name=name + "_t1a")
    t1 = _conv(t1, 320, (3, 3), stride=(2, 2), name=name + "_t1b")
    t2 = _conv(data, 192, (1, 1), name=name + "_t2a")
    t2 = _conv(t2, 192, (1, 7), pad=(0, 3), name=name + "_t2b")
    t2 = _conv(t2, 192, (7, 1), pad=(3, 0), name=name + "_t2c")
    t2 = _conv(t2, 192, (3, 3), stride=(2, 2), name=name + "_t2d")
    t3 = _pool(data, "max", stride=(2, 2), pad=(0, 0))
    return sym.Concat(t1, t2, t3, name=name)


def _block_c(data, name):
    """8x8 block with split 3x3 -> (1x3, 3x1) towers."""
    t1 = _conv(data, 320, (1, 1), name=name + "_t1")
    t2 = _conv(data, 384, (1, 1), name=name + "_t2a")
    t2a = _conv(t2, 384, (1, 3), pad=(0, 1), name=name + "_t2b")
    t2b = _conv(t2, 384, (3, 1), pad=(1, 0), name=name + "_t2c")
    t3 = _conv(data, 448, (1, 1), name=name + "_t3a")
    t3 = _conv(t3, 384, (3, 3), pad=(1, 1), name=name + "_t3b")
    t3a = _conv(t3, 384, (1, 3), pad=(0, 1), name=name + "_t3c")
    t3b = _conv(t3, 384, (3, 1), pad=(1, 0), name=name + "_t3d")
    t4 = _conv(_pool(data, "avg"), 192, (1, 1), name=name + "_t4")
    return sym.Concat(t1, t2a, t2b, t3a, t3b, t4, name=name)


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    # stem: 299 -> 35
    net = _conv(data, 32, (3, 3), stride=(2, 2), name="stem1")
    net = _conv(net, 32, (3, 3), name="stem2")
    net = _conv(net, 64, (3, 3), pad=(1, 1), name="stem3")
    net = _pool(net, "max", stride=(2, 2), pad=(0, 0))
    net = _conv(net, 80, (1, 1), name="stem4")
    net = _conv(net, 192, (3, 3), name="stem5")
    net = _pool(net, "max", stride=(2, 2), pad=(0, 0))

    net = _block_a(net, 32, "mixed_a1")
    net = _block_a(net, 64, "mixed_a2")
    net = _block_a(net, 64, "mixed_a3")
    net = _reduction_a(net, "reduce_a")
    net = _block_b(net, 128, "mixed_b1")
    net = _block_b(net, 160, "mixed_b2")
    net = _block_b(net, 160, "mixed_b3")
    net = _block_b(net, 192, "mixed_b4")
    net = _reduction_b(net, "reduce_b")
    net = _block_c(net, "mixed_c1")
    net = _block_c(net, "mixed_c2")

    net = sym.Pooling(net, kernel=(8, 8), pool_type="avg", global_pool=True)
    net = sym.Dropout(net, p=0.2)
    net = sym.Flatten(net)
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return sym.SoftmaxOutput(net, name="softmax")
