"""Model zoo — symbol generators for the reference's example networks
(reference: example/image-classification/symbol_*.py, example/rnn)."""
from . import resnet
from . import lenet
from . import mlp
from . import alexnet
from . import vgg
from . import inception_bn
from . import inception_v3
from . import googlenet
from . import resnext
from . import lstm_lm
from . import attention_lm

get_lenet = lenet.get_symbol
get_mlp = mlp.get_symbol
get_resnet = resnet.get_symbol
get_alexnet = alexnet.get_symbol
get_vgg = vgg.get_symbol
get_inception_bn = inception_bn.get_symbol
get_inception_v3 = inception_v3.get_symbol
get_googlenet = googlenet.get_symbol
get_resnext = resnext.get_symbol
get_attention_lm = attention_lm.get_symbol
