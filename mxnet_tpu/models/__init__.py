"""Model zoo — symbol generators for the reference's example networks
(reference: example/image-classification/symbol_*.py, example/rnn)."""
from . import resnet
from . import lenet
from . import mlp
from . import alexnet
from . import vgg
from . import inception_bn
from . import lstm_lm

get_lenet = lenet.get_symbol
get_mlp = mlp.get_symbol
get_resnet = resnet.get_symbol
get_alexnet = alexnet.get_symbol
get_vgg = vgg.get_symbol
get_inception_bn = inception_bn.get_symbol
