"""ResNeXt symbol generator (aggregated residual transformations).

Reference capability: example/image-classification/symbols/resnext.py
(Xie et al. 2016).  Written from the paper: bottleneck units whose middle
3x3 conv is GROUPED (cardinality C); grouped convolution maps to one
`lax.conv_general_dilated` with feature_group_count on TPU — the MXU
tiles it as a block-diagonal matmul, no per-group loop.
"""
from __future__ import annotations

from .. import symbol as sym

BN_EPS = 2e-5
BN_MOM = 0.9


def resnext_unit(data, num_filter, stride, dim_match, cardinality,
                 bottleneck_width, name):
    """One ResNeXt bottleneck: 1x1 reduce -> grouped 3x3 -> 1x1 expand."""
    group_width = cardinality * bottleneck_width * (num_filter // 256)
    c1 = sym.Convolution(data, num_filter=group_width, kernel=(1, 1),
                         no_bias=True, name=name + "_conv1")
    b1 = sym.BatchNorm(c1, fix_gamma=False, eps=BN_EPS, momentum=BN_MOM,
                       name=name + "_bn1")
    a1 = sym.Activation(b1, act_type="relu")
    c2 = sym.Convolution(a1, num_filter=group_width, kernel=(3, 3),
                         stride=stride, pad=(1, 1), num_group=cardinality,
                         no_bias=True, name=name + "_conv2")
    b2 = sym.BatchNorm(c2, fix_gamma=False, eps=BN_EPS, momentum=BN_MOM,
                       name=name + "_bn2")
    a2 = sym.Activation(b2, act_type="relu")
    c3 = sym.Convolution(a2, num_filter=num_filter, kernel=(1, 1),
                         no_bias=True, name=name + "_conv3")
    b3 = sym.BatchNorm(c3, fix_gamma=False, eps=BN_EPS, momentum=BN_MOM,
                       name=name + "_bn3")
    if dim_match:
        shortcut = data
    else:
        sc = sym.Convolution(data, num_filter=num_filter, kernel=(1, 1),
                             stride=stride, no_bias=True, name=name + "_sc")
        shortcut = sym.BatchNorm(sc, fix_gamma=False, eps=BN_EPS,
                                 momentum=BN_MOM, name=name + "_sc_bn")
    return sym.Activation(b3 + shortcut, act_type="relu")


def get_symbol(num_classes=1000, num_layers=50, cardinality=32,
               bottleneck_width=4, image_shape=(3, 224, 224), **kwargs):
    units = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3],
             152: [3, 8, 36, 3]}.get(num_layers)
    if units is None:
        raise ValueError("unsupported resnext depth %d" % num_layers)
    filters = [256, 512, 1024, 2048]

    data = sym.Variable("data")
    small = image_shape[1] <= 64
    if small:                       # cifar-style stem
        net = sym.Convolution(data, num_filter=64, kernel=(3, 3),
                              pad=(1, 1), no_bias=True, name="conv0")
    else:
        net = sym.Convolution(data, num_filter=64, kernel=(7, 7),
                              stride=(2, 2), pad=(3, 3), no_bias=True,
                              name="conv0")
    net = sym.BatchNorm(net, fix_gamma=False, eps=BN_EPS, momentum=BN_MOM,
                        name="bn0")
    net = sym.Activation(net, act_type="relu")
    if not small:
        net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                          pool_type="max")

    for stage, (n_units, nf) in enumerate(zip(units, filters)):
        for unit in range(n_units):
            stride = (1, 1) if stage == 0 or unit > 0 else (2, 2)
            net = resnext_unit(net, nf, stride, dim_match=(unit > 0),
                               cardinality=cardinality,
                               bottleneck_width=bottleneck_width,
                               name="stage%d_unit%d" % (stage + 1, unit + 1))

    net = sym.Pooling(net, kernel=(7, 7), pool_type="avg", global_pool=True)
    net = sym.Flatten(net)
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return sym.SoftmaxOutput(net, name="softmax")
