"""LSTM language model for bucketed training.

Reference: example/rnn/lstm_bucketing.py — the PTB workload (SURVEY §6
configs): embedding → stacked LSTM (fused) → FC over vocab → SoftmaxOutput,
returned as a sym_gen for BucketingModule.
"""
from __future__ import annotations

from .. import symbol as sym
from .. import rnn


def sym_gen_factory(num_hidden=200, num_layers=2, num_embed=200,
                    vocab_size=10000, fused=True, dropout=0.0):
    """Returns sym_gen(seq_len) for BucketingModule (layout NT)."""

    if fused:
        stack = rnn.FusedRNNCell(num_hidden, num_layers=num_layers,
                                 mode="lstm", prefix="lstm_", dropout=dropout)
    else:
        stack = rnn.SequentialRNNCell()
        for i in range(num_layers):
            stack.add(rnn.LSTMCell(num_hidden, prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        embed = sym.Embedding(data, input_dim=vocab_size, output_dim=num_embed,
                              name="embed")
        stack.reset()
        outputs, states = stack.unroll(seq_len, inputs=embed, layout="NTC",
                                       merge_outputs=True)
        pred = sym.Reshape(outputs, shape=(-1, num_hidden))
        pred = sym.FullyConnected(pred, num_hidden=vocab_size, name="pred")
        lab = sym.Reshape(label, shape=(-1,))
        pred = sym.SoftmaxOutput(pred, lab, name="softmax")
        return pred, ("data",), ("softmax_label",)

    return sym_gen, stack
