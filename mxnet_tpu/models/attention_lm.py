"""Decoder-only attention language model — the TPU-era LM family.

No reference analog (its LM story is the unrolled/fused LSTM,
example/rnn): this is the leapfrog model built from the framework's
attention primitives.  Pre-norm transformer blocks with causal
multi-head attention (``dot_product_attention``), optionally
mixture-of-experts FFNs (``MoEFFN``).  Composes with every mesh axis:
batch on 'data', time on 'seq' (bind with layout-'NT' DataDescs),
projection weights on 'model', expert stacks on 'expert'.
"""
from __future__ import annotations

import numpy as np

from .. import symbol as sym


def _normalize(data):
    """The LayerNorm statistics half: (x - mean) / sqrt(var + eps) over
    the last axis, no affine — the gamma/beta tail rides either the
    broadcast ops (:func:`layer_norm`) or a FusedLNLinear segment."""
    mean = sym.mean(data, axis=-1, keepdims=True)
    centered = sym.broadcast_sub(data, mean)
    var = sym.mean(sym.square(centered), axis=-1, keepdims=True)
    inv = sym.rsqrt(var + 1e-5)
    return sym.broadcast_mul(centered, inv)


def _ln_affine(name, embed):
    gamma = sym.Variable(name + "_ln_gamma", shape=(1, 1, embed))
    beta = sym.Variable(name + "_ln_beta", shape=(1, 1, embed))
    return gamma, beta


def layer_norm(data, embed, name):
    """LayerNorm over the last axis, built from registry ops (mean/var
    through broadcast arithmetic; gamma/beta as 1-wide FC is avoided — the
    scale/shift ride as learnable broadcast params via elementwise ops)."""
    normed = _normalize(data)
    gamma, beta = _ln_affine(name, embed)
    return sym.broadcast_add(sym.broadcast_mul(normed, gamma), beta)


def block(data, embed, heads, ffn_hidden, name, moe_experts=0,
          moe_capacity_factor=0.0, moe_top_k=1, num_kv_heads=0):
    """One pre-norm decoder block.

    The LN->linear segments run through :class:`FusedLNLinear` (the LN
    affine tail + projection as one op): under ``MXNET_PALLAS_FUSED``
    the op dispatches to the fused Pallas epilogue kernel forward and
    backward, otherwise it traces the same five-op einsum composition
    this graph always ran.  Parameter names/shapes are unchanged either
    way (``*_ln_gamma``/``*_ln_beta``, FC-layout weight/bias).

    ``num_kv_heads`` < ``heads`` emits grouped-query attention: the K/V
    projections are physically ``num_kv_heads * head_dim`` wide (same
    ``_k``/``_v`` param names — a GQA checkpoint loads by name with the
    grouped shapes) and the attention op maps each q-head to kv group
    ``h // G``.  0 (default) keeps the MHA graph byte-identical."""
    kv_heads = int(num_kv_heads) or heads
    if heads % kv_heads:
        raise ValueError(
            "attention_lm.block: num_heads=%d not divisible by "
            "num_kv_heads=%d" % (heads, kv_heads))
    kv_hidden = kv_heads * (embed // heads)
    normed = _normalize(data)
    gamma, beta = _ln_affine(name + "_att", embed)
    q = sym.FusedLNLinear(normed, gamma, beta, num_hidden=embed,
                          name=name + "_q")
    k = sym.FusedLNLinear(normed, gamma, beta, num_hidden=kv_hidden,
                          name=name + "_k")
    v = sym.FusedLNLinear(normed, gamma, beta, num_hidden=kv_hidden,
                          name=name + "_v")
    if kv_heads != heads:
        att = sym.dot_product_attention(q, k, v, num_heads=heads,
                                        num_kv_heads=kv_heads, causal=True)
    else:
        att = sym.dot_product_attention(q, k, v, num_heads=heads,
                                        causal=True)
    att = sym.FullyConnected(att, num_hidden=embed, flatten=False,
                             name=name + "_attout")
    data = data + att

    ffn_normed = _normalize(data)
    fgamma, fbeta = _ln_affine(name + "_ffn", embed)
    if moe_experts > 0:
        # MoEFFN routes tokens over the trailing axis; (B, T, E) in/out.
        # capacity_factor > 0 arms the sparse capacity-slot dispatch
        # (the explicit all-to-all program under an 'expert' mesh);
        # moe_top_k routes each token to its k best experts.
        ffn_in = sym.broadcast_add(sym.broadcast_mul(ffn_normed, fgamma),
                                   fbeta)
        ffn = sym.MoEFFN(ffn_in, num_experts=moe_experts,
                         hidden_size=ffn_hidden,
                         capacity_factor=moe_capacity_factor,
                         num_experts_per_tok=moe_top_k,
                         name=name + "_moe")
        return data + ffn
    h = sym.FusedLNLinear(ffn_normed, fgamma, fbeta,
                          num_hidden=ffn_hidden, name=name + "_ffn1")
    # ffn2 consumes the PRE-activation h: its ReLU is the fused op's
    # prologue and the block's residual rides its epilogue, so the
    # activated tensor never materializes in HBM on the kernel path
    return sym.FusedLNLinear(h, residual=data, num_hidden=embed,
                             relu=True, no_affine=True, has_residual=True,
                             name=name + "_ffn2")


def get_symbol(vocab_size, seq_len, num_layers=2, embed=128, heads=4,
               ffn_hidden=512, moe_experts=0, moe_capacity_factor=0.0,
               moe_top_k=1, num_kv_heads=0, **kwargs):
    """Decoder-only LM: data (B, T) int tokens, softmax over vocab at every
    position; labels (B, T) next tokens (pad = -1 ignored).

    ``num_kv_heads`` (0 = ``heads``) emits grouped-query K/V projections
    G = heads/num_kv_heads times narrower; the G=1 graph is unchanged."""
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    net = sym.Embedding(data, input_dim=vocab_size, output_dim=embed,
                        name="embed")
    # learned positional embedding, broadcast over the batch
    pos = sym.Variable("pos_embed_weight", shape=(1, seq_len, embed))
    net = sym.broadcast_add(net, pos)
    for i in range(num_layers):
        net = block(net, embed, heads, ffn_hidden, "layer%d" % i,
                    moe_experts=moe_experts,
                    moe_capacity_factor=moe_capacity_factor,
                    moe_top_k=moe_top_k, num_kv_heads=num_kv_heads)
    net = layer_norm(net, embed, "final")
    logits = sym.FullyConnected(sym.Reshape(net, shape=(-1, embed)),
                                num_hidden=vocab_size, name="head")
    flat_label = sym.Reshape(label, shape=(-1,))
    return sym.SoftmaxOutput(logits, flat_label, use_ignore=True,
                             ignore_label=-1, name="softmax")
