"""GoogLeNet (Inception v1) symbol generator.

Reference capability: example/image-classification/symbols/googlenet.py
(Szegedy et al. 2014, "Going Deeper with Convolutions").  Written from
the paper's Table 1 configuration; auxiliary classifier heads are omitted
(as the reference example also trains without them by default).
"""
from __future__ import annotations

from .. import symbol as sym


def _conv(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=None):
    c = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, name="conv_%s" % name)
    return sym.Activation(c, act_type="relu", name="relu_%s" % name)


def _inception(data, f1, f3r, f3, f5r, f5, proj, name):
    """One inception block: 1x1 / 3x3 / 5x5 / pool-proj branches."""
    b1 = _conv(data, f1, (1, 1), name="%s_1x1" % name)
    b3 = _conv(data, f3r, (1, 1), name="%s_3x3r" % name)
    b3 = _conv(b3, f3, (3, 3), pad=(1, 1), name="%s_3x3" % name)
    b5 = _conv(data, f5r, (1, 1), name="%s_5x5r" % name)
    b5 = _conv(b5, f5, (5, 5), pad=(2, 2), name="%s_5x5" % name)
    bp = sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="max", name="%s_pool" % name)
    bp = _conv(bp, proj, (1, 1), name="%s_proj" % name)
    return sym.Concat(b1, b3, b5, bp, name="%s_concat" % name)


# (f1, f3r, f3, f5r, f5, proj) per block, paper Table 1
_BLOCKS = [
    ("3a", 64, 96, 128, 16, 32, 32),
    ("3b", 128, 128, 192, 32, 96, 64),
    ("pool",),
    ("4a", 192, 96, 208, 16, 48, 64),
    ("4b", 160, 112, 224, 24, 64, 64),
    ("4c", 128, 128, 256, 24, 64, 64),
    ("4d", 112, 144, 288, 32, 64, 64),
    ("4e", 256, 160, 320, 32, 128, 128),
    ("pool",),
    ("5a", 256, 160, 320, 32, 128, 128),
    ("5b", 384, 192, 384, 48, 128, 128),
]


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    net = _conv(data, 64, (7, 7), stride=(2, 2), pad=(3, 3), name="stem1")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                      pool_type="max")
    net = _conv(net, 64, (1, 1), name="stem2r")
    net = _conv(net, 192, (3, 3), pad=(1, 1), name="stem2")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                      pool_type="max")
    for block in _BLOCKS:
        if block[0] == "pool":
            net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                              pool_type="max")
        else:
            name, f1, f3r, f3, f5r, f5, proj = block
            net = _inception(net, f1, f3r, f3, f5r, f5, proj, name)
    net = sym.Pooling(net, kernel=(7, 7), stride=(1, 1), pool_type="avg",
                      global_pool=True)
    net = sym.Dropout(net, p=0.4)
    net = sym.Flatten(net)
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return sym.SoftmaxOutput(net, name="softmax")
