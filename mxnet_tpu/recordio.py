"""RecordIO — sequential record pack format.

Reference: `python/mxnet/recordio.py` (269 LoC) + dmlc-core recordio.
Format compatible with the reference: each record is
``[kMagic:u32][cflag|len:u32][data][pad to 4B]``, with the same magic and
continuation-flag encoding, so .rec files pack with `tools/im2rec.py` here
read in reference MXNet and vice versa.  IRHeader packing is also
byte-compatible (label/id/id2 struct + optional float array).
"""
from __future__ import annotations

import ctypes
import os
import struct

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img", "build_index"]

_kMagic = 0xCED7230A


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference: recordio.py:12).

    Backed by the native C++ codec (`src/recordio.cc`, dmlc-core recordio
    analog — handles split-record reassembly) when the toolchain built it;
    degrades to a pure-Python codec otherwise."""

    def __init__(self, uri, flag):
        from . import _native

        self.uri = uri
        self.flag = flag
        self.handle = None
        self.is_open = False
        self._lib = _native.recordio_lib()
        self.open()

    def open(self):
        if self.flag == "w":
            self.writable = True
        elif self.flag == "r":
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        if self._lib is not None:
            opener = (self._lib.rio_writer_open if self.writable
                      else self._lib.rio_reader_open)
            self.handle = opener(self.uri.encode())
            if not self.handle:
                from ._native import native_error

                raise MXNetError(native_error(self._lib))
        else:
            self.handle = open(self.uri, "wb" if self.writable else "rb")
        self.is_open = True

    def close(self):
        if self.is_open:
            if self._lib is not None:
                closer = (self._lib.rio_writer_close if self.writable
                          else self._lib.rio_reader_close)
                closer(self.handle)
                self.handle = None
            else:
                self.handle.close()
            self.is_open = False

    def __del__(self):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        if self._lib is not None:
            teller = (self._lib.rio_writer_tell if self.writable
                      else self._lib.rio_reader_tell)
            return teller(self.handle)
        return self.handle.tell()

    def write(self, buf):
        assert self.writable
        data = bytes(buf)
        if self._lib is not None:
            from ._native import native_error

            if self._lib.rio_writer_write(self.handle, data, len(data)) < 0:
                raise MXNetError(native_error(self._lib))
            return
        if len(data) > 0x1FFFFFFF:
            raise MXNetError("record too large (max 2^29-1 bytes per frame)")

        def part(cflag, payload):
            self.handle.write(struct.pack(
                "<II", _kMagic, (cflag << 29) | len(payload)))
            self.handle.write(payload)
            pad = (4 - len(payload) % 4) % 4
            if pad:
                self.handle.write(b"\x00" * pad)

        # dmlc framing: payloads embedding the magic at 4B-aligned offsets
        # split there, the magic bytes replaced by the next part's header
        # (so chunked magic-scanning readers always hit real boundaries)
        magic_bytes = struct.pack("<I", _kMagic)
        splits = []
        pos = data.find(magic_bytes)
        while pos != -1:
            if pos % 4 == 0:
                splits.append(pos)
                pos = data.find(magic_bytes, pos + 4)
            else:
                pos = data.find(magic_bytes, pos + 1)
        if not splits:
            part(0, data)
            return
        begin = 0
        for k, pos in enumerate(splits):
            part(1 if k == 0 else 2, data[begin:pos])
            begin = pos + 4
        part(3, data[begin:])

    def read(self):
        assert not self.writable
        if self._lib is not None:
            from ._native import native_error

            data_p = ctypes.c_void_p()
            length = ctypes.c_uint64()
            rc = self._lib.rio_reader_next(self.handle,
                                           ctypes.byref(data_p),
                                           ctypes.byref(length))
            if rc == 0:
                return None
            if rc < 0:
                raise MXNetError(native_error(self._lib))
            return ctypes.string_at(data_p, length.value)
        record = None
        while True:
            header = self.handle.read(8)
            if len(header) < 8:
                if record is not None:
                    raise MXNetError("unterminated split record in %s"
                                     % self.uri)
                return None
            magic, lrec = struct.unpack("<II", header)
            if magic != _kMagic:
                raise MXNetError("Invalid RecordIO magic in %s" % self.uri)
            cflag, length = lrec >> 29, lrec & 0x1FFFFFFF
            data = self.handle.read(length)
            pad = (4 - length % 4) % 4
            if pad:
                self.handle.read(pad)
            # dmlc writers split records whose payload embeds the magic:
            # cflag 0 whole, 1 first, 2 middle, 3 last — reassemble
            if record is None:
                if cflag == 0:
                    return data
                if cflag != 1:
                    raise MXNetError("unexpected continuation frame in %s"
                                     % self.uri)
                record = bytearray(data)
            else:
                if cflag not in (2, 3):
                    raise MXNetError("corrupt split-record chain in %s"
                                     % self.uri)
                # restore the magic the writer dropped at the split point
                record.extend(struct.pack("<I", _kMagic))
                record.extend(data)
                if cflag == 3:
                    return bytes(record)


class MXIndexedRecordIO(MXRecordIO):
    """Indexed RecordIO with a .idx sidecar (reference: recordio.py:87)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin.readlines():
                    key, pos = line.strip().split("\t")
                    key = self.key_type(key)
                    self.idx[key] = int(pos)
                    self.keys.append(key)

    def close(self):
        if self.is_open and self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write("%s\t%d\n" % (str(k), self.idx[k]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        if self._lib is not None:
            from ._native import native_error

            if self._lib.rio_reader_seek(self.handle, self.idx[idx]) < 0:
                raise MXNetError(native_error(self._lib))
        else:
            self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


def build_index(rec_path, idx_path=None):
    """Scan a .rec file and produce its record-start offsets (the .idx
    sidecar `tools/im2rec` emits).  Uses the native scanner when built."""
    from . import _native

    lib = _native.recordio_lib()
    if lib is not None:
        out = ctypes.POINTER(ctypes.c_int64)()
        count = lib.rio_build_index(rec_path.encode(), ctypes.byref(out))
        if count < 0:
            raise MXNetError(_native.native_error(lib))
        offsets = [out[i] for i in range(count)]
        lib.rio_free(out)
    else:
        offsets = []
        reader = MXRecordIO(rec_path, "r")
        while True:
            pos = reader.tell()
            if reader.read() is None:
                break
            offsets.append(pos)
        reader.close()
    if idx_path is not None:
        with open(idx_path, "w") as fout:
            for i, pos in enumerate(offsets):
                fout.write("%d\t%d\n" % (i, pos))
    return offsets


class IRHeader:
    """Image record header (reference: recordio.py:145): flag, label, id, id2."""

    def __init__(self, flag, label, id, id2):
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2

    def __iter__(self):
        return iter((self.flag, self.label, self.id, self.id2))


_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a (header, bytes) record (reference: recordio.py:157)."""
    flag, label, id_, id2 = tuple(header)
    if isinstance(label, (np.ndarray, list, tuple)):
        label = np.asarray(label, dtype=np.float32)
        flag = label.size
        payload = struct.pack(_IR_FORMAT, flag, 0.0, id_, id2) + label.tobytes() + bytes(s)
    else:
        payload = struct.pack(_IR_FORMAT, flag, float(label), id_, id2) + bytes(s)
    return payload


def unpack(s):
    """Unpack a record into (IRHeader, bytes) (reference: recordio.py:177)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        arr = np.frombuffer(s[:flag * 4], dtype=np.float32)
        s = s[flag * 4:]
        return IRHeader(flag, arr, id_, id2), s
    return IRHeader(flag, label, id_, id2), s


def unpack_img(s, iscolor=-1):
    """Unpack record into (header, image array) — raw-array codec here;
    JPEG decode requires cv2 (gated the way opencv is in the reference)."""
    header, s = unpack(s)
    try:
        import cv2

        img = cv2.imdecode(np.frombuffer(s, dtype=np.uint8), iscolor)
        if img is not None:
            return header, img
    except ImportError:
        pass
    # raw numpy codec: [ndim:u8][dims:u32*ndim][uint8 data]
    ndim = s[0]
    dims = struct.unpack("<%dI" % ndim, s[1:1 + 4 * ndim])
    img = np.frombuffer(s[1 + 4 * ndim:], dtype=np.uint8).reshape(dims)
    return header, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image (cv2 if available, else raw-array codec)."""
    try:
        import cv2

        encode_params = None
        if img_fmt in (".jpg", ".jpeg"):
            encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
        elif img_fmt == ".png":
            encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
        ret, buf = cv2.imencode(img_fmt, img, encode_params)
        assert ret
        return pack(header, buf.tobytes())
    except ImportError:
        img = np.ascontiguousarray(img, dtype=np.uint8)
        payload = struct.pack("<B", img.ndim) + \
            struct.pack("<%dI" % img.ndim, *img.shape) + img.tobytes()
        return pack(header, payload)
