"""Global PRNG state.

Reference seeds RNG resources through the engine (`src/resource.cc:144-178`,
`MXRandomSeed`).  TPU-native: one functional ``jax.random`` key chain; each
random op splits a fresh subkey *outside* jit and passes it in as a traced
argument, so compiled computations stay pure and reproducible.
"""
from __future__ import annotations

import threading

__all__ = ["seed", "split_key"]

_state = threading.local()


def _key():
    import jax

    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
    return _state.key


def seed(seed_state):
    """Seed all random sources (reference: python/mxnet/random.py:34) —
    the device-side key chain and the host-side numpy generator the
    initializers draw from."""
    import jax
    import numpy as np

    _state.key = jax.random.PRNGKey(int(seed_state))
    np.random.seed(int(seed_state) % (2 ** 32))


def split_key():
    """Return a fresh subkey, advancing the global chain."""
    import jax

    k, sub = jax.random.split(_key())
    _state.key = k
    return sub
