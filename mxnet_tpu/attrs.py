"""Declarative operator-parameter system.

TPU-native analog of ``dmlc::Parameter`` (reference: dmlc-core parameter.h,
used by every op, e.g. `src/operator/rnn-inl.h:70-104` RNNParam).  Each op
declares a schema of typed fields with defaults/required flags; attribute
dicts arriving as *strings* (from Symbol JSON or frontend kwargs) are parsed
and validated against the schema into a hashable ``FrozenAttrs`` — hashable
so attrs can be a ``static_argnums`` of ``jax.jit`` and every (op, attrs)
pair compiles exactly once.
"""
from __future__ import annotations

import ast

from .base import MXNetError

__all__ = ["Param", "ParamSchema", "FrozenAttrs", "parse_tuple", "parse_bool"]


def parse_bool(s):
    if isinstance(s, bool):
        return s
    if isinstance(s, (int, float)):
        return bool(s)
    s = str(s).strip().lower()
    if s in ("true", "1"):
        return True
    if s in ("false", "0"):
        return False
    raise ValueError("cannot parse bool from %r" % s)


def parse_tuple(s, elem_type=int):
    """Parse '(2,2)' / '[2, 2]' / '2' / (2, 2) into a tuple."""
    if isinstance(s, (tuple, list)):
        return tuple(elem_type(x) for x in s)
    if isinstance(s, (int, float)):
        return (elem_type(s),)
    s = str(s).strip()
    if s.startswith("(") or s.startswith("["):
        val = ast.literal_eval(s)
        if isinstance(val, (int, float)):
            return (elem_type(val),)
        return tuple(elem_type(x) for x in val)
    return (elem_type(ast.literal_eval(s)),)


def _identity(x):
    return x


_PARSERS = {
    int: lambda s: int(float(s)) if not isinstance(s, str) else int(float(s)),
    float: float,
    bool: parse_bool,
    str: str,
    tuple: parse_tuple,
    "shape": parse_tuple,
    "float_tuple": lambda s: parse_tuple(s, float),
    None: _identity,
}


class Param:
    """One declared field of an op's parameter struct."""

    __slots__ = ("name", "type", "default", "required", "doc", "enum")

    def __init__(self, name, type=str, default=None, required=False, doc="", enum=None):
        self.name = name
        self.type = type
        self.default = default
        self.required = required
        self.doc = doc
        self.enum = enum

    def parse(self, value):
        parser = _PARSERS.get(self.type, self.type if callable(self.type) else _identity)
        val = parser(value)
        if self.enum is not None and val not in self.enum:
            raise MXNetError(
                "Invalid value %r for parameter %s; expected one of %s"
                % (val, self.name, self.enum)
            )
        return val


class FrozenAttrs:
    """Immutable, hashable attribute mapping — safe as a jit static arg."""

    __slots__ = ("_items", "_hash")

    def __init__(self, mapping):
        self._items = tuple(sorted(mapping.items()))
        self._hash = hash(self._items)

    def __getitem__(self, key):
        for k, v in self._items:
            if k == key:
                return v
        raise KeyError(key)

    def get(self, key, default=None):
        for k, v in self._items:
            if k == key:
                return v
        return default

    def __contains__(self, key):
        return any(k == key for k, _ in self._items)

    def __iter__(self):
        return (k for k, _ in self._items)

    def items(self):
        return self._items

    def keys(self):
        return [k for k, _ in self._items]

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return isinstance(other, FrozenAttrs) and self._items == other._items

    def __repr__(self):
        return "FrozenAttrs(%s)" % dict(self._items)

    def as_dict(self):
        return dict(self._items)


class ParamSchema:
    """Ordered collection of :class:`Param` declarations for one op."""

    def __init__(self, *params):
        self.params = {p.name: p for p in params}

    def parse(self, raw_attrs):
        """Parse raw (possibly string-valued) attrs into FrozenAttrs.

        Unknown keys are preserved as raw strings — the reference forwards
        unknown attrs into the symbol attr dict (e.g. ``ctx_group``,
        ``__shape__`` hints) rather than rejecting them.
        """
        out = {}
        raw = dict(raw_attrs) if raw_attrs else {}
        for name, p in self.params.items():
            if name in raw:
                try:
                    out[name] = p.parse(raw.pop(name))
                except (ValueError, SyntaxError) as e:
                    raise MXNetError(
                        "Failed to parse parameter %s=%r: %s" % (name, raw_attrs[name], e)
                    )
            elif p.required:
                raise MXNetError("Required parameter %s is missing" % name)
            else:
                out[name] = p.default
        for key, value in raw.items():
            # keep unknown/system attrs (strings) for graph passes
            out[key] = value if not isinstance(value, (list,)) else tuple(value)
        return FrozenAttrs(out)

    def doc(self):
        lines = []
        for p in self.params.values():
            t = getattr(p.type, "__name__", str(p.type))
            d = "required" if p.required else "default=%r" % (p.default,)
            lines.append("%s : %s, %s\n    %s" % (p.name, t, d, p.doc))
        return "\n".join(lines)
