"""Training callbacks.

Same call protocol as the reference's ``python/mxnet/callback.py`` —
batch-end callbacks receive a ``BatchEndParam``-shaped object with
``epoch``/``nbatch``/``eval_metric`` fields, epoch-end callbacks receive
``(epoch, symbol, arg_params, aux_params)`` — implemented around a small
shared rate-limiter (`_Every`) instead of per-callback counter bookkeeping.
"""
from __future__ import annotations

import logging
import sys
import time

__all__ = ["Speedometer", "do_checkpoint", "module_checkpoint",
           "log_train_metric", "ProgressBar"]


class _Every:
    """True once per ``n`` calls keyed on a monotonically growing counter;
    resets itself when the counter restarts (new epoch)."""

    def __init__(self, n):
        self.n = max(1, int(n))
        self._prev = None

    def ready(self, count):
        restarted = self._prev is not None and count < self._prev
        self._prev = count
        if restarted:
            return False
        return count > 0 and count % self.n == 0


def _emit_metric(prefix, metric, extra=""):
    for name, value in metric.get_name_value():
        logging.info("%s%s\tTrain-%s=%f", prefix, extra, name, value)


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback saving the full Module state every ``period``
    epochs (symbol + params + optionally optimizer states)."""
    gate = _Every(period)

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % gate.n == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end callback writing ``prefix-symbol.json`` +
    ``prefix-####.params`` every ``period`` epochs."""
    from .model import save_checkpoint

    gate = _Every(period)

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % gate.n == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the running training metric."""
    gate = _Every(period)

    def _callback(param):
        if param.eval_metric is None or not gate.ready(param.nbatch):
            return
        _emit_metric("Iter[%d] Batch[%d]" % (param.epoch, param.nbatch),
                     param.eval_metric)
        if auto_reset:
            param.eval_metric.reset()

    return _callback


class Speedometer:
    """Batch-end callback reporting throughput (samples/sec) and the
    training metric every ``frequent`` batches.  The metric is reset after
    each report, so values are per-window rather than running averages."""

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self._gate = _Every(frequent)
        self._window_start = None

    def __call__(self, param):
        now = time.time()
        if self._window_start is None or param.nbatch < (self._gate._prev or 0):
            self._window_start = now
        if not self._gate.ready(param.nbatch):
            return
        elapsed = max(now - self._window_start, 1e-9)
        speed = self.frequent * self.batch_size / elapsed
        head = "Epoch[%d] Batch [%d]" % (param.epoch, param.nbatch)
        if param.eval_metric is not None:
            _emit_metric(head, param.eval_metric,
                         "\tSpeed: %.2f samples/sec" % speed)
            param.eval_metric.reset()
        else:
            logging.info("%s\tSpeed: %.2f samples/sec", head, speed)
        self._window_start = now


class ProgressBar:
    """Batch-end callback drawing an in-place ASCII progress bar; useful
    for interactive runs where Speedometer logs would scroll."""

    def __init__(self, total, length=80):
        self.total = max(1, int(total))
        self.length = length

    def __call__(self, param):
        frac = min(param.nbatch / self.total, 1.0)
        done = int(self.length * frac)
        bar = "=" * done + "-" * (self.length - done)
        sys.stdout.write("[%s] %d%%\r" % (bar, int(100 * frac + 0.999)))
        sys.stdout.flush()
