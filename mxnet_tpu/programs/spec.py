"""ProgramSpec — one compiled program's registration record.

Every compiled program the framework runs (the fused train step, the
eval step, the decode/verify/chunk serving programs, the page
migration pair) used to hand-thread the same plumbing three separate
times: an aval snapshot for probes, a ``_probing`` guard so probe
traces don't count as retraces, a donated-leaf count for the donation
pass, mesh/dtype metadata for the artifact, and a lazy static-cost
prober for the roofline table.  A :class:`ProgramSpec` is that plumbing
written ONCE: the call site registers (name, jitted fn, abstract args,
donation map, partition rules, trace counters) and gets

* :meth:`artifact`  — the :class:`~mxnet_tpu.analysis.artifact.
  ProgramArtifact` probe (jaxpr + StableHLO + compiled HLO + metadata),
  donated leaves COMPUTED from ``donate_argnums`` over the actual args
  instead of hand-counted;
* :meth:`cost`      — the roofline static cost
  (``analysis.cost.program_cost``), probe-flagged;
* :meth:`lowered` / :meth:`compiled` — the raw AOT pipeline stages;
* :meth:`fingerprint` — the content address of the compiled program:
  a digest over (name, abstract args, donation map, jax version,
  backend, mesh shape, caller extras) that keys the on-disk AOT cache
  (``mxnet_tpu.programs.aot``) and lets two hosts PROVE they run
  byte-identical programs by comparing keys.

The probing helpers at module level (:func:`probing`,
:func:`probe_artifact`, :func:`probe_cost`, :func:`probe_lowered_text`)
are the ONE copy of the ``owner._probing`` guard dance that
``CompiledTrainStep``/``CompiledEvalStep``/``DecodePredictor`` each
used to hand-roll around every artifact/cost/HLO probe.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import weakref

__all__ = ["ProgramSpec", "probing", "probe_artifact", "probe_cost",
           "probe_lowered_text"]


@contextlib.contextmanager
def probing(owner):
    """Flag ``owner`` as mid-probe so its python-level trace counters
    skip the probe's (re)trace — the retrace auditors stay honest.
    ``owner=None`` is a no-op scope (free functions, registry-only
    specs)."""
    if owner is None:
        yield
        return
    owner._probing = True
    try:
        yield
    finally:
        owner._probing = False


def probe_artifact(owner, fn, args, name, refine=None, **kw):
    """Build a :class:`~mxnet_tpu.analysis.artifact.ProgramArtifact`
    from a jitted fn under the probing guard — the registry helper the
    five per-class probing blocks collapsed into.  ``refine`` is an
    optional post-hook on the artifact (decode's pallas-promise
    withdrawal)."""
    from ..analysis.artifact import artifact_from_jit

    with probing(owner):
        art = artifact_from_jit(fn, args, name=name, **kw)
    return refine(art) if refine is not None else art


def probe_cost(owner, fn, args):
    """Static FLOPs + traffic bytes (``analysis.cost.program_cost``)
    under the probing guard — the roofline prober body."""
    from ..analysis.cost import program_cost

    with probing(owner):
        return program_cost(fn, args)


def probe_lowered_text(owner, fn, args):
    """Lowered (pre-optimization) StableHLO text under the probing
    guard — the FLOP-assertion probe body."""
    with probing(owner):
        return fn.lower(*args).as_text()


def _resolve(v):
    return v() if callable(v) else v


def _leaf_sig(leaf):
    """(shape, dtype, sharding) signature of one abstract-arg leaf."""
    sharding = getattr(leaf, "sharding", None)
    return [list(getattr(leaf, "shape", ()) or ()),
            str(getattr(leaf, "dtype", None)),
            str(sharding.spec) if hasattr(sharding, "spec")
            else (str(sharding) if sharding is not None else None)]


class ProgramSpec:
    """One registered compiled program.

    Parameters
    ----------
    name : str
        The program's registry/telemetry name (``train_step``,
        ``decode_step``, ...).
    fn : jitted callable
        The ``jax.jit``-wrapped program (an
        :class:`~mxnet_tpu.programs.aot.AotDispatch` facade works too —
        probes use its ``.trace``/``.lower`` delegation).
    owner : object, optional
        The live object whose ``_probing`` flag guards probe traces;
        held weakly so a spec never pins a model's parameter store.
    abstract_args : tuple or callable, optional
        The aval pytree selecting the program's trace (a callable is
        resolved lazily — shapes often exist only after the first run —
        and may return None for "not ready yet").
    donate_argnums : tuple of int
        The jit donation map; donated-leaf counts for the donation pass
        are computed from it over the actual args.
    mesh_shape, compute_dtype, expected_traces, trace_count, meta
        Artifact metadata; values or callables.
    partition_rules : list, optional
        The regex partition rules the program's named param tree was
        placed by (``programs.partition``) — recorded for docs/probes
        and folded into the fingerprint.
    fingerprint_extra : dict or callable, optional
        Caller-identity payload for the AOT cache key (e.g. the symbol
        graph digest + decode knobs) — everything that changes the
        traced program but not the aval signature.
    """

    def __init__(self, name, fn, *, owner=None, abstract_args=None,
                 donate_argnums=(), mesh_shape=None, compute_dtype=None,
                 expected_traces=1, trace_count=None, meta=None,
                 partition_rules=None, fingerprint_extra=None):
        self.name = name
        self.fn = fn
        self._owner = weakref.ref(owner) if owner is not None else None
        self._abstract_args = abstract_args
        self.donate_argnums = tuple(donate_argnums or ())
        self._mesh_shape = mesh_shape
        self._compute_dtype = compute_dtype
        self._expected_traces = expected_traces
        self._trace_count = trace_count
        self._meta = meta
        self.partition_rules = partition_rules
        self._fingerprint_extra = fingerprint_extra

    # ------------------------------------------------------------------
    def owner(self):
        return self._owner() if self._owner is not None else None

    def avals(self, args=None):
        """The aval pytree selecting this program's trace (None when the
        spec's lazy supplier says the program is not runnable yet)."""
        return args if args is not None else _resolve(self._abstract_args)

    def donated_leaves(self, args):
        """Donated array-buffer count, computed from the donation map
        over the actual args — the hand-counted ``ndon``/``donated``
        arithmetic the per-class probes used to carry."""
        import jax.tree_util as jtu

        return sum(len(jtu.tree_leaves(args[i]))
                   for i in self.donate_argnums if i < len(args))

    # ------------------------------------------------------------------
    # probes (the uniform exposure the passes/roofline consume)
    # ------------------------------------------------------------------
    def artifact(self, args=None, name=None, refine=None, **extra_meta):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        program at ``args`` (or the spec's abstract args); None before
        the program is runnable."""
        args = self.avals(args)
        if args is None:
            return None
        meta = dict(_resolve(self._meta) or {})
        meta.update(extra_meta)
        return probe_artifact(
            self.owner(), self.fn, args, name or self.name,
            refine=refine, donated_leaves=self.donated_leaves(args),
            compute_dtype=_resolve(self._compute_dtype),
            mesh_shape=_resolve(self._mesh_shape),
            trace_count=_resolve(self._trace_count),
            expected_traces=_resolve(self._expected_traces) or 1, **meta)

    def cost(self, args=None):
        """Roofline static cost at ``args`` (None before runnable)."""
        args = self.avals(args)
        if args is None:
            return None
        return probe_cost(self.owner(), self.fn, args)

    def register_roofline(self, accounting=None, name=None):
        """Attach this spec's :meth:`cost` as the program's lazy
        static-cost prober (weakly bound through the spec's own weak
        owner ref, so registration never pins the model)."""
        from .. import obs as _obs

        acc = accounting if accounting is not None else _obs.programs
        ref = weakref.ref(self)
        acc.register_static(
            name or self.name,
            lambda: (ref().cost() if ref() is not None else None))

    # ------------------------------------------------------------------
    # the AOT pipeline stages
    # ------------------------------------------------------------------
    def lowered(self, args=None):
        """``fn.lower(*args)`` under the probing guard."""
        args = self.avals(args)
        if args is None:
            return None
        with probing(self.owner()):
            return self.fn.lower(*args)

    def compiled(self, args=None):
        """``fn.lower(*args).compile()`` under the probing guard — the
        executable the AOT cache serializes."""
        low = self.lowered(args)
        return low.compile() if low is not None else None

    def fingerprint(self, args=None, backend=None):
        """Content address of the compiled program: digest over the
        abstract args (shapes/dtypes/shardings + tree structure), the
        donation map, the jax version, the backend, the mesh shape, the
        partition rules and the caller's identity extras.  Two specs
        with equal fingerprints compile to byte-identical programs —
        the checkable "every fleet host runs the canonical program"
        invariant, and the AOT cache key."""
        import jax
        import jax.tree_util as jtu

        args = self.avals(args)
        if args is None:
            return None
        if backend is None:
            backend = jax.default_backend()
        leaves, treedef = jtu.tree_flatten(args)
        payload = {
            "name": self.name,
            "jax": jax.__version__,
            "backend": str(backend),
            "mesh_shape": _resolve(self._mesh_shape),
            "donate": list(self.donate_argnums),
            "tree": str(treedef),
            "leaves": [_leaf_sig(x) for x in leaves],
            "rules": [[p, [str(a) for a in s]]
                      for p, s in (self.partition_rules or [])],
            "extra": _resolve(self._fingerprint_extra),
        }
        blob = json.dumps(payload, sort_keys=True, default=str)
        return hashlib.blake2b(blob.encode(), digest_size=16).hexdigest()
