"""mxnet_tpu.programs — one registry for every compiled program.

The compiled-program plumbing that used to be hand-threaded three
separate times (``CompiledTrainStep`` / ``CompiledEvalStep`` /
``DecodePredictor``) lives here once (docs/programs.md):

* :mod:`~mxnet_tpu.programs.spec` — :class:`ProgramSpec` (name,
  abstract args, donation map, partition rules, trace counters ->
  artifact / roofline cost / fingerprint) and the shared ``_probing``
  guard helpers;
* :mod:`~mxnet_tpu.programs.partition` — regex partition rules over
  named param trees (the fmengine ``match_partition_rules`` idiom);
* :mod:`~mxnet_tpu.programs.aot` — AOT-serialized executables in a
  content-addressed on-disk cache (``MXNET_AOT`` /
  ``MXNET_PROGRAM_CACHE``), so fleet hosts cold-start by
  DESERIALIZING their serving programs instead of retracing them;
* :mod:`~mxnet_tpu.programs.registry` — the live-spec registry plus
  the canonical catalog ``tools/mxlint.py`` enumerates.
"""
from . import aot, partition, registry
from .aot import AOT_STATS, AotDispatch
from .partition import build_shardings, match_partition_rules, \
    rules_from_plan
from .registry import REGISTRY, ProgramRegistry
from .spec import ProgramSpec, probe_artifact, probe_cost, \
    probe_lowered_text, probing

__all__ = ["AOT_STATS", "AotDispatch", "ProgramRegistry", "ProgramSpec",
           "REGISTRY", "aot", "build_shardings", "match_partition_rules",
           "partition", "probe_artifact", "probe_cost",
           "probe_lowered_text", "probing", "registry",
           "rules_from_plan"]
