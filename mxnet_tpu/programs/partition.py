"""Regex partition rules over named param trees.

The fmengine ``match_partition_rules`` idiom: a sharding plan is a list
of ``(regex, PartitionSpec)`` rules matched against parameter NAMES,
not a hand-built per-leaf pspec tree.  This is the ONE pspec path for
registered programs — the decode parameter placement funnels its
Megatron graph-walk plan through :func:`rules_from_plan` +
:func:`build_shardings`, and a user-supplied rule list (e.g.
``[("ffn.*weight", P("model", None)), (".*", P())]``) drops into the
same matcher.

Degrade semantics match the placement code this replaces: a rule whose
spec rank differs from the leaf's, or whose sharded dims don't divide
by the mesh axis, REPLICATES that leaf instead of failing — checkpoint
shapes vary, placement must not.
"""
from __future__ import annotations

import re

import numpy as np

__all__ = ["match_partition_rules", "build_shardings", "rules_from_plan"]


def _as_spec(spec):
    from jax.sharding import PartitionSpec as P

    return spec if isinstance(spec, P) else P(*spec)


def _exact_table(rules):
    """``{literal_name: spec}`` when EVERY rule is an exact-name anchor
    (``^<re.escape(name)>$`` — what :func:`rules_from_plan` emits), else
    None.  Exact plans then match by one dict lookup per leaf instead
    of scanning P regexes for each of P params — the graph-walk plan's
    O(P) cost must not become O(P^2) for riding the regex front door.
    First rule wins, like the scan."""
    table = {}
    for patt, spec in rules or ():
        if not (isinstance(patt, str) and patt.startswith("^")
                and patt.endswith("$")):
            return None
        body = patt[1:-1]
        literal = re.sub(r"\\(.)", r"\1", body)
        if re.escape(literal) != body:
            return None
        table.setdefault(literal, spec)
    return table


def match_partition_rules(rules, named_leaves, default=(), coverage=None):
    """``{name: PartitionSpec}`` via first-matching regex per name.

    ``named_leaves`` maps parameter names to shape-bearing leaves
    (arrays or avals).  Scalars and single-element leaves always
    replicate; an unmatched name takes ``default`` (replicated unless
    told otherwise).  ``re.search`` semantics, like fmengine — anchor
    with ``^...$`` for exact names (:func:`rules_from_plan` does).

    ``coverage``, when a dict, receives one record per leaf —
    ``{"shape": [...], "spec": [...], "source": "scalar|rule|default"}``
    — the raw material of the sharding-coverage lint pass: which leaves
    a rule claimed, which fell through to the default.
    """
    from jax.sharding import PartitionSpec as P

    def note(name, shape, spec, source):
        if coverage is not None:
            coverage[name] = {"shape": [int(d) for d in shape],
                              "spec": [str(a) if a is not None else None
                                       for a in spec],
                              "source": source}

    exact = _exact_table(rules)
    out = {}
    for name, leaf in named_leaves.items():
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            out[name] = P()
            note(name, shape, (), "scalar")
            continue
        if exact is not None:
            hit = exact.get(name)
            out[name] = _as_spec(hit if hit is not None else default)
            note(name, shape, out[name],
                 "rule" if hit is not None else "default")
            continue
        for patt, spec in rules or ():
            if re.search(patt, name) is not None:
                out[name] = _as_spec(spec)
                note(name, shape, out[name], "rule")
                break
        else:
            out[name] = _as_spec(default)
            note(name, shape, out[name], "default")
    return out


def build_shardings(mesh, rules, named_leaves, default=(), coverage=None):
    """``{name: NamedSharding}`` for a named param tree under ``mesh``.

    Applies :func:`match_partition_rules`, then the divisibility guard:
    a matched spec is honored only when its rank equals the leaf's and
    every sharded dim divides by its mesh axis size — otherwise the
    leaf replicates (the same degrade rule the decode placement has
    always used, now in the one shared matcher).

    ``coverage``, when a dict, gets the per-leaf match records
    (see :func:`match_partition_rules`) with a ``"degrade"`` key
    (``"rank-mismatch"`` or ``"indivisible"``) stamped on every leaf
    the guard silently replicated — the degrade used to vanish; now the
    sharding-coverage pass makes it an error naming the param."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sizes = dict(mesh.shape)
    specs = match_partition_rules(rules, named_leaves, default,
                                  coverage=coverage)
    out = {}
    for name, leaf in named_leaves.items():
        spec = specs[name]
        shape = tuple(getattr(leaf, "shape", ()) or ())
        ok = len(spec) == len(shape) and all(
            ax is None or shape[d] % sizes.get(ax, 1) == 0
            for d, ax in enumerate(spec))
        if not ok and coverage is not None and len(spec) \
                and coverage.get(name, {}).get("source") != "default":
            coverage[name]["degrade"] = ("rank-mismatch"
                                         if len(spec) != len(shape)
                                         else "indivisible")
        out[name] = NamedSharding(mesh, spec if ok else P())
    return out


def rules_from_plan(plan):
    """Exact-name regex rules from a ``{name: axis-tuple}`` plan — the
    bridge that funnels the existing Megatron graph walk
    (``parallel.tp_rules.plan_tensor_parallel``) through the one regex
    matcher, so graph-derived and hand-written rules share a code
    path."""
    return [("^" + re.escape(name) + "$", tuple(spec))
            for name, spec in (plan or {}).items()]
