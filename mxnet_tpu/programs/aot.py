"""AOT-serialized executables — the fleet cold-start diet.

Every fleet host used to pay a full trace -> lower -> compile for each
of its serving programs (chunk prefill, decode step, verify, page
extract/install, commit, fork) before it could serve a single token —
the dominant cold-start cost.  This module turns program preparation
into a deserialize:

* :func:`save` serializes a compiled executable
  (``jax.experimental.serialize_executable``) into a CONTENT-ADDRESSED
  on-disk cache: ``<MXNET_PROGRAM_CACHE>/<fingerprint>.aotx`` (pickled
  ``(payload, in_tree, out_tree)``) plus a ``.json`` sidecar describing
  what the key hashes.  The fingerprint
  (:meth:`~mxnet_tpu.programs.spec.ProgramSpec.fingerprint`) covers the
  abstract args, donation map, partition rules, jax version, backend,
  mesh shape and the caller's identity extras — so a jax upgrade, a
  dtype/page-size change or a different model graph is a key MISS, not
  a wrong program.
* :func:`load` deserializes a cached executable; corrupt or
  incompatible entries log a VISIBLE warning and fall back to the JIT
  path (a cold start is slower, never wrong).
* :func:`load_or_compile` is the pipeline a call site drives per
  program: cache hit -> deserialize (milliseconds); miss -> trace +
  lower + compile now and save the result back, so the NEXT host's cold
  start is a deserialize.

:class:`AotDispatch` is the callable facade a program owner installs in
place of its raw ``jax.jit`` handle: dispatches to the armed executable
(donation and numerics identical — it IS the same program), falls back
to the JIT path on an aval mismatch (counted, warned once), and
delegates ``.lower``/``.trace`` to the jit fn so every artifact/FLOP
probe keeps working unchanged.

Arming: ``MXNET_AOT=1`` (off by default — nothing changes for existing
paths), cache directory from ``MXNET_PROGRAM_CACHE`` (default
``~/.cache/mxnet_tpu/programs``).  ``AOT_STATS`` carries the process
counters the bench contract publishes (hits / misses / saves / errors /
fallbacks).
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import tempfile

__all__ = ["AOT_STATS", "AotDispatch", "enabled", "cache_dir",
           "load", "save", "load_or_compile", "reset_stats"]

log = logging.getLogger(__name__)

# process-wide accounting (mirrored into the obs registry lazily so a
# scrape sees them; the python ints stay the bench's source of truth)
AOT_STATS = {"hits": 0, "misses": 0, "saves": 0, "errors": 0,
             "fallbacks": 0}

_DEFAULT_DIR = os.path.join("~", ".cache", "mxnet_tpu", "programs")


def reset_stats():
    for k in AOT_STATS:
        AOT_STATS[k] = 0


def _note(kind, n=1):
    AOT_STATS[kind] += n
    try:
        from .. import obs as _obs

        _obs.registry.counter(
            "mx_aot_" + kind,
            "AOT program cache %s" % kind).inc(n)
    except Exception:
        pass


def enabled():
    """Whether the AOT pipeline is armed (``MXNET_AOT``)."""
    from .. import config as _config

    return bool(_config.get("MXNET_AOT"))


def cache_dir(create=False):
    """The program-cache directory (``MXNET_PROGRAM_CACHE``, default
    ``~/.cache/mxnet_tpu/programs``), created on demand."""
    from .. import config as _config

    path = _config.get("MXNET_PROGRAM_CACHE") or _DEFAULT_DIR
    path = os.path.expanduser(path)
    if create:
        os.makedirs(path, exist_ok=True)
    return path


def _paths(key):
    d = cache_dir()
    return os.path.join(d, key + ".aotx"), os.path.join(d, key + ".json")


def save(key, compiled, meta=None):
    """Serialize ``compiled`` under content address ``key`` (atomic
    write: tmp + rename).  Returns True on success; serialization
    failures are warned and swallowed — the cache is an accelerator,
    never a correctness dependency."""
    from jax.experimental import serialize_executable as _se

    blob_path, meta_path = _paths(key)
    try:
        payload, in_tree, out_tree = _se.serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree))
        cache_dir(create=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(blob_path),
                                   prefix=".aot_tmp_")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, blob_path)
        except BaseException:
            os.unlink(tmp)
            raise
        with open(meta_path, "w") as f:
            json.dump(dict(meta or {}, key=key, bytes=len(blob)), f,
                      indent=2, sort_keys=True)
            f.write("\n")
        _note("saves")
        return True
    except Exception as exc:
        _note("errors")
        log.warning("AOT cache save failed for %s (%s); the program "
                    "stays JIT-compiled in this process", key, exc)
        return False


def load(key, name="program"):
    """Deserialize the executable under ``key``; None on a miss.  A
    corrupt/incompatible entry warns VISIBLY and reads as a miss (the
    caller falls back to trace+compile)."""
    from jax.experimental import serialize_executable as _se

    blob_path, _ = _paths(key)
    if not os.path.exists(blob_path):
        return None
    try:
        with open(blob_path, "rb") as f:
            payload, in_tree, out_tree = pickle.loads(f.read())
        return _se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception as exc:
        _note("errors")
        log.warning("AOT cache entry %s for %r failed to load (%s); "
                    "falling back to trace+compile", key, name, exc)
        return None


def load_or_compile(spec, args, save_ok=True, warn_miss=True):
    """The per-program AOT pipeline: fingerprint -> cache hit
    (deserialize) or miss (``spec.compiled(args)`` now, saved back when
    ``save_ok``).  Returns ``(executable, source, key)`` with source in
    {"cache", "compile"}; ``(None, "jit", key)`` when compilation
    itself fails (the caller keeps the plain JIT path)."""
    key = spec.fingerprint(args)
    exe = load(key, spec.name)
    if exe is not None:
        _note("hits")
        return exe, "cache", key
    _note("misses")
    if warn_miss and os.path.isdir(cache_dir()):
        log.warning("AOT cache miss for program %r (key %s): tracing + "
                    "compiling now; the executable will be cached for "
                    "the next cold start", spec.name, key)
    try:
        compiled = spec.compiled(args)
    except Exception as exc:
        _note("errors")
        log.warning("AOT compile of %r failed (%s); keeping the JIT "
                    "dispatch path", spec.name, exc)
        return None, "jit", key
    if save_ok:
        save(key, compiled, meta={"name": spec.name})
    return compiled, "compile", key


def _trace_clean():
    """True when no jax trace is in progress — an armed executable must
    only see CONCRETE arguments; under tracing (eval_shape probes, an
    enclosing jit) the dispatch routes straight to the jit fn."""
    try:
        from jax.core import trace_state_clean
    except ImportError:
        return True
    return trace_state_clean()


class AotDispatch:
    """Callable facade over one jitted program.

    Starts as a transparent pass-through to the ``jax.jit`` fn.
    :meth:`arm` installs an AOT executable (deserialized or freshly
    compiled); calls then dispatch to it — same program, same donation,
    same numerics, zero traces.  An argument signature the armed
    executable was not compiled for falls back to the JIT path
    (counted in ``AOT_STATS['fallbacks']``, warned once per dispatch) —
    slower, never wrong.  Probe surfaces (``.lower``/``.trace``/
    ``.eval_shape``) always delegate to the jit fn so artifacts, FLOP
    text and roofline costs keep working unchanged.
    """

    _MAX_ARMED = 4

    def __init__(self, name, fn):
        self.name = name
        self.fn = fn
        self._armed = []        # [(executable, key)] most-recent-hit first
        self.source = "jit"     # "cache" | "compile" | "jit"
        self.key = None         # fingerprint of the primary executable
        self._warned = False

    def arm(self, executable, source, key=None):
        """Install an executable (newest first; bounded)."""
        self._armed.insert(0, (executable, key))
        del self._armed[self._MAX_ARMED:]
        self.source = source
        self.key = key

    def disarm(self):
        self._armed = []
        self.source = "jit"
        self.key = None

    @property
    def armed(self):
        return bool(self._armed)

    def __call__(self, *args):
        if self._armed and not _trace_clean():
            return self.fn(*args)
        for i, (exe, key) in enumerate(self._armed):
            try:
                out = exe(*args)
            except TypeError:
                # aval mismatch — try the next armed signature, then JIT
                continue
            if i:
                self._armed.insert(0, self._armed.pop(i))
            return out
        if self._armed:
            _note("fallbacks")
            if not self._warned:
                self._warned = True
                log.warning(
                    "AOT-loaded program %r saw an argument signature it "
                    "was not compiled for; dispatching through JIT "
                    "(slower, traced) for such calls", self.name)
        return self.fn(*args)

    # probe delegation — artifacts/FLOP text/roofline never notice
    def lower(self, *args, **kw):
        return self.fn.lower(*args, **kw)

    def trace(self, *args, **kw):
        return self.fn.trace(*args, **kw)

    def eval_shape(self, *args, **kw):
        return self.fn.eval_shape(*args, **kw)
