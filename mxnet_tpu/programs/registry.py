"""The program registry — live specs + the canonical lint catalog.

Two namespaces, one registry object:

* **Live programs** — every :class:`~mxnet_tpu.programs.spec.
  ProgramSpec` a call site registers (``registry.register(spec)``,
  latest wins per name, weakly owned).  ``registry.trace_report()``
  folds their retrace counters into one accounting view; artifacts and
  roofline costs come off the specs themselves.
* **Canonical programs** — the programs ``tools/mxlint.py`` audits.
  ``analysis/programs.py`` REGISTERS builder groups here (a builder
  drives a real workload and returns ``[(name, artifact), ...]``);
  mxlint enumerates ``canonical_names()`` and calls
  ``build_canonical()`` instead of importing a hand-maintained tuple —
  adding the 13th canonical program is one ``register_canonical``
  call, not edits across three files.
"""
from __future__ import annotations

import weakref

from ..base import MXNetError

__all__ = ["ProgramRegistry", "REGISTRY", "register", "get", "names",
           "register_canonical", "canonical_names", "build_canonical",
           "trace_report"]


class ProgramRegistry:
    """Name -> :class:`ProgramSpec` (live), plus the canonical builder
    catalog the lint enumerates."""

    def __init__(self):
        self._specs = {}        # name -> weakref to ProgramSpec
        self._canonical = []    # ordered canonical names
        self._groups = {}       # name -> (group_key, builder, availability)

    # ------------------------------------------------------------------
    # live programs
    # ------------------------------------------------------------------
    def register(self, spec):
        """Register (or refresh) a live program spec; latest wins —
        the same refresh rule as the roofline's static probers.  Held
        WEAKLY: the registering call site owns the spec (the spec in
        turn owns a jitted fn closing over real model state, which a
        process-global table must never pin); a collected owner's entry
        simply evaporates."""
        self._specs[spec.name] = weakref.ref(spec)
        return spec

    def get(self, name):
        ref = self._specs.get(name)
        spec = ref() if ref is not None else None
        if ref is not None and spec is None:
            del self._specs[name]
        return spec

    def names(self):
        return sorted(n for n in list(self._specs)
                      if self.get(n) is not None)

    def trace_report(self):
        """``{name: {"trace_count", "expected_traces"}}`` over every
        live spec whose owner is still alive — the registry-native
        retrace accounting."""
        from .spec import _resolve

        out = {}
        for name in self.names():
            spec = self.get(name)
            if spec is None or (spec._owner is not None
                                and spec.owner() is None):
                continue
            out[name] = {
                "trace_count": _resolve(spec._trace_count),
                "expected_traces": _resolve(spec._expected_traces),
            }
        return out

    # ------------------------------------------------------------------
    # canonical catalog (the mxlint surface)
    # ------------------------------------------------------------------
    def register_canonical(self, names, builder, availability=None):
        """Register a builder group producing the canonical programs
        ``names`` (in catalog order).  ``builder(want)`` receives the
        subset of its names requested and returns ``[(name, artifact),
        ...]``; ``availability()`` returns None when buildable on this
        host, else a human-readable reason (surfaced as a skip note).
        """
        key = tuple(names)
        for name in names:
            if name in self._groups:
                raise MXNetError("canonical program %r registered twice"
                                 % name)
            self._canonical.append(name)
            self._groups[name] = (key, builder, availability)

    def canonical_names(self):
        return tuple(self._canonical)

    def build_canonical(self, names=None):
        """Build the requested canonical artifacts (default: all).

        Returns ``(artifacts, notes)`` — ``notes`` maps unbuildable
        programs to the reason, so the caller surfaces the gap instead
        of silently auditing a smaller set."""
        want = list(names) if names else list(self._canonical)
        unknown = [n for n in want if n not in self._groups]
        if unknown:
            raise MXNetError("unknown canonical program(s) %s; known: %s"
                             % (unknown, list(self._canonical)))
        artifacts, notes, done = [], {}, set()
        for name in want:
            key, builder, availability = self._groups[name]
            if key in done:
                continue
            done.add(key)
            group_want = [n for n in key if n in want]
            if availability is not None:
                reason = availability()
                if reason is not None:
                    for n in group_want:
                        notes[n] = reason
                    continue
            built = dict(builder(group_want))
            missing = [n for n in group_want if n not in built]
            if missing:
                raise MXNetError("canonical builder for %s did not "
                                 "produce %s" % (list(key), missing))
            for n in group_want:
                art = built[n]
                art.name = n
                artifacts.append(art)
        order = {n: i for i, n in enumerate(self._canonical)}
        artifacts.sort(key=lambda a: order.get(a.name, len(order)))
        return artifacts, notes


REGISTRY = ProgramRegistry()

# module-level conveniences bound to the process-wide registry
register = REGISTRY.register
get = REGISTRY.get
names = REGISTRY.names
register_canonical = REGISTRY.register_canonical
canonical_names = REGISTRY.canonical_names
build_canonical = REGISTRY.build_canonical
trace_report = REGISTRY.trace_report
