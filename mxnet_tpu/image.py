"""Image pipeline: decode, geometric/photometric augmenters, image iterators.

Capability parity with the reference's ``python/mxnet/image.py`` +
``src/io/iter_image_recordio.cc`` / ``image_aug_default.cc``, re-designed:

* augmenters are single-image -> single-image callables with an explicit
  per-pipeline ``numpy.random.Generator`` (reproducible via ``seed``;
  the reference uses process-global RNG state);
* the sample stream is split out into small Source objects (record file,
  image list / directory) so the iterator body is only batching+augmenting;
* batches are assembled HWC and transposed to NCHW once, at the end.

Decode uses cv2 when available and falls back to the raw-array codec in
``recordio`` otherwise (TPU hosts often have no OpenCV).
"""
from __future__ import annotations

import os

import numpy as np

from .base import MXNetError
from . import io as io_mod
from . import ndarray as nd
from . import recordio
from .io import DataBatch, DataDesc, DataIter

__all__ = ["imdecode", "scale_down", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "ResizeAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "RandomOrderAug",
           "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "HorizontalFlipAug", "CastAug", "CreateAugmenter", "ImageIter",
           "ImageRecordIter", "DetAugmenter", "DetHorizontalFlipAug",
           "DetRandomCropAug", "DetBorderAug", "CreateDetAugmenter",
           "ImageDetIter", "ImageDetRecordIter"]

_LUMA = np.array([0.299, 0.587, 0.114], np.float32)  # ITU-R BT.601


def _cv2():
    try:
        import cv2
        return cv2
    except ImportError:
        return None


def imdecode(buf, flag=1, to_rgb=True):
    """Decode a compressed image buffer to an HWC uint8 array."""
    cv2 = _cv2()
    if cv2 is None:
        raise MXNetError("imdecode needs cv2; store raw-array records when "
                         "OpenCV is unavailable")
    img = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), flag)
    if img is None:
        raise MXNetError("imdecode failed (truncated or unsupported buffer)")
    return img[:, :, ::-1] if to_rgb else img


def _resize(img, w, h, interp=1):
    cv2 = _cv2()
    if cv2 is not None:
        return cv2.resize(img, (w, h), interpolation=interp)
    # nearest-neighbor fallback via index maps
    rows = np.minimum((np.arange(h) * img.shape[0]) // h, img.shape[0] - 1)
    cols = np.minimum((np.arange(w) * img.shape[1]) // w, img.shape[1] - 1)
    return img[rows[:, None], cols[None, :]]


# -- functional geometry ----------------------------------------------------


def scale_down(src_size, size):
    """Shrink the requested crop size to fit inside the source, keeping
    aspect."""
    sw, sh = src_size
    w, h = size
    if sh < h:
        w, h = w * sh / h, sh
    if sw < w:
        w, h = sw, h * sw / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the SHORTER edge equals ``size`` exactly (the longer edge
    rounds to preserve aspect)."""
    h, w = src.shape[:2]
    if h <= w:
        new_h, new_w = size, max(1, int(round(w * size / h)))
    else:
        new_h, new_w = max(1, int(round(h * size / w))), size
    return _resize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    window = src[y0:y0 + h, x0:x0 + w]
    if size is not None and size != (w, h):
        window = _resize(window, size[0], size[1], interp)
    return window


def _rng_of(rng):
    return rng if rng is not None else np.random.default_rng()


def random_crop(src, size, interp=2, rng=None):
    rng = _rng_of(rng)
    h, w = src.shape[:2]
    cw, ch = scale_down((w, h), size)
    x0 = int(rng.integers(0, w - cw + 1))
    y0 = int(rng.integers(0, h - ch + 1))
    return fixed_crop(src, x0, y0, cw, ch, size, interp), (x0, y0, cw, ch)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    cw, ch = scale_down((w, h), size)
    x0, y0 = (w - cw) // 2, (h - ch) // 2
    return fixed_crop(src, x0, y0, cw, ch, size, interp), (x0, y0, cw, ch)


def random_size_crop(src, size, min_area, ratio, interp=2, rng=None,
                     attempts=10):
    """Crop a random area/aspect window (Inception-style), falling back to a
    center crop when no attempt fits."""
    rng = _rng_of(rng)
    h, w = src.shape[:2]
    for _ in range(attempts):
        target_area = rng.uniform(min_area, 1.0) * w * h
        aspect = rng.uniform(*ratio)
        cw = int(round(np.sqrt(target_area * aspect)))
        ch = int(round(np.sqrt(target_area / aspect)))
        if rng.random() < 0.5:
            cw, ch = ch, cw
        if cw <= w and ch <= h:
            x0 = int(rng.integers(0, w - cw + 1))
            y0 = int(rng.integers(0, h - ch + 1))
            return fixed_crop(src, x0, y0, cw, ch, size, interp), \
                (x0, y0, cw, ch)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    out = src.astype(np.float32) - mean
    return out if std is None else out / std


# -- augmenters -------------------------------------------------------------
#
# An augmenter is a callable (img) -> img carrying its own Generator.  The
# factory names mirror the reference API; seed= gives reproducibility.


class Augmenter:
    def __init__(self, fn, rng=None):
        self._fn = fn
        self.rng = _rng_of(rng)

    def __call__(self, img):
        return self._fn(img, self.rng)


def ResizeAug(size, interp=2, seed=None):
    return Augmenter(lambda img, rng: resize_short(img, size, interp),
                     np.random.default_rng(seed))


def RandomCropAug(size, interp=2, seed=None):
    return Augmenter(
        lambda img, rng: random_crop(img, size, interp, rng)[0],
        np.random.default_rng(seed))


def RandomSizedCropAug(size, min_area, ratio, interp=2, seed=None):
    return Augmenter(
        lambda img, rng: random_size_crop(img, size, min_area, ratio,
                                          interp, rng)[0],
        np.random.default_rng(seed))


def CenterCropAug(size, interp=2, seed=None):
    return Augmenter(lambda img, rng: center_crop(img, size, interp)[0],
                     np.random.default_rng(seed))


def HorizontalFlipAug(p, seed=None):
    return Augmenter(
        lambda img, rng: img[:, ::-1] if rng.random() < p else img,
        np.random.default_rng(seed))


def CastAug(seed=None):
    return Augmenter(lambda img, rng: img.astype(np.float32),
                     np.random.default_rng(seed))


def ColorNormalizeAug(mean, std, seed=None):
    return Augmenter(lambda img, rng: color_normalize(img, mean, std),
                     np.random.default_rng(seed))


def RandomOrderAug(members, seed=None):
    """Apply every member augmenter, in a freshly shuffled order per image."""
    members = list(members)

    def apply(img, rng):
        order = rng.permutation(len(members))
        for i in order:
            img = members[i](img)
        return img

    return Augmenter(apply, np.random.default_rng(seed))


def _jitter(img, alpha, toward):
    """Blend img toward a target frame: alpha*img + (1-alpha)*toward."""
    return img * alpha + toward * (1.0 - alpha)


def ColorJitterAug(brightness, contrast, saturation, seed=None):
    """Random brightness/contrast/saturation jitter, shuffled order.

    Each member augmenter gets an independent generator derived from
    ``seed`` (SeedSequence spawn), so a seeded pipeline is fully
    reproducible and the three jitters stay uncorrelated.
    """
    ss = seed if isinstance(seed, np.random.SeedSequence) \
        else np.random.SeedSequence(seed)
    children = iter(ss.spawn(4))
    members = []
    if brightness > 0:
        def jitter_b(img, rng):
            return img * (1.0 + rng.uniform(-brightness, brightness))
        members.append(Augmenter(jitter_b,
                                 np.random.default_rng(next(children))))
    if contrast > 0:
        def jitter_c(img, rng):
            alpha = 1.0 + rng.uniform(-contrast, contrast)
            mean_luma = (img * _LUMA).sum() / (img.size / 3)
            return _jitter(img, alpha, mean_luma)
        members.append(Augmenter(jitter_c,
                                 np.random.default_rng(next(children))))
    if saturation > 0:
        def jitter_s(img, rng):
            alpha = 1.0 + rng.uniform(-saturation, saturation)
            luma = (img * _LUMA).sum(axis=2, keepdims=True)
            return _jitter(img, alpha, luma)
        members.append(Augmenter(jitter_s,
                                 np.random.default_rng(next(children))))
    return RandomOrderAug(members, next(children))


def LightingAug(alphastd, eigval, eigvec, seed=None):
    """AlexNet-style PCA lighting noise."""
    def light(img, rng):
        alpha = rng.normal(0, alphastd, 3)
        return img + eigvec @ (alpha * eigval)

    return Augmenter(light, np.random.default_rng(seed))


# ImageNet RGB PCA basis (AlexNet paper) and torchvision-convention moments
_IMAGENET_EIGVAL = np.array([55.46, 4.794, 1.148])
_IMAGENET_EIGVEC = np.array([[-0.5675, 0.7192, 0.4009],
                             [-0.5808, -0.0045, -0.8140],
                             [-0.5836, -0.6948, 0.4203]])
_IMAGENET_MEAN = np.array([123.68, 116.28, 103.53])
_IMAGENET_STD = np.array([58.395, 57.12, 57.375])


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2,
                    seed=None, cast=True):
    """Assemble the standard training/eval chain: resize -> crop -> flip ->
    cast -> photometric -> normalize.

    Every random augmenter gets its own generator spawned from ``seed``
    (independent streams; reproducible when seed is set).
    """
    spawn = iter(np.random.SeedSequence(seed).spawn(8))
    chain = []
    if resize > 0:
        chain.append(ResizeAug(resize, inter_method, next(spawn)))
    crop = (data_shape[2], data_shape[1])
    if rand_resize:
        if not rand_crop:
            raise ValueError("rand_resize requires rand_crop")
        chain.append(RandomSizedCropAug(crop, 0.3, (3 / 4, 4 / 3),
                                        inter_method, next(spawn)))
    elif rand_crop:
        chain.append(RandomCropAug(crop, inter_method, next(spawn)))
    else:
        chain.append(CenterCropAug(crop, inter_method, next(spawn)))
    if rand_mirror:
        chain.append(HorizontalFlipAug(0.5, next(spawn)))
    if cast:
        chain.append(CastAug())
    if brightness or contrast or saturation:
        chain.append(ColorJitterAug(brightness, contrast, saturation,
                                    next(spawn)))
    if pca_noise > 0:
        chain.append(LightingAug(pca_noise, _IMAGENET_EIGVAL,
                                 _IMAGENET_EIGVEC, next(spawn)))
    if mean is True:
        mean = _IMAGENET_MEAN
    if std is True:
        std = _IMAGENET_STD
    if mean is not None and getattr(mean, "shape", None):
        chain.append(ColorNormalizeAug(mean, std))
    return chain


# -- sample sources ---------------------------------------------------------


class _RecordSource:
    """Samples from a RecordIO file, optionally index-seekable."""

    def __init__(self, path_imgrec, path_imgidx):
        if path_imgidx:
            self._rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec,
                                                   "r")
            self.keys = list(self._rec.keys)
        else:
            self._rec = recordio.MXRecordIO(path_imgrec, "r")
            self.keys = None

    def reset(self):
        self._rec.reset()

    def read(self, key=None):
        """(label, payload) — by key when index-backed, else sequential."""
        blob = self._rec.read_idx(key) if key is not None else \
            self._rec.read()
        if blob is None:
            raise StopIteration
        header, payload = recordio.unpack(blob)
        return header.label, payload


class _ListSource:
    """Samples named by an image-list (key -> (label, filename))."""

    def __init__(self, entries, path_root):
        self.table = entries
        self.keys = list(entries)
        self.root = path_root or "."

    def reset(self):
        pass

    def read(self, key):
        label, fname = self.table[key]
        with open(os.path.join(self.root, fname), "rb") as f:
            return label, f.read()


def _parse_imglist_file(path):
    entries = {}
    with open(path) as f:
        for line in f:
            cols = line.strip().split("\t")
            if not cols or not cols[0]:
                continue
            entries[int(cols[0])] = (
                np.array([float(v) for v in cols[1:-1]], np.float32),
                cols[-1])
    return entries


class ImageIter(DataIter):
    """Batched, augmented image iterator over .rec files or image lists.

    Combines a sample source, an augmenter chain, and batch assembly; decode
    failures fall back to the raw-array record codec.  ``seed`` makes the
    shuffle + augmenter randomness reproducible.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", seed=None,
                 preprocess_threads=4, dtype="float32", **kwargs):
        super().__init__(batch_size)
        self._rng = np.random.default_rng(seed)
        # dtype="uint8": assemble and ship uint8 batches (4x less host ->
        # device traffic; the compiled train step casts/normalizes on
        # device).  The TPU-first input recipe: photometric/normalize
        # augmenters need float and are rejected at batch time.
        self._dtype = np.dtype(dtype)
        # parallel DECODE pool (the C++ reader's preprocess_threads analog,
        # iter_image_recordio.cc): cv2 imdecode releases the GIL so threads
        # overlap; augmentation stays on the caller thread because the
        # augmenters carry sequential per-pipeline RNG state
        self._pool = None
        if preprocess_threads and preprocess_threads > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(max_workers=preprocess_threads,
                                            thread_name_prefix="mxtpu-decode")

        # choose a source; a list/imglist overrides record labels
        self._labels = None
        if path_imglist:
            self._labels = _parse_imglist_file(path_imglist)
        elif isinstance(imglist, list):
            self._labels = {i + 1: (np.asarray(row[:-1], np.float32),
                                    row[-1])
                            for i, row in enumerate(imglist)}
        if path_imgrec:
            if self._labels and not path_imgidx:
                raise MXNetError(
                    "an external label list over a record file needs "
                    "path_imgidx (records must be fetched by key)")
            self._source = _RecordSource(path_imgrec, path_imgidx)
            self._order = list(self._labels) if self._labels else \
                self._source.keys
        elif self._labels:
            self._source = _ListSource(self._labels, path_root)
            self._order = self._source.keys
        else:
            raise MXNetError("ImageIter needs path_imgrec, path_imglist, or "
                             "imglist")

        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        if num_parts > 1:
            if self._order is None:
                # silently iterating the full set would duplicate every
                # sample across workers — fail loudly instead (sequential
                # record files can't be sharded; supply path_imgidx)
                raise MXNetError(
                    "num_parts > 1 needs a keyed source to shard "
                    "(path_imgidx for record files, or an image list)")
            span = len(self._order) // num_parts
            self._order = self._order[part_index * span:
                                      (part_index + 1) * span]

        if aug_list is None:
            aug_keys = ("resize", "rand_crop", "rand_resize", "rand_mirror",
                        "mean", "std", "brightness", "contrast",
                        "saturation", "pca_noise", "inter_method")
            if self._dtype == np.uint8:
                for k in ("mean", "std", "brightness", "contrast",
                          "saturation", "pca_noise"):
                    v = kwargs.get(k)
                    # mean/std arrive as arrays (ambiguous truth value)
                    if v is not None and np.any(v):
                        raise MXNetError(
                            "dtype='uint8' keeps batches integral; "
                            "%r needs float math — normalize on device "
                            "instead (cast + scale in the graph)" % k)
            aug_list = CreateAugmenter(
                data_shape, seed=seed, cast=self._dtype != np.uint8,
                **{k: v for k, v in kwargs.items() if k in aug_keys})
        self.auglist = aug_list

        label_shape = (batch_size, label_width) if label_width > 1 \
            else (batch_size,)
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape,
                                      dtype=self._dtype)]
        self.provide_label = [DataDesc(label_name, label_shape)]
        self._cursor = 0
        self.reset()

    def reset(self):
        self._cursor = 0
        self._source.reset()
        if self.shuffle and self._order is not None:
            self._rng.shuffle(self._order)

    # -- sample stream -----------------------------------------------------
    def _next_raw(self):
        """(label, undecoded payload) for the next sample — the one copy of
        the order/cursor/label-override protocol (det iterator reuses it)."""
        if self._order is not None:
            if self._cursor >= len(self._order):
                raise StopIteration
            key = self._order[self._cursor]
            self._cursor += 1
            label, payload = self._source.read(key)
            if self._labels is not None:
                label = self._labels[key][0]
            return label, payload
        return self._source.read()

    def next_sample(self):
        """(label, decoded HWC image) for the next sample."""
        label, payload = self._next_raw()
        return label, self._decode(payload, label)

    def _decode(self, payload, label):
        if not isinstance(payload, bytes):
            return payload
        try:
            return imdecode(payload)
        except MXNetError:
            _, arr = recordio.unpack_img(
                recordio.pack(recordio.IRHeader(0, label, 0, 0), payload))
            return arr

    def close(self):
        """Release the decode thread pool (also runs at GC)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __del__(self):
        self.close()

    def _collect_decoded(self, n):
        """Up to ``n`` (label, decoded image) pairs; raw reads are
        sequential (cheap), decodes run on the thread pool."""
        overridden = type(self).next_sample is not ImageIter.next_sample
        if overridden:
            # honor the documented next_sample() extension hook: subclass
            # overrides see every sample (sequential, no pool)
            out = []
            for _ in range(n):
                try:
                    out.append(self.next_sample())
                except StopIteration:
                    break
            if not out:
                raise StopIteration
            return out
        raws = []
        for _ in range(n):
            try:
                raws.append(self._next_raw())
            except StopIteration:
                break
        if not raws:
            raise StopIteration
        if self._pool is not None and len(raws) > 1:
            decoded = list(self._pool.map(
                lambda lp: self._decode(lp[1], lp[0]), raws))
        else:
            decoded = [self._decode(p, l) for l, p in raws]
        return [(l, img) for (l, _), img in zip(raws, decoded)]

    # -- batching ----------------------------------------------------------
    def next(self):
        c, h, w = self.data_shape
        # assemble NCHW directly: one strided store per image instead of an
        # NHWC store plus a whole-batch transposed copy (the assembly cost
        # matters — on a 1-core host it was ~35% of pipeline time,
        # benchmarks/bench_input_pipeline.py)
        images = np.zeros((self.batch_size, c, h, w), self._dtype)
        label_shape = self.provide_label[0].shape
        labels = np.zeros(label_shape, np.float32)
        samples = self._collect_decoded(self.batch_size)
        for filled, (label, img) in enumerate(samples):
            if img.ndim == 2:
                img = np.repeat(img[:, :, None], c, axis=2)
            for aug in self.auglist:
                img = aug(img)
            if self._dtype == np.uint8 and img.dtype != np.uint8:
                # a float augmenter slipped into a uint8 pipeline: numpy
                # would wrap negatives modulo 256 silently — fail instead
                raise MXNetError(
                    "dtype='uint8' batch received a %s image from the "
                    "augmenter chain; float augmenters (normalize/jitter) "
                    "are incompatible — normalize on device instead"
                    % img.dtype)
            if img.shape[:2] != (h, w):
                if self._dtype != np.uint8:
                    img = img.astype(np.float32)
                img = _resize(img, w, h)
            images[filled] = img.transpose(2, 0, 1)
            labels[filled] = label
        return DataBatch([nd.array(images)], [nd.array(labels)],
                         pad=self.batch_size - len(samples))


def ImageRecordIter(path_imgrec=None, data_shape=None, batch_size=None,
                    shuffle=False, mean_r=0, mean_g=0, mean_b=0,
                    std_r=1, std_g=1, std_b=1, rand_crop=False,
                    rand_mirror=False, preprocess_threads=4, num_parts=1,
                    part_index=0, path_imgidx=None, prefetch_buffer=4,
                    seed=None, dtype="float32", **kwargs):
    """RecordIO image pipeline (C++ ``ImageRecordIter`` analog): ImageIter
    decode+augment wrapped in a prefetch thread double-buffer."""
    mean = np.array([mean_r, mean_g, mean_b]) \
        if (mean_r or mean_g or mean_b) else None
    std = np.array([std_r, std_g, std_b]) \
        if (std_r, std_g, std_b) != (1, 1, 1) else None
    passthrough = ("resize", "rand_resize", "brightness", "contrast",
                   "saturation", "pca_noise", "inter_method")
    inner = ImageIter(batch_size=batch_size, data_shape=data_shape,
                      path_imgrec=path_imgrec, path_imgidx=path_imgidx,
                      shuffle=shuffle, rand_crop=rand_crop,
                      rand_mirror=rand_mirror, mean=mean, std=std,
                      num_parts=num_parts, part_index=part_index, seed=seed,
                      preprocess_threads=preprocess_threads, dtype=dtype,
                      **{k: v for k, v in kwargs.items() if k in passthrough})
    return io_mod.PrefetchingIter(inner, capacity=prefetch_buffer)


# ---------------------------------------------------------------------------
# Detection pipeline (reference: src/io/iter_image_det_recordio.cc +
# image_det_aug_default.cc).  Labels are object lists
# ``[header_width, object_width, ...header extras, (cls, xmin, ymin, xmax,
# ymax)*]`` with normalized [0,1] corner coordinates; augmenters transform
# boxes together with pixels.
# ---------------------------------------------------------------------------

class DetAugmenter:
    """Augmenter over (image, boxes): boxes is (N, >=5) [cls, x0, y0, x1, y1]
    in normalized coordinates."""

    def __init__(self, fn, rng=None):
        self._fn = fn
        self.rng = _rng_of(rng)

    def __call__(self, img, boxes):
        return self._fn(img, boxes, self.rng)


def DetHorizontalFlipAug(p, seed=None):
    """Mirror image and x-coordinates together (det_aug_default mirror)."""
    def flip(img, boxes, rng):
        if rng.random() < p:
            img = img[:, ::-1]
            boxes = boxes.copy()
            x0 = boxes[:, 1].copy()
            boxes[:, 1] = 1.0 - boxes[:, 3]
            boxes[:, 3] = 1.0 - x0
        return img, boxes

    return DetAugmenter(flip, np.random.default_rng(seed))


def DetRandomCropAug(min_object_covered=0.3, aspect_ratio_range=(0.75, 1.33),
                     area_range=(0.3, 1.0), max_attempts=20, seed=None):
    """Sample a crop keeping enough of the objects (SSD-style data aug,
    image_det_aug_default.cc crop sampling); boxes are clipped and
    re-normalized to the crop, fully-cropped-out objects dropped."""
    def crop(img, boxes, rng):
        h, w = img.shape[:2]
        for _ in range(max_attempts):
            area = rng.uniform(*area_range) * h * w
            ratio = rng.uniform(*aspect_ratio_range)
            cw = int(round(np.sqrt(area * ratio)))
            ch = int(round(np.sqrt(area / ratio)))
            if cw > w or ch > h or cw <= 0 or ch <= 0:
                continue
            x0 = rng.integers(0, w - cw + 1)
            y0 = rng.integers(0, h - ch + 1)
            cx0, cy0 = x0 / w, y0 / h
            cx1, cy1 = (x0 + cw) / w, (y0 + ch) / h
            if len(boxes):
                ix0 = np.maximum(boxes[:, 1], cx0)
                iy0 = np.maximum(boxes[:, 2], cy0)
                ix1 = np.minimum(boxes[:, 3], cx1)
                iy1 = np.minimum(boxes[:, 4], cy1)
                inter = np.clip(ix1 - ix0, 0, None) * \
                    np.clip(iy1 - iy0, 0, None)
                obj = (boxes[:, 3] - boxes[:, 1]) * (boxes[:, 4] - boxes[:, 2])
                covered = np.where(obj > 0, inter / np.maximum(obj, 1e-12), 0)
                keep = covered >= min_object_covered
                if not keep.any():
                    continue
            else:
                keep = np.zeros((0,), bool)
            img = img[y0:y0 + ch, x0:x0 + cw]
            boxes = boxes[keep].copy()
            if len(boxes):
                sw, sh = cx1 - cx0, cy1 - cy0
                boxes[:, 1] = np.clip((boxes[:, 1] - cx0) / sw, 0, 1)
                boxes[:, 2] = np.clip((boxes[:, 2] - cy0) / sh, 0, 1)
                boxes[:, 3] = np.clip((boxes[:, 3] - cx0) / sw, 0, 1)
                boxes[:, 4] = np.clip((boxes[:, 4] - cy0) / sh, 0, 1)
            return img, boxes
        return img, boxes

    return DetAugmenter(crop, np.random.default_rng(seed))


def DetBorderAug(pad_ratio_range=(1.0, 1.5), fill=127, seed=None):
    """Zoom-out padding (expand canvas, objects shrink) — the complement of
    random crop in SSD augmentation."""
    def border(img, boxes, rng):
        ratio = rng.uniform(*pad_ratio_range)
        if ratio <= 1.0:
            return img, boxes
        h, w = img.shape[:2]
        nh, nw = int(h * ratio), int(w * ratio)
        y0 = rng.integers(0, nh - h + 1)
        x0 = rng.integers(0, nw - w + 1)
        canvas = np.full((nh, nw) + img.shape[2:], fill, img.dtype)
        canvas[y0:y0 + h, x0:x0 + w] = img
        boxes = boxes.copy()
        if len(boxes):
            boxes[:, 1] = (boxes[:, 1] * w + x0) / nw
            boxes[:, 2] = (boxes[:, 2] * h + y0) / nh
            boxes[:, 3] = (boxes[:, 3] * w + x0) / nw
            boxes[:, 4] = (boxes[:, 4] * h + y0) / nh
        return canvas, boxes

    return DetAugmenter(border, np.random.default_rng(seed))


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None,
                       min_object_covered=0.3, area_range=(0.3, 1.0),
                       aspect_ratio_range=(0.75, 1.33),
                       pad_ratio_range=(1.0, 1.5), pad_val=127,
                       inter_method=2, seed=None):
    """Standard detection chain (det_aug_default): [resize-short] -> [pad]
    -> [crop] -> resize-to-shape -> [mirror] -> [normalize]."""
    ss = seed if isinstance(seed, np.random.SeedSequence) \
        else np.random.SeedSequence(seed)
    children = iter(ss.spawn(6))
    augs = []
    if resize > 0:
        def resize_aug(img, boxes, rng, _s=resize, _i=inter_method):
            # box coords are normalized, so a pure resize leaves them alone
            return resize_short(img, _s, _i), boxes

        augs.append(DetAugmenter(resize_aug))
    if rand_pad > 0:
        pad_aug = DetBorderAug(pad_ratio_range, pad_val, next(children))
        prob = rand_pad

        def maybe_pad(img, boxes, rng, _a=pad_aug, _p=prob):
            return _a(img, boxes) if rng.random() < _p else (img, boxes)

        augs.append(DetAugmenter(maybe_pad, np.random.default_rng(next(children))))
    if rand_crop > 0:
        crop_aug = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                    area_range, seed=next(children))
        prob = rand_crop

        def maybe_crop(img, boxes, rng, _a=crop_aug, _p=prob):
            return _a(img, boxes) if rng.random() < _p else (img, boxes)

        augs.append(DetAugmenter(maybe_crop, np.random.default_rng(next(children))))

    h, w = data_shape[1], data_shape[2]

    def force_resize(img, boxes, rng, _i=inter_method):
        return _resize(img.astype(np.float32), w, h, _i), boxes

    augs.append(DetAugmenter(force_resize))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5, next(children)))
    if mean is not None or std is not None:
        m = np.asarray(mean if mean is not None else 0.0, np.float32)
        s = np.asarray(std if std is not None else 1.0, np.float32)

        def normalize(img, boxes, rng):
            return (img.astype(np.float32) - m) / s, boxes

        augs.append(DetAugmenter(normalize))
    return augs


class ImageDetIter(ImageIter):
    """Detection iterator: images + variable-length object-box labels padded
    to a fixed (batch, max_objects, object_width) tensor (pad value -1),
    the shape MultiBoxTarget consumes.  Analog of the reference's
    ImageDetRecordIter (iter_image_det_recordio.cc)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="label",
                 label_pad_width=None, label_pad_value=-1.0, seed=None,
                 preprocess_threads=4, **kwargs):
        if aug_list is None:
            det_keys = ("resize", "rand_crop", "rand_pad", "rand_mirror",
                        "mean", "std", "min_object_covered", "area_range",
                        "aspect_ratio_range", "pad_ratio_range", "pad_val",
                        "inter_method")
            aug_list = CreateDetAugmenter(
                data_shape, seed=seed,
                **{k: v for k, v in kwargs.items() if k in det_keys})
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, path_imgidx=path_imgidx,
                         shuffle=shuffle, part_index=part_index,
                         num_parts=num_parts, aug_list=aug_list,
                         imglist=imglist, data_name=data_name,
                         label_name=label_name, seed=seed,
                         preprocess_threads=preprocess_threads)
        self.label_pad_value = float(label_pad_value)
        if label_pad_width is None:
            if num_parts > 1:
                # each part would scan only its slice and derive a different
                # max_objs -> mismatched label shapes across workers
                raise MXNetError(
                    "ImageDetIter with num_parts>1 needs an explicit "
                    "label_pad_width so every worker pads identically")
            label_pad_width, obj_width = self._scan_label_shape()
        else:
            # size the object width from the first record even when the pad
            # width is caller-supplied (labels may be wider than 5)
            obj_width = self._scan_label_shape(first_only=True)[1]
        self._obj_width = obj_width or 5
        self._max_objs = max(1, label_pad_width)
        self.provide_label = [DataDesc(
            label_name, (batch_size, self._max_objs, self._obj_width))]

    def _scan_label_shape(self, first_only=False):
        """Pass over the labels to size the padded tensor (construction-time
        I/O; pass label_pad_width to skip the full scan)."""
        max_objs, obj_width = 0, None
        self.reset()
        while True:
            try:
                label, _ = self._next_raw()
            except StopIteration:
                break
            objs, ow = self._parse_label(label)
            max_objs = max(max_objs, len(objs))
            obj_width = ow if obj_width is None else obj_width
            if first_only:
                break
        self.reset()
        return max_objs, obj_width

    def _parse_label(self, label):
        """-> (objects (N, obj_width), obj_width).  Accepts the packed
        header format or a flat (N*5,) / (N,5) array."""
        raw = np.asarray(label, np.float32).ravel()
        if raw.size > 2 and float(raw[0]).is_integer() \
                and 2 <= raw[0] <= raw.size and raw[1] >= 5 \
                and (raw.size - raw[0]) % raw[1] == 0 \
                and float(raw[1]).is_integer():
            hw, ow = int(raw[0]), int(raw[1])
            return raw[hw:].reshape(-1, ow), ow
        if raw.size % 5 == 0:
            return raw.reshape(-1, 5), 5
        raise MXNetError("cannot parse detection label of size %d" % raw.size)

    def next(self):
        c, h, w = self.data_shape
        images = np.zeros((self.batch_size, h, w, c), np.float32)
        labels = np.full((self.batch_size, self._max_objs, self._obj_width),
                         self.label_pad_value, np.float32)
        samples = self._collect_decoded(self.batch_size)
        for filled, (label, img) in enumerate(samples):
            boxes, _ = self._parse_label(label)
            if img.ndim == 2:
                img = np.repeat(img[:, :, None], c, axis=2)
            for aug in self.auglist:
                img, boxes = aug(img, boxes)
            if img.shape[:2] != (h, w):
                img = _resize(img.astype(np.float32), w, h)
            images[filled] = img
            n = min(len(boxes), self._max_objs)
            if n:
                width = min(boxes.shape[1], self._obj_width)
                labels[filled, :n, :width] = boxes[:n, :width]
        return DataBatch([nd.array(images.transpose(0, 3, 1, 2))],
                         [nd.array(labels)],
                         pad=self.batch_size - len(samples))


def ImageDetRecordIter(path_imgrec=None, data_shape=None, batch_size=None,
                       shuffle=False, prefetch_buffer=4, seed=None,
                       **kwargs):
    """Detection RecordIO pipeline with prefetch (C++ ImageDetRecordIter
    analog)."""
    inner = ImageDetIter(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, shuffle=shuffle, seed=seed,
                         **kwargs)
    return io_mod.PrefetchingIter(inner, capacity=prefetch_buffer)
