"""Image pipeline: record-backed and list-backed image iterators + augmenters.

Reference: `src/io/iter_image_recordio.cc` (threaded decode + augment chain)
and `python/mxnet/image.py` (pure-python pipeline).  TPU-native: numpy
augmenters on a host worker thread (PrefetchingIter) feeding device batches;
JPEG decode uses cv2 when present, else the raw-array codec from recordio.
A C++ reader for the hot path lives in src/ (native runtime).
"""
from __future__ import annotations

import os
import random as pyrandom
import threading

import numpy as np

from .base import MXNetError
from . import io as io_mod
from . import ndarray as nd
from . import recordio
from .io import DataBatch, DataDesc, DataIter

__all__ = ["imdecode", "scale_down", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize", "random_size_crop",
           "ResizeAug", "RandomCropAug", "RandomSizedCropAug", "CenterCropAug",
           "RandomOrderAug", "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "HorizontalFlipAug", "CastAug", "CreateAugmenter", "ImageIter",
           "ImageRecordIter"]


def _cv2():
    try:
        import cv2

        return cv2
    except ImportError:
        return None


def imdecode(buf, flag=1, to_rgb=True):
    """Decode an image buffer to HWC uint8 numpy (reference: image.py:32)."""
    cv2 = _cv2()
    if cv2 is not None:
        img = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), flag)
        if img is None:
            raise MXNetError("imdecode failed")
        if to_rgb:
            img = img[:, :, ::-1]
        return img
    raise MXNetError("imdecode requires cv2; use raw-array records instead")


def _resize(img, w, h, interp=1):
    cv2 = _cv2()
    if cv2 is not None:
        return cv2.resize(img, (w, h), interpolation=interp)
    # nearest-neighbor fallback
    ys = (np.arange(h) * img.shape[0] / h).astype(np.int64)
    xs = (np.arange(w) * img.shape[1] / w).astype(np.int64)
    return img[ys][:, xs]


def scale_down(src_size, size):
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return _resize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = _resize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src.astype(np.float32) - mean
    if std is not None:
        src /= std
    return src


def random_size_crop(src, size, min_area, ratio, interp=2):
    h, w = src.shape[:2]
    area = w * h
    for _ in range(10):
        new_area = pyrandom.uniform(min_area, 1.0) * area
        new_ratio = pyrandom.uniform(*ratio)
        new_w = int(round(np.sqrt(new_area * new_ratio)))
        new_h = int(round(np.sqrt(new_area / new_ratio)))
        if pyrandom.random() < 0.5:
            new_w, new_h = new_h, new_w
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


# -- augmenter functors (reference: image_aug_default.cc chain) -------------

def ResizeAug(size, interp=2):
    def aug(src):
        return [resize_short(src, size, interp)]

    return aug


def RandomCropAug(size, interp=2):
    def aug(src):
        return [random_crop(src, size, interp)[0]]

    return aug


def RandomSizedCropAug(size, min_area, ratio, interp=2):
    def aug(src):
        return [random_size_crop(src, size, min_area, ratio, interp)[0]]

    return aug


def CenterCropAug(size, interp=2):
    def aug(src):
        return [center_crop(src, size, interp)[0]]

    return aug


def RandomOrderAug(ts):
    def aug(src):
        srcs = [src]
        pyrandom.shuffle(ts)
        for t in ts:
            srcs = [j for i in srcs for j in t(i)]
        return srcs

    return aug


def ColorJitterAug(brightness, contrast, saturation):
    ts = []
    coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)
    if brightness > 0:
        def baug(src):
            alpha = 1.0 + pyrandom.uniform(-brightness, brightness)
            return [src * alpha]

        ts.append(baug)
    if contrast > 0:
        def caug(src):
            alpha = 1.0 + pyrandom.uniform(-contrast, contrast)
            gray = src * coef
            gray = (3.0 * (1.0 - alpha) / gray.size) * np.sum(gray)
            return [src * alpha + gray]

        ts.append(caug)
    if saturation > 0:
        def saug(src):
            alpha = 1.0 + pyrandom.uniform(-saturation, saturation)
            gray = np.sum(src * coef, axis=2, keepdims=True)
            return [src * alpha + gray * (1.0 - alpha)]

        ts.append(saug)
    return RandomOrderAug(ts)


def LightingAug(alphastd, eigval, eigvec):
    def aug(src):
        alpha = np.random.normal(0, alphastd, size=(3,))
        rgb = np.dot(eigvec * alpha, eigval)
        return [src + rgb]

    return aug


def ColorNormalizeAug(mean, std):
    def aug(src):
        return [color_normalize(src, mean, std)]

    return aug


def HorizontalFlipAug(p):
    def aug(src):
        if pyrandom.random() < p:
            src = src[:, ::-1]
        return [src]

    return aug


def CastAug():
    def aug(src):
        return [src.astype(np.float32)]

    return aug


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Standard augmenter chain (reference: image.py:170)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.3, (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and getattr(mean, "shape", None):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Image iterator over .rec files or image lists (reference: image.py:247)."""

    def __init__(self, batch_size, data_shape, label_width=1, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or (isinstance(imglist, list))
        if path_imgrec:
            if path_imgidx:
                self.imgrec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
                self.imgidx = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.imgidx = None
        else:
            self.imgrec = None

        self.imglist = None
        if path_imglist:
            imglist = {}
            imgkeys = []
            with open(path_imglist) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    label = np.array([float(i) for i in line[1:-1]], dtype=np.float32)
                    key = int(line[0])
                    imglist[key] = (label, line[-1])
                    imgkeys.append(key)
            self.imglist = imglist
            self.seq = imgkeys
        elif isinstance(imglist, list):
            result = {}
            imgkeys = []
            index = 1
            for img in imglist:
                key = str(index)
                index += 1
                result[key] = (np.array(img[:-1], dtype=np.float32), img[-1])
                imgkeys.append(str(key))
            self.imglist = result
            self.seq = imgkeys
        else:
            self.seq = self.imgidx

        self.path_root = path_root
        self.provide_data = [DataDesc(data_name, (batch_size,) + tuple(data_shape))]
        if label_width > 1:
            self.provide_label = [DataDesc(label_name, (batch_size, label_width))]
        else:
            self.provide_label = [DataDesc(label_name, (batch_size,))]
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        if num_parts > 1 and self.seq is not None:
            part = len(self.seq) // num_parts
            self.seq = self.seq[part_index * part:(part_index + 1) * part]
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_resize", "rand_mirror",
                         "mean", "std", "brightness", "contrast", "saturation",
                         "pca_noise", "inter_method")})
        else:
            self.auglist = aug_list
        self.cur = 0
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                if self.imglist is None:
                    return header.label, img
                return self.imglist[idx][0], img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root, fname), "rb") as fin:
                img = fin.read()
            return label, img
        else:
            s = self.imgrec.read()
            if s is None:
                raise StopIteration
            header, img = recordio.unpack(s)
            return header.label, img

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((batch_size, h, w, c), dtype=np.float32)
        batch_label = np.zeros((batch_size,) if self.label_width == 1
                               else (batch_size, self.label_width),
                               dtype=np.float32)
        i = 0
        try:
            while i < batch_size:
                label, s = self.next_sample()
                if isinstance(s, bytes):
                    try:
                        data = [imdecode(s)]
                    except MXNetError:
                        _, data_arr = recordio.unpack_img(
                            recordio.pack(recordio.IRHeader(0, label, 0, 0), s))
                        data = [data_arr]
                else:
                    data = [s]
                if data[0].ndim == 2:
                    data = [np.broadcast_to(d[:, :, None], d.shape + (c,))
                            for d in data]
                for aug in self.auglist:
                    data = [ret for src in data for ret in aug(src)]
                for d in data:
                    if i >= batch_size:
                        break
                    if d.shape[:2] != (h, w):
                        d = _resize(d.astype(np.float32), w, h)
                    batch_data[i] = d
                    batch_label[i] = label
                    i += 1
        except StopIteration:
            if i == 0:
                raise
        # HWC -> CHW
        batch_data = np.transpose(batch_data, (0, 3, 1, 2))
        return DataBatch([nd.array(batch_data)], [nd.array(batch_label)],
                         pad=batch_size - i)


def ImageRecordIter(path_imgrec=None, data_shape=None, batch_size=None,
                    shuffle=False, mean_r=0, mean_g=0, mean_b=0,
                    std_r=1, std_g=1, std_b=1, rand_crop=False,
                    rand_mirror=False, preprocess_threads=4, num_parts=1,
                    part_index=0, path_imgidx=None, prefetch_buffer=4,
                    **kwargs):
    """RecordIO image iterator (reference: iter_image_recordio.cc), assembled
    from ImageIter + PrefetchingIter (threaded decode analog)."""
    mean = None
    if mean_r or mean_g or mean_b:
        mean = np.array([mean_r, mean_g, mean_b])
    std = None
    if (std_r, std_g, std_b) != (1, 1, 1):
        std = np.array([std_r, std_g, std_b])
    aug_kwargs = {k: v for k, v in kwargs.items()
                  if k in ("resize", "rand_resize", "brightness", "contrast",
                           "saturation", "pca_noise", "inter_method")}
    it = ImageIter(batch_size=batch_size, data_shape=data_shape,
                   path_imgrec=path_imgrec, path_imgidx=path_imgidx,
                   shuffle=shuffle, rand_crop=rand_crop, rand_mirror=rand_mirror,
                   mean=mean, std=std, num_parts=num_parts,
                   part_index=part_index, **aug_kwargs)
    return io_mod.PrefetchingIter(it, capacity=prefetch_buffer)
