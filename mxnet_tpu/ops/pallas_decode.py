"""Pallas flash-decoding kernels over paged KV pools (TPU) — gather +
dequant + attention fused into ONE HBM pass.

The serving path's einsum formulation walks the largest tensor in the
system three times per generated token: ``ops.attention.paged_gather``
materializes a full (B, M*pt, E) dense-ring view of the shared page pool
in HBM, ``dequantize_kv`` materializes the f32 copy of an int8/fp8 pool,
and ``sdpa_decode`` streams that copy again for the score/value matmuls.
Decode attention is bandwidth-bound on exactly those bytes, so the three
passes ARE the step time.

These kernels implement the two fixes the literature names, together:

* **PagedAttention** (Kwon et al., SOSP 2023): the page-table gather
  moves *inside* the kernel.  The (B, M) table rides in as a
  scalar-prefetch argument (``pltpu.PrefetchScalarGridSpec``) and every
  pool BlockSpec's index map reads it — ``(table[b, m], 0, h)`` — so each
  grid step DMAs one page's one head-slice straight from the pool.  No
  gathered view, no dequantized copy: int8/fp8 pages dequantize in VMEM
  (per-(token, head) scales, the ``QuantKV`` layout) on their way into
  the score matmul.
* **Flash-Decoding** (Dao et al., 2023): the grid parallelizes over the
  CACHE-LENGTH axis, not just (batch, head).  At decode (tq=1) with
  batch = serving slots, a (B, H) grid strands the chip when B*H is
  small; a split-K axis of S splits walks M/S pages each, maintaining
  the running (max, sum, acc) flash softmax per split, and a small
  cross-split logsumexp combine (host-side jnp over (B, H, S, tq)-shaped
  partials — tiny) reduces them exactly.  The (b, h, s) grid prefix is
  marked ``parallel`` toward Mosaic (each instance owns its scratch
  lifetime) so it fans across cores; only the within-split page walk
  ``ms`` is ``arbitrary`` (sequential softmax accumulation).

Three entry points share one kernel core:

* :func:`flash_sdpa_decode` — tq == 1, the decode hot path;
* :func:`flash_sdpa_verify` — tq == k+1, the speculative verify window
  (and the chunked-prefill window: any tq with per-query length masks);
* :func:`dense_ring_attend` — the non-paged ring buffers take the same
  kernel through an identity page table: a (B, C, E) cache reshapes
  (free, row-major split) into a (B*Mb, bs, E) pool and
  ``table[b, m] = b*Mb + m``.

All are length-masked and wrap-aware exactly like
``ops.attention._sdpa_cache``: query i of a window whose total appended
length is ``total`` sees view slots v < min(total - (tq-1) + i, C), so a
wrapped ring (total > C) attends all C live slots.  Numerics follow the
einsum path (f32 logits, f32 softmax accumulation); streaming
accumulation reorders the sums, so parity is tolerance-tested
(documented in docs/inference.md), not bit-asserted.

Dispatch lives in ``ops.attention.paged_attend`` / ``cache_attend``,
gated by ``MXNET_PALLAS_DECODE`` with shape fallback to the einsum path;
``interpret=True`` runs the same kernels on CPU (the tier-1 parity
suite, tests/test_pallas_decode.py).
"""
from __future__ import annotations

import functools

import numpy as np

# Split-K sizing: at most MAX_SPLITS splits over the view's M pages (the
# largest power of two <= min(M, MAX_SPLITS) dividing M).  More splits =
# more cross-core parallelism on the cache-length axis but more combine
# partials; 8 covers a v5e megacore with headroom.
MAX_SPLITS = 8
# Residual lane width for the per-split (max, sum) partials — matches the
# (rows, lanes) layout pallas_attention.py uses for its logsumexp
# residuals, so no kernel ever writes a 1-lane vector.
LANES = 128
# TPU (non-interpret) gates: Mosaic wants the lane (last) dim a multiple
# of 128 and the sublane dim a multiple of 8; interpret mode has no tile
# constraints and takes any positive shape.
_TPU_LANE = 128
_TPU_SUBLANE = 8


def _num_splits(m, cap=None, groups=1):
    """Largest power-of-two split count <= min(m, cap) that divides m
    (1 when m is odd — the split axis degrades gracefully).  ``cap``
    defaults to the tuning cache's ``max_splits`` for this view width
    (the :data:`MAX_SPLITS` constant when cold and no sweep armed)."""
    if cap is None:
        cap = _tuned_split_cap(m, groups=groups)
    s = 1
    while s * 2 <= min(m, cap) and m % (s * 2) == 0:
        s *= 2
    return s


_STALE_GROUP_CHECKED = set()


def _tuned_split_cap(m, groups=1):
    from . import tuning

    # split width is a parallelism knob, not a dtype-layout one: one
    # decision per view width serves every pool dtype
    if groups <= 1:
        return int(tuning.resolve("pallas_decode",
                                  tuning.shape_class_for(m=m),
                                  "any").get("max_splits", MAX_SPLITS))
    # grouped K/V shapes get their own content-addressed tune key (the
    # kv-head group class rides in the shape class) so a GQA sweep never
    # collides with an MHA winner for the same view width
    sc = tuning.shape_class_for(m=m, g=groups)
    if sc not in _STALE_GROUP_CHECKED:
        _STALE_GROUP_CHECKED.add(sc)
        mha_sc = tuning.shape_class_for(m=m)
        if (tuning.get("pallas_decode", sc, "any", version=1) is None
                and tuning.get("pallas_decode", mha_sc, "any",
                               version=1) is not None):
            import warnings

            warnings.warn(
                "tuning cache holds an MHA-keyed pallas_decode record for "
                "m=%d but the shape is grouped (G=%d); the MHA winner "
                "does not apply — treating as a miss" % (m, groups))
    return int(tuning.resolve("pallas_decode", sc,
                              "any").get("max_splits", MAX_SPLITS))


def _is_quant(pool):
    from .attention import QuantKV

    return isinstance(pool, QuantKV)


def supported(q_shape, k_pool, v_pool, table_shape, num_heads,
              interpret=False, num_kv_heads=0):
    """Whether the fused kernel handles this paged-decode shape.

    Correctness constraints always: heads divide both embed dims and the
    (quantized) scale planes carry exactly the K/V head count.  Grouped
    configs (``num_kv_heads < num_heads``) require the pools to be
    physically H_kv heads wide — the kernel maps q-head h to pool slice
    ``h // G``.  On a real TPU (``interpret=False``) the Mosaic tile
    constraints add: per-head dims and page_tokens aligned to the
    (8, 128) tile.  Anything else falls back to the einsum path — same
    numerics, three HBM passes.
    """
    kd = k_pool.data if _is_quant(k_pool) else k_pool
    vd = v_pool.data if _is_quant(v_pool) else v_pool
    b, tq, e = q_shape
    kvh = int(num_kv_heads) or int(num_heads)
    if num_heads <= 0 or kvh <= 0 or num_heads % kvh:
        return False
    if e % num_heads or vd.shape[2] % kvh:
        return False
    if kd.shape[2] != kvh * (e // num_heads):
        return False
    if _is_quant(k_pool) and k_pool.scale.shape[-1] != kvh:
        return False
    if _is_quant(v_pool) and v_pool.scale.shape[-1] != kvh:
        return False
    pt = kd.shape[1]
    if pt <= 0 or table_shape[1] <= 0:
        return False
    if not interpret:
        hd_k = e // num_heads
        hd_v = vd.shape[2] // kvh
        if hd_k % _TPU_LANE or hd_v % _TPU_LANE:
            return False
        if pt % _TPU_SUBLANE:
            return False
    return True


def _kernel(table_ref, lens_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
            acc_ref, m_ref, l_ref, m_scr, l_scr, acc_scr, *, scale, tq,
            page_tokens, pages_per_split, view_pages, quant):
    """One (b, h, s, ms) grid step: fold page ``s*pages_per_split + ms``
    of slot b's view into the running flash softmax for head h.

    ``ks_ref``/``vs_ref`` are the per-(token, head) scale pages of a
    quantized pool (None otherwise) — dequantization happens HERE, on
    the (pt, hd) tile in VMEM, never in HBM.  At the split's last page
    the UNNORMALIZED partial (acc, max, sum) is written out; the caller
    combines splits with a logsumexp reduction.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    ms = pl.program_id(3)
    nms = pl.num_programs(3)
    s = pl.program_id(2)

    @pl.when(ms == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    m_view = s * pages_per_split + ms          # view page index in [0, M)
    total = lens_ref[b]
    cap = view_pages * page_tokens             # C, the ring capacity
    visible = jnp.minimum(total, cap)          # live view slots

    def _update():
        q = q_ref[0, 0].astype(jnp.float32)                 # (tq, hd_k)
        k = k_ref[0].astype(jnp.float32)                    # (pt, hd_k)
        v = v_ref[0].astype(jnp.float32)                    # (pt, hd_v)
        if quant:
            k = k * ks_ref[0]                               # (pt, 1) scale
            v = v * vs_ref[0]
        logits = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(scale)
        # view slot v = m_view*pt + j; query i sees v < min(total-(tq-1)+i, C)
        vpos = m_view * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (tq, page_tokens), 1)
        limit = jnp.minimum(
            total - (tq - 1) + jax.lax.broadcasted_iota(
                jnp.int32, (tq, page_tokens), 0), cap)
        logits = jnp.where(vpos < limit, logits, -jnp.inf)

        m_prev = m_scr[:, :1]                               # (tq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
        m_safe = jnp.where(m_new == -jnp.inf, 0.0, m_new)
        p = jnp.exp(logits - m_safe)
        p = jnp.where(logits == -jnp.inf, 0.0, p)
        corr = jnp.where(m_prev == -jnp.inf, 0.0, jnp.exp(m_prev - m_safe))
        l_scr[:] = l_scr[:] * corr + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), l_scr.shape)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    # pages wholly past the live window contribute nothing — skip their
    # compute entirely (their DMA still lands, the index map ran)
    @pl.when(m_view * page_tokens < visible)
    def _masked_update():
        _update()

    @pl.when(ms == nms - 1)
    def _finish():
        acc_ref[0, 0, 0] = acc_scr[:]
        m_ref[0, 0, 0] = m_scr[:]
        l_ref[0, 0, 0] = l_scr[:]


def _paged_flash_call(q, k_pool, v_pool, table, lens, num_heads, scale,
                      interpret, split_cap=None, num_kv_heads=0):
    """Launch the kernel and combine split partials; returns (B, tq, Ev)
    in the V pool's compute dtype (f32 for quantized pools, matching the
    einsum path's dequantized output).

    Grouped pools (``num_kv_heads < num_heads``) keep the (b, h, s, ms)
    q-head grid; the pool/scale BlockSpec index maps gather ONE kv-head
    slice per G q-heads (``hi // G`` — the group id), so the pool is
    never widened to H_q."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    quant = _is_quant(k_pool)
    kd = k_pool.data if quant else k_pool
    vd = v_pool.data if quant else v_pool
    b, tq, e = q.shape
    h = num_heads
    kvh = int(num_kv_heads) or int(h)
    g = h // kvh
    hd_k = e // h
    hd_v = vd.shape[2] // kvh
    pt = kd.shape[1]
    m = table.shape[1]
    s = _num_splits(m, split_cap, groups=g)
    ms = m // s
    scale = float(scale or 1.0 / np.sqrt(hd_k))

    qh = q.reshape(b, tq, h, hd_k).transpose(0, 2, 1, 3)  # (B, H, tq, hd)
    table = jnp.asarray(table, jnp.int32)
    lens = jnp.broadcast_to(jnp.asarray(lens, jnp.int32).reshape(-1), (b,))

    kernel = functools.partial(
        _kernel, scale=scale, tq=tq, page_tokens=pt, pages_per_split=ms,
        view_pages=m, quant=quant)

    # index maps: every pool block is one page's one head-slice, located
    # through the scalar-prefetched table — the in-kernel gather
    def _q_map(bi, hi, si, mi, tr, lr):
        return (bi, hi, 0, 0)

    if g == 1:
        def _page_map(bi, hi, si, mi, tr, lr):
            return (tr[bi, si * ms + mi], 0, hi)
    else:
        # pool blocks keyed by GROUP id: q-heads hi in [gi*G, (gi+1)*G)
        # all DMA kv-head slice gi = hi // G of the physically-grouped pool
        def _page_map(bi, hi, si, mi, tr, lr):
            return (tr[bi, si * ms + mi], 0, hi // g)

    def _out_map(bi, hi, si, mi, tr, lr):
        return (bi, hi, si, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, tq, hd_k), _q_map),
        pl.BlockSpec((1, pt, hd_k), _page_map),
        pl.BlockSpec((1, pt, hd_v), _page_map),
    ]
    args = [qh, kd, vd]
    if quant:
        in_specs += [pl.BlockSpec((1, pt, 1), _page_map),
                     pl.BlockSpec((1, pt, 1), _page_map)]
        args += [k_pool.scale, v_pool.scale]
    else:
        # keep ONE kernel signature: unquantized pools ride a zero-cost
        # dummy scale page (never read — quant=False skips it)
        dummy = jnp.zeros((1, pt, 1), jnp.float32)
        in_specs += [pl.BlockSpec((1, pt, 1),
                                  lambda bi, hi, si, mi, tr, lr: (0, 0, 0))] \
            * 2
        args += [dummy, dummy]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, s, ms),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, 1, tq, hd_v), _out_map),
            pl.BlockSpec((1, 1, 1, tq, LANES), _out_map),
            pl.BlockSpec((1, 1, 1, tq, LANES), _out_map),
        ],
        scratch_shapes=[
            pltpu.VMEM((tq, LANES), jnp.float32),   # running max
            pltpu.VMEM((tq, LANES), jnp.float32),   # running sum
            pltpu.VMEM((tq, hd_v), jnp.float32),    # output accumulator
        ],
    )
    # (b, h, s) are independent — each owns its scratch lifetime via the
    # ms==0 init — so Mosaic may fan them across cores (the split-K
    # parallelism that fills the chip at batch=slots); only ms, the
    # running-softmax accumulation over a split's pages, is sequential.
    # Without this, all four grid dims default to 'arbitrary' and the
    # whole grid serializes on one core.
    acc, m_p, l_p = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, tq, hd_v), jnp.float32),
            jax.ShapeDtypeStruct((b, h, s, tq, LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, h, s, tq, LANES), jnp.float32),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(table, lens, *args)

    # cross-split logsumexp combine (Flash-Decoding's reduction): tiny
    # (B, H, S, tq)-shaped partials, exact in f32
    m_p = m_p[..., 0]                                   # (B, H, S, tq)
    l_p = l_p[..., 0]
    m_star = jnp.max(m_p, axis=2, keepdims=True)
    m_star = jnp.where(m_star == -jnp.inf, 0.0, m_star)
    alpha = jnp.where(m_p == -jnp.inf, 0.0, jnp.exp(m_p - m_star))
    l_tot = jnp.sum(alpha * l_p, axis=2)                # (B, H, tq)
    acc = jnp.sum(alpha[..., None] * acc, axis=2)       # (B, H, tq, hd_v)
    denom = jnp.where(l_tot == 0.0, 1.0, l_tot)
    out = acc / denom[..., None]
    out = out.transpose(0, 2, 1, 3).reshape(b, tq, h * hd_v)
    out_dtype = jnp.float32 if quant else vd.dtype
    return out.astype(out_dtype)


def flash_sdpa_decode(q, k_pool, v_pool, table, total_len, num_heads=1,
                      scale=None, interpret=False, split_cap=None,
                      num_kv_heads=0):
    """Fused paged decode attention: (B, 1, E) queries over (P, pt, E_kv)
    pools through (B, M) page tables -> (B, 1, Ev).

    ``total_len`` counts tokens appended INCLUDING the query position
    (the ``sdpa_decode`` contract); once the view ring has wrapped
    (total > M*pt) every slot is live.  Pools may be
    :class:`~mxnet_tpu.ops.attention.QuantKV` — dequantized per
    (token, kv-head) in VMEM.  One HBM pass over the live pool pages;
    grouped pools (``num_kv_heads``) are walked once per kv head group.
    """
    return _paged_flash_call(q, k_pool, v_pool, table, total_len,
                             num_heads, scale, interpret,
                             split_cap=split_cap,
                             num_kv_heads=num_kv_heads)


def flash_sdpa_verify(q, k_pool, v_pool, table, total_len, num_heads=1,
                      scale=None, interpret=False, split_cap=None,
                      num_kv_heads=0):
    """Fused paged multi-position cache attention — the speculative
    verify window (tq = k+1) and the chunked-prefill window (tq = chunk
    width) share it.  Query i masks to view slots
    v < min(total - (tq-1) + i, C), exactly ``sdpa_verify``'s rule, so
    each output row equals what a sequential decode chain would produce.
    """
    return _paged_flash_call(q, k_pool, v_pool, table, total_len,
                             num_heads, scale, interpret,
                             split_cap=split_cap,
                             num_kv_heads=num_kv_heads)


def _dense_block(c, pt_pref=128):
    """Page size for the dense-ring identity view: the largest
    power-of-two <= min(c, pt_pref) dividing c."""
    bs = min(pt_pref, c)
    while c % bs:
        bs //= 2
    return bs


class _Shape:
    """Shape/dtype carrier so the paged ``supported`` gate can vet a
    dense ring's pool view without reshaping real arrays."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = tuple(shape)
        self.dtype = dtype


def supported_dense(q_shape, k_cache, v_cache, num_heads, interpret=False,
                    num_kv_heads=0):
    """Whether the dense-ring variant handles these cache shapes: the
    (B, C, E) ring must tile into identity pages the paged gate accepts."""
    from .attention import QuantKV

    kd = k_cache.data if _is_quant(k_cache) else k_cache
    c = kd.shape[1]
    bs = _dense_block(c)
    if bs < 1:
        return False
    mb = c // bs

    def as_pool(cache):
        if _is_quant(cache):
            return QuantKV(as_pool(cache.data), as_pool(cache.scale))
        return _Shape((cache.shape[0] * mb, bs, cache.shape[2]),
                      cache.dtype)

    return supported(q_shape, as_pool(k_cache), as_pool(v_cache),
                     (q_shape[0], mb), num_heads, interpret=interpret,
                     num_kv_heads=num_kv_heads)


def dense_ring_attend(q, k_cache, v_cache, total_len, num_heads=1,
                      scale=None, interpret=False, num_kv_heads=0):
    """The dense-ring variant: run the SAME fused kernel over a non-paged
    (B, C, E) ring buffer through an identity page table.

    The ring reshapes (free: a row-major split of C into Mb pages of bs
    tokens) into a (B*Mb, bs, E) pool and ``table[b, m] = b*Mb + m``;
    split-K then parallelizes the plain KV-cached decode path over cache
    length too.  Length masks/wrap behave exactly like ``_sdpa_cache``.
    """
    import jax.numpy as jnp

    from .attention import QuantKV

    kd = k_cache.data if _is_quant(k_cache) else k_cache
    b, c = kd.shape[0], kd.shape[1]
    bs = _dense_block(c)
    mb = c // bs

    def as_pool(cache):
        if _is_quant(cache):
            return QuantKV(as_pool(cache.data), as_pool(cache.scale))
        return cache.reshape(b * mb, bs, cache.shape[2])

    table = (jnp.arange(b, dtype=jnp.int32)[:, None] * mb
             + jnp.arange(mb, dtype=jnp.int32)[None, :])
    return _paged_flash_call(q, as_pool(k_cache), as_pool(v_cache), table,
                             total_len, num_heads, scale, interpret,
                             num_kv_heads=num_kv_heads)


# ---------------------------------------------------------------------------
# tunable space (ops/tuning.py): split-K width per view-width class
# ---------------------------------------------------------------------------

def _tuning_candidates(shape_class, interpret):
    if interpret:
        # 2-candidate toy space for the tier-1 CPU sweep
        return [{"max_splits": 2}, {"max_splits": 4}]
    return [{"max_splits": c} for c in (1, 2, 4, 8, 16)]


def _tuning_runner(params, shape_class, dtype, interpret):
    import jax
    import jax.numpy as jnp

    from . import tuning

    m = tuning.parse_shape_class(shape_class).get("m", 8)
    cap = params["max_splits"]
    if cap > m:
        raise tuning.SpaceError("max_splits %d exceeds view width m=%d"
                                % (cap, m))
    dt = jnp.float32 if dtype == "any" else jnp.dtype(dtype)
    pt, e, b = 16, 128, 4
    rng = jax.random.PRNGKey(0)
    kp = jax.random.normal(rng, (b * m + 1, pt, e), dt)
    vp = jax.random.normal(jax.random.fold_in(rng, 1), (b * m + 1, pt, e),
                           dt)
    q = jax.random.normal(jax.random.fold_in(rng, 2), (b, 1, e), dt)
    table = (jnp.arange(b * m, dtype=jnp.int32).reshape(b, m) + 1)
    lens = jnp.full((b,), m * pt, jnp.int32)

    @jax.jit
    def probe(q, kp, vp, table, lens):
        # explicit split_cap: the sweep must not re-enter resolve()
        return flash_sdpa_decode(q, kp, vp, table, lens, num_heads=1,
                                 interpret=interpret, split_cap=cap)

    def run():
        jax.block_until_ready(probe(q, kp, vp, table, lens))

    return run


def _register_space():
    from . import tuning

    tuning.register_space(
        "pallas_decode", version=1,
        defaults={"max_splits": MAX_SPLITS},
        constants=("MAX_SPLITS",),
        candidates=_tuning_candidates, runner=_tuning_runner)


_register_space()
