"""Attention operators — the long-context leapfrog.

The reference (2017-era MXNet) has no attention op; its long-sequence story
is bucketing + fused RNN (SURVEY §2.5 "Sequence-length scaling").  The TPU
build upgrades that niche with first-class attention that composes with the
mesh axes:

* ``dot_product_attention`` — multi-head scaled-dot-product attention over
  already-projected (B, T, E) tensors (compose MHA from FullyConnected +
  this op, the framework's op-granularity convention).  Pure jnp einsum:
  under the mesh executor, GSPMD partitions it over the ``seq`` axis from
  the input shardings (all-gather/all-to-all sequence parallelism — the
  Ulysses-style path) and over ``model`` for the head dimension.
* For the explicit-collective path (memory-optimal long context), see
  ``mxnet_tpu.parallel.ring.ring_attention`` — blockwise ring attention
  with K/V rotating via ``lax.ppermute`` under ``shard_map``.
"""
from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op


def check_head_groups(num_heads, num_kv_heads, e, ev=None, kv_dim=None,
                      where="dot_product_attention"):
    """Validate a (possibly grouped) head configuration, raising
    ``ValueError``s that NAME the offending dims — the silent-fallthrough
    guards (``e % heads``, ``heads % kv_heads``) all route through here
    so every call path fails with the same loud message.

    Returns ``(kv_heads, group)`` with ``kv_heads`` resolved (0 ->
    ``num_heads``, the MHA default) and ``group = num_heads //
    kv_heads`` — the GQA/MQA group factor G (Ainslie et al. 2023;
    Shazeer 2019 at kv_heads == 1)."""
    heads = int(num_heads)
    kvh = int(num_kv_heads) or heads
    if heads <= 0:
        raise ValueError("%s: num_heads=%d must be positive"
                         % (where, heads))
    if kvh <= 0:
        raise ValueError("%s: num_kv_heads=%d must be positive"
                         % (where, kvh))
    if heads % kvh != 0:
        raise ValueError("%s: num_heads=%d not divisible by "
                         "num_kv_heads=%d" % (where, heads, kvh))
    if e % heads != 0:
        raise ValueError("%s: query embed dim %d not divisible by "
                         "num_heads=%d" % (where, e, heads))
    if ev is not None and ev % kvh != 0:
        raise ValueError("%s: value embed dim %d not divisible by "
                         "num_kv_heads=%d" % (where, ev, kvh))
    if kv_dim is not None and kv_dim != kvh * (e // heads):
        raise ValueError(
            "%s: key embed dim %d != num_kv_heads=%d * head_dim=%d"
            % (where, kv_dim, kvh, e // heads))
    return kvh, heads // kvh


def sdpa(q, k, v, num_heads=1, causal=False, scale=None, num_kv_heads=0):
    """Multi-head scaled-dot-product attention kernel.

    (B, Tq, E), (B, Tk, Ek), (B, Tk, Ev) -> (B, Tq, H*hdv).  The softmax
    runs in float32 regardless of the input dtype (bf16-safe
    accumulation); the output is cast back to the value dtype.  Shared by
    the registered op and ``parallel.ring.dense_attention`` (one copy of
    the numerics).

    ``num_kv_heads`` (0 = ``num_heads``, plain MHA) enables grouped-query
    attention: K/V carry only ``H_kv`` heads (``Ek == H_kv * hd``) and
    q-head ``h`` attends kv-head ``h // G`` with ``G = H / H_kv`` —
    mapped INSIDE the einsum by reshaping q to (B, Tq, H_kv, G, hd), so
    the G× smaller K/V are never broadcast into a materialized copy.
    """
    import jax.numpy as jnp

    b, tq, e = q.shape
    tk = k.shape[1]
    ev = v.shape[2]
    kvh, g = check_head_groups(num_heads, num_kv_heads, e, ev, k.shape[2],
                               where="sdpa")
    hd = e // num_heads
    scale = scale or 1.0 / np.sqrt(hd)
    if g == 1:
        # ungrouped path kept verbatim: G=1 stays bit-identical to the
        # pre-GQA kernel (same einsums in the same order)
        qh = q.reshape(b, tq, num_heads, hd)
        kh = k.reshape(b, tk, num_heads, hd)
        vh = v.reshape(b, tk, num_heads, ev // num_heads)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh,
                            kh).astype(jnp.float32) * scale
        if causal:
            mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
            logits = jnp.where(mask[None, None], logits,
                               jnp.finfo(jnp.float32).min)
        m = jnp.max(logits, axis=-1, keepdims=True)
        p = jnp.exp(logits - m)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        out = jnp.einsum("bhqk,bkhe->bqhe", p.astype(vh.dtype), vh)
        return out.reshape(b, tq, ev)
    qh = q.reshape(b, tq, kvh, g, hd)
    kh = k.reshape(b, tk, kvh, hd)
    vh = v.reshape(b, tk, kvh, ev // kvh)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qh,
                        kh).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(mask[None, None, None], logits,
                           jnp.finfo(jnp.float32).min)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhgqk,bkhe->bqhge", p.astype(vh.dtype), vh)
    return out.reshape(b, tq, num_heads * (ev // kvh))


# ---------------------------------------------------------------------------
# Decode mode — incremental attention against a preallocated ring-buffer KV
# cache (the Pope et al. "Efficiently Scaling Transformer Inference" decode
# plan).  The full-sequence op above re-scores the whole prefix for every
# generated token (O(T^2) per sequence); these kernels make decode O(T):
# append the new K/V at the next ring slot, attend the query position(s)
# against the cache with a length mask.  ``mxnet_tpu.decode`` drives them —
# it splits an attention_lm-style symbol into a prefill program and a
# donated decode-step program that calls cache_append + sdpa_decode at every
# dot_product_attention node.  Under a mesh, the cache's E (head) dim is
# sharded on 'model' (an E-split IS a head-group split — see
# parallel/tp_rules.py) so each model shard holds and scores only its own
# head group's cache slice.
# ---------------------------------------------------------------------------

class QuantKV(NamedTuple):
    """A quantized ring-buffer cache: narrow ``data`` plus per-(token,
    head) fp32 ``scale``.

    ``data`` is the (B, C, E) K or V buffer in the narrow storage dtype
    (int8 / fp8); ``scale`` is (B, C, H) float32 — one scale per cache
    slot per head, chosen at append time so each head's hd-wide slice
    fills the storage dtype's representable range.  A jax pytree (both
    leaves donate/shard independently: ``data`` follows
    ``tp_rules.kv_cache_pspec``; ``scale``'s trailing head dim shards the
    same way, an H-split IS the same head-group split).
    """

    data: object
    scale: object


# quantization range per storage dtype: int8 is symmetric round-to-nearest
# in [-127, 127]; the fp8 variants scale into their finite max so the cast
# never saturates (values are <= qmax by construction)
_KV_QMAX = {"int8": 127.0, "float8_e4m3fn": 448.0, "float8_e5m2": 57344.0}


def kv_qmax(dtype):
    """Quantization range of a KV storage dtype (KeyError = unsupported —
    the MXNET_KV_DTYPE consumer turns that into a config error)."""
    return _KV_QMAX[np.dtype(dtype).name]


def quantize_kv(x, dtype, num_heads=1):
    """(B, t, E) float K/V -> :class:`QuantKV` with per-(token, head)
    scales: ``scale = amax_head / qmax``, ``data = round(x / scale)``
    (int8) or a saturating-range fp8 cast.  All-zero heads (pad slots)
    quantize to zeros under a floor scale instead of dividing by zero."""
    import jax.numpy as jnp

    b, t, e = x.shape
    if e % num_heads != 0:
        raise ValueError("quantize_kv: embed dim %d not divisible by "
                         "num_heads=%d" % (e, num_heads))
    qmax = kv_qmax(dtype)
    xh = x.astype(jnp.float32).reshape(b, t, num_heads, e // num_heads)
    amax = jnp.max(jnp.abs(xh), axis=-1)                      # (B, t, H)
    scale = jnp.maximum(amax, 1e-8) / qmax
    q = xh / scale[..., None]
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        q = jnp.clip(jnp.round(q), -qmax, qmax)
    data = q.astype(dtype).reshape(b, t, e)
    return QuantKV(data, scale)


def dequantize_kv(cache, num_heads=None, out_dtype=None):
    """:class:`QuantKV` -> the float (B, C, E) buffer the kernels attend
    against (``data * scale`` per head).  Plain arrays pass through, so
    callers handle both cache layouts with one code path.  The head
    count is authoritative in the scale plane's trailing dim;
    ``num_heads``, when given, must agree (a cache built under a
    different head config must fail loudly, not descale wrongly)."""
    import jax.numpy as jnp

    if not isinstance(cache, QuantKV):
        return cache
    b, c, e = cache.data.shape
    h = cache.scale.shape[-1]
    assert num_heads is None or num_heads == h, \
        "cache quantized with %d heads, caller expects %d" % (h, num_heads)
    x = cache.data.astype(jnp.float32).reshape(b, c, h, e // h) \
        * cache.scale[..., None]
    x = x.reshape(b, c, e)
    return x.astype(out_dtype) if out_dtype is not None else x


def cache_append(cache, new, start_pos, num_heads=1):
    """Write ``new`` (B, t, E) into ring-buffer slots [start_pos,
    start_pos+t) mod C of ``cache`` (B, C, E).

    ``start_pos`` is the number of tokens already in the cache — a scalar
    or a per-sequence (B,) vector (batched serving: each slot at its own
    length).  The t == 1 decode hot path is a per-row
    ``jax.lax.dynamic_update_slice`` (never wraps: one slot always fits);
    multi-position appends (the speculative verify pass's fixed-width
    k+1-token append) scatter, wrapping modulo C so the cache keeps the
    latest C tokens (sliding-window semantics — attention over a set of
    keys is order-agnostic, positions having been added at the input
    embedding).  Rejected speculative entries are not un-written: the
    caller rolls back ``lens`` instead, the length mask hides them, and
    the next append overwrites them in place.

    A :class:`QuantKV` cache quantizes ``new`` on the way in
    (per-(token, head) scales — pass ``num_heads``); both its leaves
    update at the same slots.  Traceable; donated-safe (pure functional
    update).
    """
    import jax
    import jax.numpy as jnp

    if isinstance(cache, QuantKV):
        qnew = quantize_kv(new, cache.data.dtype, num_heads)
        return QuantKV(cache_append(cache.data, qnew.data, start_pos),
                       cache_append(cache.scale, qnew.scale, start_pos))
    b, t = new.shape[0], new.shape[1]
    c = cache.shape[1]
    start = jnp.broadcast_to(jnp.asarray(start_pos, jnp.int32).reshape(-1),
                             (b,))
    new = new.astype(cache.dtype)
    if t == 1:
        slot = start % c
        zero = (jnp.int32(0),) * (new.ndim - 2)
        return jax.vmap(
            lambda buf, row, s: jax.lax.dynamic_update_slice(
                buf, row, (s,) + zero))(cache, new, slot)
    if t > c:
        # only the latest C tokens can land; trimming BEFORE the scatter
        # keeps the slot indices unique per row (scatter order with
        # duplicate indices is backend-unspecified)
        new = new[:, -c:]
        start = start + (t - c)
        t = c
    pos = (start[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]) % c
    return cache.at[jnp.arange(b)[:, None], pos].set(new)


def _sdpa_cache(q, k_cache, v_cache, total_len, num_heads, scale,
                num_kv_heads=0):
    """Shared length-masked cache-attention core behind
    :func:`sdpa_decode` (tq == 1) and :func:`sdpa_verify` (tq == k+1).
    Quantized caches (:class:`QuantKV`) dequantize here, per head, before
    the score matmul — the logits are bit-identical to attending the
    dequantized buffers densely, which is what the parity tests pin.
    With ``num_kv_heads < num_heads`` the caches hold H_kv heads (and
    QuantKV scale planes are per-(token, kv-head)); q-head ``h`` scores
    kv-head ``h // G`` through the grouped einsum — no broadcast copy."""
    import jax.numpy as jnp

    b, tq, e = q.shape
    kvh, g = check_head_groups(num_heads, num_kv_heads, e,
                               where="sdpa_decode")
    k_cache = dequantize_kv(k_cache, kvh)
    v_cache = dequantize_kv(v_cache, kvh)
    c = k_cache.shape[1]
    ev = v_cache.shape[2]
    if ev % kvh != 0:
        raise ValueError("sdpa_decode: value cache dim %d not divisible "
                         "by num_kv_heads=%d" % (ev, kvh))
    hd = e // num_heads
    if k_cache.shape[2] != kvh * hd:
        raise ValueError(
            "sdpa_decode: key cache dim %d != num_kv_heads=%d * "
            "head_dim=%d" % (k_cache.shape[2], kvh, hd))
    scale = scale or 1.0 / np.sqrt(hd)
    if g == 1:
        # ungrouped path kept verbatim (G=1 bit-identity)
        qh = q.reshape(b, tq, num_heads, hd)
        kh = k_cache.reshape(b, c, num_heads, hd)
        vh = v_cache.reshape(b, c, num_heads, ev // num_heads)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qh,
                            kh).astype(jnp.float32) * scale
        total = jnp.asarray(total_len, jnp.int32).reshape(-1, 1, 1, 1)
        qpos = jnp.arange(tq, dtype=jnp.int32).reshape(1, 1, tq, 1)
        limit = jnp.minimum(total - (tq - 1) + qpos, c)
        slot = jnp.arange(c, dtype=jnp.int32).reshape(1, 1, 1, c)
        logits = jnp.where(slot < limit, logits, jnp.finfo(jnp.float32).min)
        m = jnp.max(logits, axis=-1, keepdims=True)
        p = jnp.exp(logits - m)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        out = jnp.einsum("bhqk,bkhe->bqhe", p.astype(vh.dtype), vh)
        return out.reshape(b, tq, ev)
    qh = q.reshape(b, tq, kvh, g, hd)
    kh = k_cache.reshape(b, c, kvh, hd)
    vh = v_cache.reshape(b, c, kvh, ev // kvh)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qh,
                        kh).astype(jnp.float32) * scale
    total = jnp.asarray(total_len, jnp.int32).reshape(-1, 1, 1, 1, 1)
    qpos = jnp.arange(tq, dtype=jnp.int32).reshape(1, 1, 1, tq, 1)
    limit = jnp.minimum(total - (tq - 1) + qpos, c)
    slot = jnp.arange(c, dtype=jnp.int32).reshape(1, 1, 1, 1, c)
    logits = jnp.where(slot < limit, logits, jnp.finfo(jnp.float32).min)
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhgqk,bkhe->bqhge", p.astype(vh.dtype), vh)
    return out.reshape(b, tq, num_heads * (ev // kvh))


def sdpa_decode(q, k_cache, v_cache, total_len, num_heads=1, scale=None,
                num_kv_heads=0):
    """Attend query position(s) against a ring-buffer KV cache.

    (B, tq, E) queries over (B, C, E)/(B, C, Ev) caches -> (B, tq, Ev).
    ``total_len`` — scalar or (B,) — counts tokens appended to the cache
    INCLUDING the query position(s): query i (the token at global position
    ``total_len - tq + i``) sees cache slots j < min(total_len - tq + 1 + i,
    C); once the ring has wrapped every slot holds a live token and the
    window is all C slots.  Same fp32-softmax numerics as :func:`sdpa`, so
    prefill+decode logits match the full forward pass.  Caches may be
    :class:`QuantKV` (dequantized per head inside the kernel).  With
    tq > 1 the caller must not have wrapped past its own queries
    (total <= C) — that multi-position form is :func:`sdpa_verify`.
    """
    return _sdpa_cache(q, k_cache, v_cache, total_len, num_heads, scale,
                       num_kv_heads=num_kv_heads)


def sdpa_verify(q, k_cache, v_cache, total_len, num_heads=1, scale=None,
                num_kv_heads=0):
    """Length-masked multi-position cache attention — the speculative
    verify kernel.

    The target model scores all k+1 speculative positions in ONE pass:
    ``q`` is (B, k+1, E) (last committed token + k drafts), the caches
    already hold their K/V (``cache_append`` fixed-width append), and
    ``total_len`` counts through the last draft.  Query i masks to slots
    ``j < min(total_len - k + i, C)`` — itself and everything before it,
    never a later draft — so the k+1 output rows each equal what a
    sequential :func:`sdpa_decode` chain would have produced (the
    acceptance rule compares them against the proposal distribution).
    Requires the verify window not to wrap (``total_len <= C``); the
    decode layer gates speculation off near the ring boundary and falls
    back to single-token steps, keeping every shape static.
    """
    return _sdpa_cache(q, k_cache, v_cache, total_len, num_heads, scale,
                       num_kv_heads=num_kv_heads)


# ---------------------------------------------------------------------------
# Paged mode — the KV pool is one device-resident buffer of fixed-size pages
# per attention node (vLLM's PagedAttention memory plan, Kwon et al. SOSP
# 2023), shared by every serving slot, and each slot carries a PAGE TABLE:
# position p of a slot lives at pool[table[slot, (p // page_tokens) %
# table_width], p % page_tokens].  The table is DATA, not shape — one traced
# decode/verify/chunk program serves every page mapping (admissions, COW
# forks, retirements never retrace).  Because the table indexes ring-mod over
# its width, the gathered per-slot view is laid out exactly like a dense
# ring buffer of capacity table_width * page_tokens, so sdpa_decode /
# sdpa_verify's length masking (including wrap) applies unchanged and paged
# results are bit-parity with a dense ring of the same capacity.  Page id 0
# is reserved as a scratch page: unmapped table entries point at it (their
# slots are masked anyway) and writes of inactive rows are redirected into
# it, which is what lets one fixed-shape batched program carry slots that
# are empty or mid-prefill.  The host side (allocator, refcounts,
# copy-on-write prefix sharing) lives in mxnet_tpu/serve/.
# ---------------------------------------------------------------------------

def paged_gather(pool, table):
    """Gather a per-slot dense-ring view out of the shared page pool.

    ``pool`` is (P, page_tokens, E) (or :class:`QuantKV` of pools);
    ``table`` is (B, M) int32 page ids.  Returns the (B, M*page_tokens, E)
    view whose index ``v`` holds the slot's position ``p`` with
    ``v == p % (M*page_tokens)`` — the dense ring layout, so the cached
    attention kernels mask it exactly like a ring buffer.  Unmapped table
    entries (id 0, the scratch page) gather garbage into slots the length
    mask already hides."""
    if isinstance(pool, QuantKV):
        return QuantKV(paged_gather(pool.data, table),
                       paged_gather(pool.scale, table))
    b, m = table.shape
    pages = pool[table]                       # (B, M, page_tokens, E)
    return pages.reshape(b, m * pool.shape[1], pool.shape[2])


def paged_append(pool, table, new, start_pos, num_heads=1, active=None,
                 valid=None):
    """Scatter ``new`` (B, t, E) into the page pool at ring positions
    [start_pos, start_pos + t) of each slot's page table.

    ``start_pos`` — scalar or (B,) tokens already appended per slot.
    ``active`` — optional (B,) 0/1 mask: rows with 0 (empty or mid-prefill
    slots riding a fixed-shape batched step) redirect their writes to the
    scratch page instead of touching real pages.  ``valid`` — optional (B,)
    count of REAL rows within ``new``'s width (a padded final prefill
    chunk): positions >= valid are redirected too, so pad garbage is never
    written at all.  A :class:`QuantKV` pool quantizes on the way in, both
    planes at the same slots.  The caller (serve.PagedKVManager) guarantees
    every really-written page is exclusively owned — copy-on-write forks
    shared pages BEFORE the step — so scatter indices never collide except
    on the scratch page, whose contents are never read unmasked.
    """
    import jax.numpy as jnp

    if isinstance(pool, QuantKV):
        qnew = quantize_kv(new, pool.data.dtype, num_heads)
        return QuantKV(
            paged_append(pool.data, table, qnew.data, start_pos,
                         active=active, valid=valid),
            paged_append(pool.scale, table, qnew.scale, start_pos,
                         active=active, valid=valid))
    b, t = new.shape[0], new.shape[1]
    m = table.shape[1]
    pt = pool.shape[1]
    c = m * pt
    start = jnp.broadcast_to(jnp.asarray(start_pos, jnp.int32).reshape(-1),
                             (b,))
    new = new.astype(pool.dtype)
    if t > c:
        # only the latest C tokens can land (same trim as cache_append)
        new = new[:, -c:]
        start = start + (t - c)
        t = c
    pos = start[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]  # (B, t)
    page = jnp.take_along_axis(table.astype(jnp.int32), (pos // pt) % m,
                               axis=1)
    write = jnp.ones((b, t), bool)
    if active is not None:
        write &= jnp.asarray(active).reshape(-1, 1).astype(bool)
    if valid is not None:
        write &= jnp.arange(t, dtype=jnp.int32)[None, :] \
            < jnp.asarray(valid, jnp.int32).reshape(-1, 1)
    page = jnp.where(write, page, 0)          # masked writes -> scratch
    slot = pos % pt
    return pool.at[page.reshape(-1), slot.reshape(-1)].set(
        new.reshape(b * t, -1))


def paged_copy(pool, src, dst):
    """Copy page ``src`` -> page ``dst`` (traced scalar ids) in one pool —
    the device half of a copy-on-write fork: the host allocator picks
    ``dst``, this kernel duplicates the shared page, and the forking slot's
    next append diverges in its own copy.  :class:`QuantKV` pools copy both
    planes."""
    if isinstance(pool, QuantKV):
        return QuantKV(paged_copy(pool.data, src, dst),
                       paged_copy(pool.scale, src, dst))
    return pool.at[dst].set(pool[src])


# Which path the last dot_product_attention dispatch traced: "flash" or
# "einsum".  Written at trace time (dispatch happens under jit tracing), so
# tests can assert the kernel path actually ran instead of silently
# regressing to 100%-einsum (round-3 verdict, Weak #2).
PATH_TAKEN = {"last": None}

# Same marker for the DECODE-side dispatch (paged_attend / cache_attend):
# "pallas" when the fused flash-decoding kernel traced, "einsum" for the
# gather+dequant+attend fallback (knob off or mesh-sharded cache), and
# "einsum-gated" when the kernel was ARMED but the shape gate
# (pallas_decode.supported) refused — a legitimate, visible fallback
# (e.g. head dims off the Mosaic tile on TPU).  mxnet_tpu.decode records
# it per program so artifact meta promises the kernel only when the
# dispatch actually took it; the mxlint flop-dtype pass then turns a
# promised-but-missing pallas_call into a lint error (the artifact-level
# tripwire), without false-flagging gated shapes.
DECODE_PATH = {"last": None}


def decode_kernel_mode():
    """``(engage, interpret)`` for the fused decode kernel under the
    current config and backend: engaged when ``MXNET_PALLAS_DECODE`` is
    set AND the backend can run it (TPU natively, anything else only
    under ``MXNET_PALLAS_INTERPRET``)."""
    from .. import config as _config

    if not _config.get("MXNET_PALLAS_DECODE"):
        return False, False
    import jax

    interpret = bool(_config.get("MXNET_PALLAS_INTERPRET"))
    on_tpu = jax.default_backend() == "tpu"
    return (on_tpu or interpret), (interpret and not on_tpu)


def paged_attend(q, k_pool, v_pool, table, total_len, num_heads=1,
                 scale=None, mesh_active=False, num_kv_heads=0):
    """Decode/verify attention over shared page pools — the ONE entry the
    decode programs call.

    With ``MXNET_PALLAS_DECODE`` armed and the shapes supported, this is
    the fused Pallas flash-decoding kernel
    (:mod:`~mxnet_tpu.ops.pallas_decode`): the page-table gather, the
    int8/fp8 dequant and the length-masked softmax run in ONE HBM pass
    over the pool, split-K parallel over cache length.  Otherwise (knob
    off, unsupported shape, or a mesh-sharded pool — Pallas is opaque to
    GSPMD) it falls back to the three-pass einsum path:
    :func:`paged_gather` + :func:`sdpa_decode`/:func:`sdpa_verify`, whose
    numerics the kernel matches within documented tolerances
    (docs/inference.md)."""
    engage, interp = decode_kernel_mode()
    if engage and not mesh_active:
        from . import pallas_decode as _pd

        if _pd.supported(q.shape, k_pool, v_pool, table.shape, num_heads,
                         interpret=interp, num_kv_heads=num_kv_heads):
            DECODE_PATH["last"] = "pallas"
            fn = _pd.flash_sdpa_decode if q.shape[1] == 1 \
                else _pd.flash_sdpa_verify
            return fn(q, k_pool, v_pool, table, total_len,
                      num_heads=num_heads, scale=scale, interpret=interp,
                      num_kv_heads=num_kv_heads)
        DECODE_PATH["last"] = "einsum-gated"
    else:
        DECODE_PATH["last"] = "einsum"
    return _sdpa_cache(q, paged_gather(k_pool, table),
                       paged_gather(v_pool, table), total_len, num_heads,
                       scale, num_kv_heads=num_kv_heads)


def cache_attend(q, k_cache, v_cache, total_len, num_heads=1, scale=None,
                 mesh_active=False, num_kv_heads=0):
    """Decode/verify attention over dense (B, C, E) ring buffers — the
    non-paged twin of :func:`paged_attend`.  The fused path is the SAME
    kernel through an identity page table
    (:func:`~mxnet_tpu.ops.pallas_decode.dense_ring_attend`), so the
    plain KV-cached serving path gets split-K decode attention too;
    fallback is :func:`sdpa_decode`/:func:`sdpa_verify` unchanged."""
    engage, interp = decode_kernel_mode()
    if engage and not mesh_active:
        from . import pallas_decode as _pd

        if _pd.supported_dense(q.shape, k_cache, v_cache, num_heads,
                               interpret=interp,
                               num_kv_heads=num_kv_heads):
            DECODE_PATH["last"] = "pallas"
            return _pd.dense_ring_attend(q, k_cache, v_cache, total_len,
                                         num_heads=num_heads, scale=scale,
                                         interpret=interp,
                                         num_kv_heads=num_kv_heads)
        DECODE_PATH["last"] = "einsum-gated"
    else:
        DECODE_PATH["last"] = "einsum"
    return _sdpa_cache(q, k_cache, v_cache, total_len, num_heads, scale,
                       num_kv_heads=num_kv_heads)


_KV_LAYOUT_WARNED = {"done": False}


def apply_kv_layout(buf, device=None):
    """Place a KV cache/pool buffer with the device layout requested by
    ``MXNET_KV_LAYOUT`` — a comma-separated ``major_to_minor``
    permutation, set from the winning row of ``benchmarks/layout_probe.py
    --kv`` (which times decode attention under each candidate pool layout
    on the bench chip, per the ROADMAP's wire-the-probe clause).

    Empty knob (default): the persistent tuning cache is consulted for
    a ``--kv`` winner this probe ingested on this device generation
    (op ``"kv_layout"``, keyed by pool rank + dtype); a cached native
    winner or a cache miss is a plain ``device_put`` to ``device`` (or
    the buffer as-is when no device is given).  Backends without
    ``jax.experimental.layout`` support for the request — the CPU harness
    — fall back to the native layout with a one-time warning, so the knob
    is safe to leave set in mixed fleets."""
    import jax

    from .. import config as _config

    spec = str(_config.get("MXNET_KV_LAYOUT")).strip()
    if not spec:
        try:
            from . import tuning

            hit = tuning.get("kv_layout",
                             tuning.shape_class_for(rank=buf.ndim),
                             buf.dtype.name, version=1)
            spec = str((hit or {}).get("kv_layout", "")).strip()
        except Exception:
            spec = ""
    if not spec:
        return jax.device_put(buf, device) if device is not None else buf
    try:
        order = tuple(int(t) for t in spec.split(","))
        if sorted(order) != list(range(buf.ndim)):
            raise ValueError(
                "MXNET_KV_LAYOUT=%r is not a permutation of 0..%d"
                % (spec, buf.ndim - 1))
        from jax.experimental.layout import DeviceLocalLayout, Layout
        from jax.sharding import SingleDeviceSharding

        dev = device if device is not None else jax.devices()[0]
        target = Layout(DeviceLocalLayout(major_to_minor=order),
                        SingleDeviceSharding(dev))
        out = jax.device_put(buf, target)
        # some backends accept the API but silently keep their native
        # layout; that is fine — the request is best-effort by design
        return out
    except Exception as exc:
        if not _KV_LAYOUT_WARNED["done"]:
            _KV_LAYOUT_WARNED["done"] = True
            import warnings

            warnings.warn(
                "MXNET_KV_LAYOUT=%r not applied (%s); KV buffers keep "
                "the backend's native layout" % (spec, exc))
        return jax.device_put(buf, device) if device is not None else buf


def _attn_shape(attrs, in_shapes, aux_shapes):
    q, k, v = in_shapes
    heads = attrs.get("num_heads", 1)
    kvh, _ = check_head_groups(heads, attrs.get("num_kv_heads", 0),
                               q[-1], v[-1], k[-1],
                               where="dot_product_attention")
    assert k[0] == v[0] and k[1] == v[1], "key/value (B, T) differ"
    # grouped K/V carry H_kv heads of width hdv each; the output is one
    # hdv-wide slice per Q head (v[-1] itself when H_kv == H)
    out = (q[0], q[1], heads * (v[-1] // kvh))
    return [tuple(q), tuple(k), tuple(v)], [out], []


def register_all():
    def _compute_full(attrs, inputs, aux, octx):
        q, k, v = inputs
        heads = attrs.get("num_heads", 1)
        kv_heads = attrs.get("num_kv_heads", 0) or heads
        causal = attrs.get("causal", False)
        scale = attrs.get("scale", 0.0) or None
        # malformed head configs (e % heads, heads % kv_heads, grouped
        # K/V width mismatch) raise HERE, before any dispatch — they used
        # to fall through silently until some downstream reshape tripped
        check_head_groups(heads, kv_heads, q.shape[2], v.shape[2],
                          k.shape[2], where="dot_product_attention")
        from .. import config as _config

        # mesh path: with the time axis sharded on 'seq', run
        # explicit-collective ring attention INSIDE the executor program —
        # a shard_map region whose per-hop compute is the flash kernel on
        # TPU — instead of leaving the partitioner to all-gather K/V.
        # Ring attention is per-head independent, so Megatron head-group
        # sharding on 'model' composes with the K/V rotation on 'seq': the
        # in/out specs carry 'model' on the embed dim (an E-split IS a
        # head-group split — heads are contiguous hd-wide slices of E),
        # and each model shard rotates only its own K/V slice — the full
        # ring×TP (data, seq, model) composition, Module-reachable.
        if octx.mesh is not None and _config.get("MXNET_RING_ATTENTION"):
            mesh_axes = dict(octx.mesh.shape)
            b, tq, e = q.shape
            seq_par = mesh_axes.get("seq", 1)
            model_par = mesh_axes.get("model", 1)
            # malformed head configs already raised above (ValueError
            # naming the dims); what remains here are legitimate DEGRADE
            # conditions: heads % model (and kv_heads % model — a grouped
            # E-split is an H_kv-split on K/V) keep head groups whole per
            # model shard; indivisible configs degrade to the GSPMD
            # einsum, never to wrong numbers.
            if (seq_par > 1 and k.shape[1] == tq and v.shape[1] == tq
                    and heads % model_par == 0
                    and kv_heads % model_par == 0
                    and tq % seq_par == 0
                    and b % mesh_axes.get("data", 1) == 0):
                from jax.sharding import PartitionSpec as P

                from ..parallel.compat import shard_map
                from ..parallel.ring import ring_attention

                data_ax = "data" if mesh_axes.get("data", 1) > 1 else None
                model_ax = "model" if model_par > 1 else None
                spec = P(data_ax, "seq", model_ax)
                # schedule knob threaded explicitly so the trace bakes in
                # the CURRENT config value (the ring would otherwise read
                # it lazily at trace time — same value, but the dispatch
                # is where benchmarks A/B the schedules from)
                dbuf = _config.get("MXNET_RING_DOUBLE_BUFFER")
                ring = shard_map(
                    lambda q_, k_, v_: ring_attention(
                        q_, k_, v_, axis_name="seq", num_heads=heads,
                        causal=causal, scale=scale, head_axis=model_ax,
                        double_buffer=dbuf, num_kv_heads=kv_heads),
                    mesh=octx.mesh, in_specs=(spec,) * 3, out_specs=spec,
                    check_vma=False)
                PATH_TAKEN["last"] = "ring"
                return [ring(q, k, v)], []

        # single-chip fast path, training AND inference (the backward
        # kernels + custom_vjp make pallas differentiable):
        #  - it is opaque to GSPMD -> mesh-sharded executors take einsum
        #    (which the partitioner splits over 'seq'); explicit-collective
        #    long context uses parallel.ring instead;
        #  - on non-TPU backends interpret mode would be a slow emulation,
        #    so they take einsum too unless MXNET_PALLAS_INTERPRET forces
        #    the kernel (tests exercise the real dispatch on CPU with it).
        if not octx.mesh_active and _config.get("MXNET_PALLAS_ATTENTION"):
            from . import pallas_attention as _pa

            import jax

            interpret = bool(_config.get("MXNET_PALLAS_INTERPRET"))
            on_tpu = jax.default_backend() == "tpu"
            if (on_tpu or interpret) \
                    and _pa.supported(q.shape, k.shape, causal, heads,
                                      num_kv_heads=kv_heads):
                PATH_TAKEN["last"] = "flash"
                out = _pa.sdpa_flash(q, k, v, heads, causal, scale,
                                     interpret=interpret and not on_tpu,
                                     num_kv_heads=kv_heads)
                return [out], []
        PATH_TAKEN["last"] = "einsum"
        return [sdpa(q, k, v, num_heads=heads, causal=causal,
                     scale=scale, num_kv_heads=kv_heads)], []

    register_op(OpDef(
        "dot_product_attention", _compute_full,
        schema=ParamSchema(
            Param("num_heads", int, default=1),
            Param("num_kv_heads", int, default=0,
                  doc="grouped-query attention: K/V head count "
                      "(must divide num_heads); 0 = num_heads (MHA)"),
            Param("causal", bool, default=False),
            Param("scale", float, default=0.0,
                  doc="0 = 1/sqrt(head_dim)"),
        ),
        num_inputs=3, arguments=["query", "key", "value"],
        infer_shape=_attn_shape, needs_train=True,
        doc="Multi-head scaled-dot-product attention over projected "
            "(B, T, E) inputs.  Leapfrog op: no reference analog "
            "(SURVEY §2.5 row 'Sequence-length scaling'); sequence "
            "parallelism arrives via GSPMD seq-axis sharding or "
            "parallel.ring.ring_attention."),
        aliases=("_contrib_DotProductAttention",))
