"""Spatial operators: ROI pooling, spatial transformer family, crop,
correlation.

TPU-native designs of the reference's spatial layer ops
(`src/operator/roi_pooling.cc`, `spatial_transformer-inl.h`,
`bilinear_sampler-inl.h`, `grid_generator-inl.h`, `crop-inl.h`,
`correlation-inl.h`).  Every kernel is fully vectorized jnp — masked
reductions and flat gathers instead of the reference's per-pixel CUDA
loops — so XLA can tile them, and gradients come from jax AD rather than
hand-written backward kernels.
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op, simple_compute


def _pair(v, n=2):
    if isinstance(v, int):
        return (v,) * n
    if len(v) == 1:
        return tuple(v) * n
    return tuple(v)


# ---------------------------------------------------------------------------
# ROIPooling
# ---------------------------------------------------------------------------

def _roi_pool_one(data, roi, pooled, spatial_scale):
    """Max-pool one ROI from (C, H, W) via a bin-membership mask.

    Bin edges follow the reference: start = floor(i * l / P), end =
    ceil((i+1) * l / P) over the scaled-and-rounded ROI window, so bins can
    overlap by one row/col exactly as in roi_pooling.cc.
    """
    import jax.numpy as jnp

    c, h, w = data.shape
    ph, pw = pooled

    def c_round(v):
        # C round(): half away from zero (jnp.round is half-to-even, which
        # would shift bin edges for coords landing exactly on .5)
        return jnp.trunc(v + jnp.copysign(0.5, v)).astype(jnp.int32)

    # reference rounds the scaled corners to the integer grid
    x1 = c_round(roi[1] * spatial_scale)
    y1 = c_round(roi[2] * spatial_scale)
    x2 = c_round(roi[3] * spatial_scale)
    y2 = c_round(roi[4] * spatial_scale)
    roi_h = jnp.maximum(y2 - y1 + 1, 1)
    roi_w = jnp.maximum(x2 - x1 + 1, 1)

    ys = jnp.arange(h)
    xs = jnp.arange(w)

    def bin_bounds(i, length, n_bins, origin):
        lo = origin + (i * length) // n_bins
        hi = origin + -((-(i + 1) * length) // n_bins)  # ceil division
        return lo, hi

    bi = jnp.arange(ph)
    bj = jnp.arange(pw)
    y_lo, y_hi = bin_bounds(bi, roi_h, ph, y1)         # (ph,)
    x_lo, x_hi = bin_bounds(bj, roi_w, pw, x1)         # (pw,)
    # membership masks: (ph, H) and (pw, W)
    ymask = (ys[None, :] >= y_lo[:, None]) & (ys[None, :] < y_hi[:, None])
    xmask = (xs[None, :] >= x_lo[:, None]) & (xs[None, :] < x_hi[:, None])
    mask = ymask[:, None, :, None] & xmask[None, :, None, :]  # (ph,pw,H,W)

    neg = jnp.asarray(-jnp.inf, data.dtype)
    # (C, ph, pw, H, W) -> max over pixels
    masked = jnp.where(mask[None], data[:, None, None, :, :], neg)
    out = masked.max(axis=(-2, -1))
    # empty bins pool to 0 (reference memsets the output)
    return jnp.where(jnp.isfinite(out), out, 0.0)


def _roi_pooling(attrs, data, rois):
    import jax

    pooled = _pair(attrs["pooled_size"])
    scale = attrs["spatial_scale"]

    def one(roi):
        image = jax.lax.dynamic_index_in_dim(
            data, roi[0].astype("int32"), keepdims=False)
        return _roi_pool_one(image, roi, pooled, scale)

    return jax.vmap(one)(rois).astype(data.dtype)


def _roi_shape(attrs, in_shapes, aux_shapes):
    dshape, rshape = in_shapes
    ph, pw = _pair(attrs["pooled_size"])
    return in_shapes, [(rshape[0], dshape[1], ph, pw)], []


# ---------------------------------------------------------------------------
# GridGenerator / BilinearSampler / SpatialTransformer
# ---------------------------------------------------------------------------

def _base_grid(h, w, dtype):
    """Normalized target coords in [-1, 1]: returns (3, h*w) rows x,y,1."""
    import jax.numpy as jnp

    ys = jnp.linspace(-1.0, 1.0, h, dtype=dtype)
    xs = jnp.linspace(-1.0, 1.0, w, dtype=dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    return jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])


def _grid_generator(attrs, data):
    import jax.numpy as jnp

    mode = attrs["transform_type"]
    if mode == "affine":
        h, w = _pair(attrs["target_shape"])
        theta = data.reshape(-1, 2, 3)
        grid = theta @ _base_grid(h, w, data.dtype)     # (N, 2, h*w)
        return grid.reshape(-1, 2, h, w)
    if mode == "warp":
        # data: (N, 2, H, W) pixel flow; output normalized sample coords
        n, _, h, w = data.shape
        gy, gx = jnp.meshgrid(jnp.arange(h, dtype=data.dtype),
                              jnp.arange(w, dtype=data.dtype), indexing="ij")
        x = data[:, 0] + gx
        y = data[:, 1] + gy
        xn = 2.0 * x / jnp.maximum(w - 1, 1) - 1.0
        yn = 2.0 * y / jnp.maximum(h - 1, 1) - 1.0
        return jnp.stack([xn, yn], axis=1)
    raise ValueError("transform_type must be 'affine' or 'warp'")


def _grid_shape(attrs, in_shapes, aux_shapes):
    mode = attrs["transform_type"]
    dshape = in_shapes[0]
    if mode == "affine":
        h, w = _pair(attrs["target_shape"])
        return [(dshape[0], 6)], [(dshape[0], 2, h, w)], []
    return in_shapes, [dshape], []


def _bilinear_sample(data, grid):
    """Sample (N,C,H,W) at normalized grid (N,2,h,w); zero outside."""
    import jax.numpy as jnp

    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0            # (N, gh, gw)
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        """data values at integer coords, 0 outside the image."""
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        flat = data.reshape(n, c, h * w)
        idx = (yc * w + xc).reshape(n, 1, -1)           # (N,1,gh*gw)
        vals = jnp.take_along_axis(flat, jnp.broadcast_to(
            idx, (n, c, idx.shape[-1])), axis=2)
        vals = vals.reshape(n, c, *yi.shape[1:])
        return vals * valid[:, None].astype(data.dtype)

    tl = gather(y0, x0)
    tr = gather(y0, x0 + 1)
    bl = gather(y0 + 1, x0)
    br = gather(y0 + 1, x0 + 1)
    wx = wx[:, None]
    wy = wy[:, None]
    return (tl * (1 - wx) * (1 - wy) + tr * wx * (1 - wy)
            + bl * (1 - wx) * wy + br * wx * wy)


def _bilinear_sampler(attrs, data, grid):
    return _bilinear_sample(data, grid).astype(data.dtype)


def _sampler_shape(attrs, in_shapes, aux_shapes):
    dshape, gshape = in_shapes
    return in_shapes, [(dshape[0], dshape[1], gshape[2], gshape[3])], []


def _spatial_transformer(attrs, data, loc):
    h, w = _pair(attrs["target_shape"])
    theta = loc.reshape(-1, 2, 3)
    grid = (theta @ _base_grid(h, w, data.dtype)).reshape(-1, 2, h, w)
    return _bilinear_sample(data, grid).astype(data.dtype)


def _st_shape(attrs, in_shapes, aux_shapes):
    dshape = in_shapes[0]
    h, w = _pair(attrs["target_shape"])
    return [dshape, (dshape[0], 6)], [(dshape[0], dshape[1], h, w)], []


# ---------------------------------------------------------------------------
# Crop
# ---------------------------------------------------------------------------

def _crop_window(attrs, h, w, th, tw):
    """Resolve (oy, ox) and validate the crop fits (reference crop-inl.h
    CHECKs bounds; silent truncation would contradict infer_shape)."""
    if attrs["center_crop"]:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = _pair(attrs["offset"])
    if th > h or tw > w or oy < 0 or ox < 0 or oy + th > h or ox + tw > w:
        raise ValueError(
            "Crop window offset=(%d,%d) size=(%d,%d) exceeds input (%d,%d)"
            % (oy, ox, th, tw, h, w))
    return oy, ox


def _crop(attrs, data, *like):
    if like:
        th, tw = like[0].shape[2], like[0].shape[3]
    else:
        th, tw = _pair(attrs["h_w"])
    oy, ox = _crop_window(attrs, data.shape[2], data.shape[3], th, tw)
    return data[:, :, oy:oy + th, ox:ox + tw]


def _crop_shape(attrs, in_shapes, aux_shapes):
    dshape = in_shapes[0]
    if len(in_shapes) > 1:
        th, tw = in_shapes[1][2], in_shapes[1][3]
    else:
        th, tw = _pair(attrs["h_w"])
    _crop_window(attrs, dshape[2], dshape[3], th, tw)  # bounds check
    return in_shapes, [(dshape[0], dshape[1], th, tw)], []


# ---------------------------------------------------------------------------
# Correlation (FlowNet-style)
# ---------------------------------------------------------------------------

def _correlation(attrs, data1, data2):
    """Patch cross-correlation between two feature maps.

    For each displacement (dy, dx) on the search grid, the per-position
    correlation is the channel-mean of data1 * shift(data2) averaged over
    the patch window — expressed as shifts + an average pool so the whole
    op is three fused XLA ops per displacement instead of a 6-deep loop
    nest (correlation-inl.h).
    """
    import jax.numpy as jnp
    from jax import lax

    max_disp = attrs["max_displacement"]
    stride1 = attrs["stride1"]
    stride2 = attrs["stride2"]
    kernel = attrs["kernel_size"]
    # the shift window needs at least max_disp of padding to stay in bounds
    pad = max(attrs["pad_size"], max_disp)
    is_mult = attrs["is_multiply"]

    n, c, h, w = data1.shape
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    offsets = list(range(-max_disp, max_disp + 1, stride2))
    maps = []
    for dy in offsets:
        for dx in offsets:
            shifted = lax.dynamic_slice(
                p2, (0, 0, pad + dy, pad + dx), (n, c, h, w))
            prod = data1 * shifted if is_mult else jnp.abs(data1 - shifted)
            corr = prod.mean(axis=1)                   # channel mean (N,H,W)
            if kernel > 1:
                corr = lax.reduce_window(
                    corr, 0.0, lax.add, (1, kernel, kernel), (1, 1, 1),
                    "SAME") / (kernel * kernel)
            # stride1 subsamples the output positions (FlowNet-C uses 2)
            maps.append(corr[:, ::stride1, ::stride1])
    return jnp.stack(maps, axis=1).astype(data1.dtype)  # (N, D*D, h', w')


def _correlation_shape(attrs, in_shapes, aux_shapes):
    dshape = in_shapes[0]
    max_disp = attrs["max_displacement"]
    s1 = attrs["stride1"]
    d = len(range(-max_disp, max_disp + 1, attrs["stride2"]))
    out_h = -(-dshape[2] // s1)
    out_w = -(-dshape[3] // s1)
    return in_shapes, [(dshape[0], d * d, out_h, out_w)], []


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------

def register_all():
    register_op(OpDef(
        "ROIPooling", simple_compute(_roi_pooling),
        schema=ParamSchema(
            Param("pooled_size", "shape", required=True),
            Param("spatial_scale", float, required=True)),
        num_inputs=2, arguments=["data", "rois"],
        infer_shape=_roi_shape, hint="roipooling",
        doc="Max-pool regions of interest to a fixed size "
            "(ref: src/operator/roi_pooling.cc)."))

    register_op(OpDef(
        "GridGenerator", simple_compute(_grid_generator),
        schema=ParamSchema(
            Param("transform_type", str, required=True),
            Param("target_shape", "shape", default=(0, 0))),
        num_inputs=1, arguments=["data"],
        infer_shape=_grid_shape, hint="gridgenerator",
        doc="Sampling-grid generation for bilinear sampling "
            "(ref: src/operator/grid_generator-inl.h)."))

    register_op(OpDef(
        "BilinearSampler", simple_compute(_bilinear_sampler),
        num_inputs=2, arguments=["data", "grid"],
        infer_shape=_sampler_shape, hint="bilinearsampler",
        doc="Bilinear sampling by normalized grid, zero padding outside "
            "(ref: src/operator/bilinear_sampler-inl.h)."))

    register_op(OpDef(
        "SpatialTransformer", simple_compute(_spatial_transformer),
        schema=ParamSchema(
            Param("target_shape", "shape", required=True),
            Param("transform_type", str, default="affine"),
            Param("sampler_type", str, default="bilinear")),
        num_inputs=2, arguments=["data", "loc"],
        infer_shape=_st_shape, hint="spatialtransformer",
        doc="Affine spatial transformer network layer "
            "(ref: src/operator/spatial_transformer-inl.h)."))

    register_op(OpDef(
        "Crop", simple_compute(_crop),
        schema=ParamSchema(
            Param("num_args", int, required=True),
            Param("offset", "shape", default=(0, 0)),
            Param("h_w", "shape", default=(0, 0)),
            Param("center_crop", bool, default=False)),
        num_inputs=lambda a: a["num_args"],
        arguments=lambda a: ["data"] if a["num_args"] == 1
        else ["data", "crop_like"],
        key_var_num_args="num_args",
        infer_shape=_crop_shape, hint="crop",
        doc="Spatial crop to explicit size or a reference symbol's size "
            "(ref: src/operator/crop-inl.h)."))

    register_op(OpDef(
        "Correlation", simple_compute(_correlation),
        schema=ParamSchema(
            Param("kernel_size", int, default=1),
            Param("max_displacement", int, default=1),
            Param("stride1", int, default=1),
            Param("stride2", int, default=1),
            Param("pad_size", int, default=0),
            Param("is_multiply", bool, default=True)),
        num_inputs=2, arguments=["data1", "data2"],
        infer_shape=_correlation_shape, hint="correlation",
        doc="Patch cross-correlation of two feature maps "
            "(ref: src/operator/correlation-inl.h)."))
