"""Elementwise unary, binary, scalar, and logic operators.

Covers the reference's `src/operator/tensor/elemwise_unary_op.cc` (68 regs),
`elemwise_binary_op*.cc`, `elemwise_binary_broadcast_op*.cc`,
`elemwise_scalar_op*.cc`, and `elemwise_sum.cc`.  One table-driven
registration per family; compute bodies are jax.numpy — XLA fuses chains of
these into single kernels, which is the TPU-native replacement for mshadow
expression templates.
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op, simple_compute


def _jnp():
    import jax.numpy as jnp

    return jnp


def _erf(x):
    import jax

    return jax.scipy.special.erf(x)


def _gamma(x):
    import jax

    return jax.numpy.exp(jax.scipy.special.gammaln(x))


def _softrelu(x):
    jnp = _jnp()
    return jnp.logaddexp(x, 0.0)


def _unary_table():
    jnp = _jnp()
    import jax

    return {
        "abs": jnp.abs,
        "sign": jnp.sign,
        "rint": jnp.rint,
        "ceil": jnp.ceil,
        "floor": jnp.floor,
        "trunc": jnp.trunc,
        "fix": jnp.trunc,
        "round": jnp.round,
        "square": jnp.square,
        "sqrt": jnp.sqrt,
        "rsqrt": lambda x: 1.0 / jnp.sqrt(x),
        "cbrt": jnp.cbrt,
        "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
        "exp": jnp.exp,
        "log": jnp.log,
        "log10": jnp.log10,
        "log2": jnp.log2,
        "log1p": jnp.log1p,
        "expm1": jnp.expm1,
        "sin": jnp.sin,
        "cos": jnp.cos,
        "tan": jnp.tan,
        "arcsin": jnp.arcsin,
        "arccos": jnp.arccos,
        "arctan": jnp.arctan,
        "sinh": jnp.sinh,
        "cosh": jnp.cosh,
        "tanh": jnp.tanh,
        "arcsinh": jnp.arcsinh,
        "arccosh": jnp.arccosh,
        "arctanh": jnp.arctanh,
        "degrees": jnp.degrees,
        "radians": jnp.radians,
        "gamma": _gamma,
        "gammaln": lambda x: jax.scipy.special.gammaln(x),
        "erf": _erf,
        "negative": jnp.negative,
        "reciprocal": lambda x: 1.0 / x,
        "sigmoid": jax.nn.sigmoid,
        "relu": lambda x: jnp.maximum(x, 0),
        "softsign": lambda x: x / (1.0 + jnp.abs(x)),
        "softrelu": _softrelu,
        "logical_not": lambda x: (x == 0).astype(x.dtype),
    }


def register_all():
    jnp = _jnp()

    for name, fn in _unary_table().items():
        register_op(
            OpDef(name, simple_compute(lambda attrs, x, f=fn: f(x)), num_inputs=1,
                  doc="Elementwise %s." % name)
        )

    # identity-style ops
    register_op(
        OpDef("_copy", simple_compute(lambda attrs, x: x + 0), num_inputs=1),
        aliases=["identity"],
    )
    register_op(
        OpDef(
            "_identity_with_attr_like_rhs",
            simple_compute(lambda attrs, lhs, rhs: lhs),
            num_inputs=2,
            visible=False,
        )
    )

    def _cast(attrs, x):
        dt = attrs["dtype"]
        if dt == "bfloat16":
            return x.astype(jnp.bfloat16)
        return x.astype(np.dtype(dt))

    def _cast_type(attrs, in_types, aux_types):
        dt = attrs["dtype"]
        if dt == "bfloat16":
            import ml_dtypes

            dt = np.dtype(ml_dtypes.bfloat16)
        else:
            dt = np.dtype(dt)
        return in_types, [dt], aux_types

    register_op(
        OpDef("Cast", simple_compute(_cast),
              schema=ParamSchema(Param("dtype", str, required=True)),
              num_inputs=1, hint="cast", infer_type=_cast_type),
        aliases=["cast"],
    )

    # -- binary elementwise (broadcast-capable, superset of reference _plus) --
    def binary_table():
        import jax

        def fmod(a, b):
            return a - jnp.trunc(a / b) * b

        return {
            "plus": jnp.add,
            "minus": jnp.subtract,
            "mul": jnp.multiply,
            "div": jnp.divide,
            "mod": fmod,
            "power": jnp.power,
            "maximum": jnp.maximum,
            "minimum": jnp.minimum,
            "hypot": jnp.hypot,
        }

    for name, fn in binary_table().items():
        # canonical arithmetic name: plus->add, minus->sub, else unchanged
        canon = {"plus": "add", "minus": "sub"}.get(name, name)
        # elemwise form: _plus / _minus / ... (reference elemwise_binary_op.cc)
        extra = []
        if canon != name:
            extra.append("_" + canon)
        if name in ("plus", "minus", "mul", "div"):
            # reference registers elemwise_{add,sub,mul,div} names too
            extra.append("elemwise_" + canon)
        register_op(
            OpDef("_" + name, simple_compute(lambda attrs, a, b, f=fn: f(a, b)),
                  num_inputs=2, hint=name),
            aliases=extra,
        )
        # broadcast form: broadcast_add / broadcast_plus ...
        main = "broadcast_" + canon
        ali = ["broadcast_" + name] if main != "broadcast_" + name else []
        register_op(
            OpDef(main, simple_compute(lambda attrs, a, b, f=fn: f(a, b)),
                  num_inputs=2, hint=main),
            aliases=ali,
        )
        # scalar forms: _plus_scalar, _rminus_scalar, ...
        sschema = ParamSchema(Param("scalar", float, required=True))
        register_op(
            OpDef("_%s_scalar" % name,
                  simple_compute(lambda attrs, a, f=fn: f(a, jnp.asarray(attrs["scalar"], a.dtype))),
                  schema=sschema, num_inputs=1, hint=name)
        )
        if name in ("minus", "div", "power", "mod"):
            register_op(
                OpDef("_r%s_scalar" % name,
                      simple_compute(
                          lambda attrs, a, f=fn: f(jnp.asarray(attrs["scalar"], a.dtype), a)),
                      schema=sschema, num_inputs=1, hint=name)
            )

    # comparison / logic (return 0/1 in the input dtype, as the reference does)
    def logic_table():
        return {
            "equal": jnp.equal,
            "not_equal": jnp.not_equal,
            "greater": jnp.greater,
            "greater_equal": jnp.greater_equal,
            "lesser": jnp.less,
            "lesser_equal": jnp.less_equal,
        }

    for name, fn in logic_table().items():
        register_op(
            OpDef("broadcast_" + name,
                  simple_compute(lambda attrs, a, b, f=fn: f(a, b).astype(a.dtype)),
                  num_inputs=2, hint=name),
            aliases=["_" + name],
        )
        register_op(
            OpDef("_%s_scalar" % name,
                  simple_compute(lambda attrs, a, f=fn: f(a, attrs["scalar"]).astype(a.dtype)),
                  schema=ParamSchema(Param("scalar", float, required=True)),
                  num_inputs=1, hint=name)
        )

    # smooth_l1 (reference: elemwise_unary_op.cc smooth_l1 w/ scalar sigma)
    def _smooth_l1(attrs, x):
        import jax

        s2 = float(attrs.get("scalar", 1.0)) ** 2

        @jax.custom_jvp
        def f(v):
            av = jnp.abs(v)
            return jnp.where(av < 1.0 / s2, 0.5 * s2 * v * v, av - 0.5 / s2)

        @f.defjvp
        def f_jvp(primals, tangents):
            (v,), (dv,) = primals, tangents
            g = jnp.where(jnp.abs(v) < 1.0 / s2, s2 * v, jnp.sign(v))
            return f(v), g * dv

        return f(x)

    register_op(
        OpDef("smooth_l1", simple_compute(_smooth_l1),
              schema=ParamSchema(Param("scalar", float, default=1.0)), num_inputs=1)
    )

    # add_n / ElementWiseSum: variadic sum (reference: elemwise_sum.cc)
    def _add_n(attrs, *xs):
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out

    nargs_schema = ParamSchema(Param("num_args", int, required=True))
    register_op(
        OpDef("add_n", simple_compute(_add_n), schema=nargs_schema,
              num_inputs=lambda attrs: attrs["num_args"],
              arguments=lambda attrs: ["arg%d" % i for i in range(attrs["num_args"])],
              key_var_num_args="num_args", hint="add_n"),
        aliases=["ElementWiseSum", "_sum", "elemwise_sum"],
    )

    # BlockGrad / stop_gradient
    def _block_grad(attrs, x):
        import jax

        return jax.lax.stop_gradient(x)

    register_op(OpDef("BlockGrad", simple_compute(_block_grad), num_inputs=1,
                      hint="blockgrad"), aliases=["stop_gradient"])

    # clip
    def _clip(attrs, x):
        return jnp.clip(x, attrs["a_min"], attrs["a_max"])

    register_op(
        OpDef("clip", simple_compute(_clip),
              schema=ParamSchema(Param("a_min", float, required=True),
                                 Param("a_max", float, required=True)),
              num_inputs=1)
    )

    # _maximum/_minimum scalar already above via table; mod handled too
    # _grad_add: used by executor for gradient accumulation
    register_op(
        OpDef("_grad_add", simple_compute(lambda attrs, a, b: a + b), num_inputs=2,
              visible=False)
    )


def register_op_with_aliases(opdef, aliases):
    register_op(opdef, aliases)
