"""Fused optimizer update kernels.

Reference: `src/operator/optimizer_op.cc` — sgd_update, sgd_mom_update,
adam_update, rmsprop_update, rmspropalex_update; these are what
`python/mxnet/optimizer.py` dispatches to.  TPU-native deviation: state
tensors (momentum etc.) are *returned* as extra outputs instead of being
mutated through engine write-vars; `mxnet_tpu.optimizer` writes them back,
preserving the user-visible in-place behavior.  Each update is one jitted
XLA fusion — the analog of the reference's single fused kernel.
"""
from __future__ import annotations

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op, simple_compute


def _common_params(*extra):
    return ParamSchema(
        *extra,
        Param("lr", float, required=True),
        Param("wd", float, default=0.0),
        Param("rescale_grad", float, default=1.0),
        Param("clip_gradient", float, default=-1.0),
    )


def _prep_grad(attrs, grad, jnp):
    g = grad * attrs.get("rescale_grad", 1.0)
    clip = attrs.get("clip_gradient", -1.0)
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    return g


def register_all():
    import jax.numpy as jnp

    def _sgd(attrs, weight, grad):
        g = _prep_grad(attrs, grad, jnp)
        return weight - attrs["lr"] * (g + attrs.get("wd", 0.0) * weight)

    register_op(OpDef("sgd_update", simple_compute(_sgd), schema=_common_params(),
                      num_inputs=2, arguments=["weight", "grad"]))

    def _sgd_mom(attrs, weight, grad, mom):
        g = _prep_grad(attrs, grad, jnp)
        new_mom = attrs.get("momentum", 0.0) * mom - \
            attrs["lr"] * (g + attrs.get("wd", 0.0) * weight)
        return weight + new_mom, new_mom

    register_op(OpDef("sgd_mom_update", simple_compute(_sgd_mom),
                      schema=_common_params(Param("momentum", float, default=0.0)),
                      num_inputs=3, num_outputs=2,
                      arguments=["weight", "grad", "mom"],
                      outputs=["weight", "mom"]))

    def _adam(attrs, weight, grad, mean, var):
        g = _prep_grad(attrs, grad, jnp)
        b1 = attrs.get("beta1", 0.9)
        b2 = attrs.get("beta2", 0.999)
        eps = attrs.get("epsilon", 1e-8)
        g = g + attrs.get("wd", 0.0) * weight
        new_mean = b1 * mean + (1 - b1) * g
        new_var = b2 * var + (1 - b2) * jnp.square(g)
        w = weight - attrs["lr"] * new_mean / (jnp.sqrt(new_var) + eps)
        return w, new_mean, new_var

    register_op(OpDef("adam_update", simple_compute(_adam),
                      schema=_common_params(Param("beta1", float, default=0.9),
                                            Param("beta2", float, default=0.999),
                                            Param("epsilon", float, default=1e-8)),
                      num_inputs=4, num_outputs=3,
                      arguments=["weight", "grad", "mean", "var"],
                      outputs=["weight", "mean", "var"]))

    def _rmsprop(attrs, weight, grad, n):
        g = _prep_grad(attrs, grad, jnp)
        g = g + attrs.get("wd", 0.0) * weight
        rho = attrs.get("gamma1", 0.95)
        eps = attrs.get("epsilon", 1e-8)
        new_n = rho * n + (1 - rho) * jnp.square(g)
        cw = attrs.get("clip_weights", -1.0)
        w = weight - attrs["lr"] * g / jnp.sqrt(new_n + eps)
        if cw is not None and cw > 0:
            w = jnp.clip(w, -cw, cw)
        return w, new_n

    register_op(OpDef("rmsprop_update", simple_compute(_rmsprop),
                      schema=_common_params(Param("gamma1", float, default=0.95),
                                            Param("epsilon", float, default=1e-8),
                                            Param("clip_weights", float, default=-1.0)),
                      num_inputs=3, num_outputs=2,
                      arguments=["weight", "grad", "n"],
                      outputs=["weight", "n"]))

    def _rmspropalex(attrs, weight, grad, n, g_state, delta):
        g = _prep_grad(attrs, grad, jnp)
        g = g + attrs.get("wd", 0.0) * weight
        rho = attrs.get("gamma1", 0.95)
        mom = attrs.get("gamma2", 0.9)
        eps = attrs.get("epsilon", 1e-8)
        new_n = rho * n + (1 - rho) * jnp.square(g)
        new_g = rho * g_state + (1 - rho) * g
        new_delta = mom * delta - attrs["lr"] * g / \
            jnp.sqrt(new_n - jnp.square(new_g) + eps)
        w = weight + new_delta
        cw = attrs.get("clip_weights", -1.0)
        if cw is not None and cw > 0:
            w = jnp.clip(w, -cw, cw)
        return w, new_n, new_g, new_delta

    register_op(OpDef("rmspropalex_update", simple_compute(_rmspropalex),
                      schema=_common_params(Param("gamma1", float, default=0.95),
                                            Param("gamma2", float, default=0.9),
                                            Param("epsilon", float, default=1e-8),
                                            Param("clip_weights", float, default=-1.0)),
                      num_inputs=5, num_outputs=4,
                      arguments=["weight", "grad", "n", "g", "delta"],
                      outputs=["weight", "n", "g", "delta"]))
