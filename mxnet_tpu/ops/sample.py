"""Random sampling operators.

Reference: `src/operator/tensor/sample_op.cc` (uniform/normal) and
`multisample_op.cc` (distribution family).  TPU-native: functional
``jax.random`` keyed from the global chain (`mxnet_tpu.random`), key passed
as a traced jit argument.
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op


def _shape_dtype(attrs, jnp):
    shape = tuple(attrs.get("shape", ()) or ())
    dt = attrs.get("dtype", "float32") or "float32"
    return shape, (jnp.bfloat16 if dt == "bfloat16" else np.dtype(dt))


def is_greedy_policy(temperature, top_k):
    """``temperature == 0`` and ``top_k == 1`` are both deterministic
    argmax — THE greedy predicate, shared by the sampler and the
    speculative verifier so they can never disagree."""
    return temperature == 0 or top_k == 1


def policy_logits(logits, temperature=1.0, top_k=0):
    """The scaled / top-k-truncated logits the sampling policy draws
    from.  Single source of truth for the policy transformation:
    :func:`sample_tokens` feeds these to ``jax.random.categorical`` and
    the speculative verifier (``decode._policy_probs``) softmaxes the
    SAME values into explicit probability vectors — the
    distribution-preservation guarantee rests on the two never
    diverging, so there is exactly one implementation."""
    import jax
    import jax.numpy as jnp

    scaled = logits.astype(jnp.float32) / float(temperature)
    if top_k and 0 < top_k < logits.shape[-1]:
        vals = jax.lax.top_k(scaled, top_k)[0]
        kth = vals[..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return scaled


def sample_tokens(key, logits, temperature=1.0, top_k=0):
    """Draw token ids from ``(..., V)`` logits (or log-probabilities).

    The decode-loop sampler (`mxnet_tpu.decode`): ``temperature == 0`` OR
    ``top_k == 1`` is greedy — a pure argmax, no PRNG fold-in, no
    ``jax.random.categorical`` on the per-token hot path (``key`` unused —
    fully deterministic, bit-identical across keys).  Otherwise draw via
    ``jax.random.categorical`` over :func:`policy_logits`.  Traceable, so
    the whole sampler bakes into the jitted decode-step program;
    determinism under a fixed PRNGKey comes from jax's counter-based RNG.
    Returns int32 ids with the leading logits dims.
    """
    import jax
    import jax.numpy as jnp

    if is_greedy_policy(temperature, top_k):
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, policy_logits(logits, temperature, top_k),
        axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Speculative sampling (Leviathan et al., "Fast Inference from Transformers
# via Speculative Decoding"): a draft proposes k tokens, the target scores
# all k+1 positions in one verify pass, and the acceptance-rejection rule
# below keeps the OUTPUT distribution exactly the target's.  Pure jnp — it
# bakes into the jitted verify program (mxnet_tpu.decode).
# ---------------------------------------------------------------------------

def residual_probs(p, q):
    """The rejection-resample distribution ``norm(max(p - q, 0))``.

    ``p``/``q`` are (..., V) probability vectors (target and proposal at
    the first rejected position).  The identity that makes speculative
    sampling exact:  ``q(v) * min(1, p(v)/q(v)) + P(reject) * res(v) =
    p(v)`` with ``P(reject) = 1 - sum_u q(u) min(1, p(u)/q(u))`` — pinned
    by tests/test_decode.py.  Degenerate ``p <= q`` everywhere (reject
    probability zero, the branch is never taken) falls back to ``p`` so
    the program stays NaN-free.
    """
    import jax.numpy as jnp

    res = jnp.maximum(p.astype(jnp.float32) - q.astype(jnp.float32), 0.0)
    tot = jnp.sum(res, axis=-1, keepdims=True)
    return jnp.where(tot > 0, res / jnp.where(tot > 0, tot, 1.0), p)


def speculative_accept(key, target_probs, draft_toks, draft_probs=None,
                       greedy=False):
    """Accept a prefix of k drafted tokens against k+1 target
    distributions; resample at the first mismatch.

    Parameters
    ----------
    key
        PRNG key (unused when ``greedy``).
    target_probs : (B, k+1, V)
        The target model's sampling distributions at the k+1 verify
        positions: row i is ``p(. | prefix, d_1..d_i)`` (row 0
        conditions on the last committed token only; row k is the bonus
        distribution after all k drafts).
    draft_toks : (B, k) int32
        The proposed tokens ``d_1..d_k``.
    draft_probs : (B, k, V) or None
        The proposal distributions the drafts were DRAWN from.  ``None``
        means a deterministic proposer (n-gram lookup, greedy draft):
        ``q_i`` is a delta at ``d_i``, so acceptance is ``u < p_i(d_i)``
        and the residual is ``p_i`` with ``d_i`` zeroed, renormalized.
    greedy : bool
        Target samples by argmax: accept ``d_i`` iff it IS the argmax of
        ``p_i``; the resampled/bonus token is an argmax too.  Output then
        equals target-only greedy decoding token for token.

    Returns ``(counts, out_toks)``: ``counts`` (B,) int32 in [1, k+1] —
    accepted drafts + the one resampled/bonus token; ``out_toks``
    (B, k+1) int32 — the emitted tokens, valid through ``counts`` (later
    columns are garbage the caller must mask).
    """
    import jax
    import jax.numpy as jnp

    b, kp1, v = target_probs.shape
    k = kp1 - 1
    p = target_probs.astype(jnp.float32)
    toks = draft_toks.astype(jnp.int32)
    rows = jnp.arange(b)

    if greedy:
        tgt = jnp.argmax(p, axis=-1).astype(jnp.int32)        # (B, k+1)
        accept = toks == tgt[:, :k]                            # (B, k)
    else:
        p_at_d = jnp.take_along_axis(p[:, :k], toks[..., None],
                                     axis=-1)[..., 0]          # (B, k)
        if draft_probs is None:
            ratio = p_at_d                                     # q = delta
        else:
            q_at_d = jnp.take_along_axis(
                draft_probs.astype(jnp.float32), toks[..., None],
                axis=-1)[..., 0]
            ratio = p_at_d / jnp.maximum(q_at_d, 1e-30)
        key, ukey = jax.random.split(key)
        u = jax.random.uniform(ukey, (b, k))
        accept = u < ratio                                     # min(1,.) free

    # accepted prefix length a in [0, k]: drafts up to the first rejection
    a = jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(axis=1)
    # the distribution the (a+1)-th emitted token comes from: p_{a+1} —
    # which at a == k is already the bonus row — with the rejected
    # draft's proposal mass removed when a < k
    p_next = p[rows, a]                                        # (B, V)
    if greedy:
        next_tok = jnp.argmax(p_next, axis=-1).astype(jnp.int32)
    else:
        j = jnp.minimum(a, k - 1)
        if draft_probs is None:
            d_rej = toks[rows, j]
            q_row = jax.nn.one_hot(d_rej, v, dtype=jnp.float32)
        else:
            q_row = draft_probs.astype(jnp.float32)[rows, j]
        res = residual_probs(p_next, q_row)
        dist = jnp.where((a == k)[:, None], p_next, res)
        next_tok = jax.random.categorical(
            key, jnp.log(dist + 1e-30), axis=-1).astype(jnp.int32)

    out = jnp.concatenate([toks, jnp.zeros((b, 1), jnp.int32)], axis=1)
    out = out.at[rows, a].set(next_tok)
    return (a + 1).astype(jnp.int32), out


def register_all():
    import jax
    import jax.numpy as jnp

    base_schema = lambda *extra: ParamSchema(
        *extra,
        Param("shape", "shape", default=()),
        Param("ctx", str, default=""),
        Param("dtype", str, default="float32"),
    )

    def _sample_shape(attrs, in_shapes, aux_shapes):
        return [], [tuple(attrs.get("shape", ()) or ())], []

    def reg(name, fn, schema, aliases=()):
        def fcompute(attrs, inputs, aux, octx):
            return [fn(attrs, octx.rng)], []

        register_op(OpDef(name, fcompute, schema=schema, num_inputs=0,
                          needs_rng=True, infer_shape=_sample_shape,
                          hint=name.lstrip("_")),
                    aliases=aliases)

    def _uniform(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        return jax.random.uniform(key, shape, minval=attrs.get("low", 0.0),
                                  maxval=attrs.get("high", 1.0)).astype(dt)

    reg("uniform", _uniform,
        base_schema(Param("low", float, default=0.0), Param("high", float, default=1.0)),
        aliases=["random_uniform"])

    def _normal(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        loc = attrs.get("loc", 0.0)
        scale = attrs.get("scale", 1.0)
        return (jax.random.normal(key, shape) * scale + loc).astype(dt)

    reg("normal", _normal,
        base_schema(Param("loc", float, default=0.0), Param("scale", float, default=1.0)),
        aliases=["random_normal"])

    def _gamma(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        a = attrs.get("alpha", 1.0)
        b = attrs.get("beta", 1.0)
        return (jax.random.gamma(key, a, shape) * b).astype(dt)

    reg("random_gamma", _gamma,
        base_schema(Param("alpha", float, default=1.0), Param("beta", float, default=1.0)))

    def _exponential(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        lam = attrs.get("lam", 1.0)
        return (jax.random.exponential(key, shape) / lam).astype(dt)

    reg("random_exponential", _exponential,
        base_schema(Param("lam", float, default=1.0)))

    def _poisson(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        lam = attrs.get("lam", 1.0)
        return jax.random.poisson(key, lam, shape).astype(dt)

    reg("random_poisson", _poisson,
        base_schema(Param("lam", float, default=1.0)))

    def _neg_binomial(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        k = attrs.get("k", 1)
        p = attrs.get("p", 1.0)
        k1, k2 = jax.random.split(key)
        lam = jax.random.gamma(k1, k, shape) * (1 - p) / p
        return jax.random.poisson(k2, lam, shape).astype(dt)

    reg("random_negative_binomial", _neg_binomial,
        base_schema(Param("k", int, default=1), Param("p", float, default=1.0)))

    def _gen_neg_binomial(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        mu = attrs.get("mu", 1.0)
        alpha = attrs.get("alpha", 1.0)
        k1, k2 = jax.random.split(key)
        r = 1.0 / alpha
        lam = jax.random.gamma(k1, r, shape) * (mu * alpha)
        return jax.random.poisson(k2, lam, shape).astype(dt)

    reg("random_generalized_negative_binomial", _gen_neg_binomial,
        base_schema(Param("mu", float, default=1.0), Param("alpha", float, default=1.0)))

    # -----------------------------------------------------------------
    # Multisample family (ref: src/operator/tensor/multisample_op.cc):
    # distribution params are TENSORS; each element draws `shape` samples
    # -> output shape = param.shape + shape.
    # -----------------------------------------------------------------
    ms_schema = ParamSchema(Param("shape", "shape", default=()),
                            Param("dtype", str, default="float32"))

    def reg_ms(name, draw, num_inputs):
        def _ms_shape(attrs, in_shapes, aux_shapes):
            s = tuple(attrs.get("shape", ()) or ())
            base = tuple(in_shapes[0]) if in_shapes[0] is not None else ()
            return [tuple(base)] * num_inputs, [base + s], []

        def fcompute(attrs, inputs, aux, octx):
            s = tuple(attrs.get("shape", ()) or ())
            dt = attrs.get("dtype", "float32") or "float32"
            dt = jnp.bfloat16 if dt == "bfloat16" else np.dtype(dt)
            base = tuple(inputs[0].shape)
            out_shape = base + s
            ps = [p.reshape(base + (1,) * len(s)).astype(jnp.float32)
                  for p in inputs]
            return [draw(octx.rng, out_shape, *ps).astype(dt)], []

        register_op(OpDef(name, fcompute, schema=ms_schema,
                          num_inputs=num_inputs, needs_rng=True,
                          infer_shape=_ms_shape, hint=name.lstrip("_")))

    reg_ms("_sample_uniform",
           lambda key, sh, lo, hi:
           lo + jax.random.uniform(key, sh) * (hi - lo), 2)
    reg_ms("_sample_normal",
           lambda key, sh, mu, sigma:
           mu + jax.random.normal(key, sh) * sigma, 2)
    reg_ms("_sample_gamma",
           lambda key, sh, alpha, beta:
           jax.random.gamma(key, jnp.broadcast_to(alpha, sh)) * beta, 2)
    reg_ms("_sample_exponential",
           lambda key, sh, lam:
           jax.random.exponential(key, sh) / lam, 1)
    reg_ms("_sample_poisson",
           lambda key, sh, lam:
           jax.random.poisson(key, jnp.broadcast_to(lam, sh)), 1)

    def _ms_neg_binomial(key, sh, k, p):
        k1, k2 = jax.random.split(key)
        lam = jax.random.gamma(k1, jnp.broadcast_to(k, sh)) * (1 - p) / p
        return jax.random.poisson(k2, lam)

    reg_ms("_sample_negative_binomial", _ms_neg_binomial, 2)

    def _ms_gen_neg_binomial(key, sh, mu, alpha):
        k1, k2 = jax.random.split(key)
        r = 1.0 / alpha
        lam = jax.random.gamma(k1, jnp.broadcast_to(r, sh)) * (mu * alpha)
        return jax.random.poisson(k2, lam)

    reg_ms("_sample_generalized_negative_binomial", _ms_gen_neg_binomial, 2)
