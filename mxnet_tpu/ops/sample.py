"""Random sampling operators.

Reference: `src/operator/tensor/sample_op.cc` (uniform/normal) and
`multisample_op.cc` (distribution family).  TPU-native: functional
``jax.random`` keyed from the global chain (`mxnet_tpu.random`), key passed
as a traced jit argument.
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op


def _shape_dtype(attrs, jnp):
    shape = tuple(attrs.get("shape", ()) or ())
    dt = attrs.get("dtype", "float32") or "float32"
    return shape, (jnp.bfloat16 if dt == "bfloat16" else np.dtype(dt))


def register_all():
    import jax
    import jax.numpy as jnp

    base_schema = lambda *extra: ParamSchema(
        *extra,
        Param("shape", "shape", default=()),
        Param("ctx", str, default=""),
        Param("dtype", str, default="float32"),
    )

    def _sample_shape(attrs, in_shapes, aux_shapes):
        return [], [tuple(attrs.get("shape", ()) or ())], []

    def reg(name, fn, schema, aliases=()):
        def fcompute(attrs, inputs, aux, octx):
            return [fn(attrs, octx.rng)], []

        register_op(OpDef(name, fcompute, schema=schema, num_inputs=0,
                          needs_rng=True, infer_shape=_sample_shape,
                          hint=name.lstrip("_")),
                    aliases=aliases)

    def _uniform(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        return jax.random.uniform(key, shape, minval=attrs.get("low", 0.0),
                                  maxval=attrs.get("high", 1.0)).astype(dt)

    reg("uniform", _uniform,
        base_schema(Param("low", float, default=0.0), Param("high", float, default=1.0)),
        aliases=["_sample_uniform", "random_uniform"])

    def _normal(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        loc = attrs.get("loc", 0.0)
        scale = attrs.get("scale", 1.0)
        return (jax.random.normal(key, shape) * scale + loc).astype(dt)

    reg("normal", _normal,
        base_schema(Param("loc", float, default=0.0), Param("scale", float, default=1.0)),
        aliases=["_sample_normal", "random_normal"])

    def _gamma(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        a = attrs.get("alpha", 1.0)
        b = attrs.get("beta", 1.0)
        return (jax.random.gamma(key, a, shape) * b).astype(dt)

    reg("_sample_gamma", _gamma,
        base_schema(Param("alpha", float, default=1.0), Param("beta", float, default=1.0)),
        aliases=["random_gamma"])

    def _exponential(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        lam = attrs.get("lam", 1.0)
        return (jax.random.exponential(key, shape) / lam).astype(dt)

    reg("_sample_exponential", _exponential,
        base_schema(Param("lam", float, default=1.0)),
        aliases=["random_exponential"])

    def _poisson(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        lam = attrs.get("lam", 1.0)
        return jax.random.poisson(key, lam, shape).astype(dt)

    reg("_sample_poisson", _poisson,
        base_schema(Param("lam", float, default=1.0)),
        aliases=["random_poisson"])

    def _neg_binomial(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        k = attrs.get("k", 1)
        p = attrs.get("p", 1.0)
        k1, k2 = jax.random.split(key)
        lam = jax.random.gamma(k1, k, shape) * (1 - p) / p
        return jax.random.poisson(k2, lam, shape).astype(dt)

    reg("_sample_negative_binomial", _neg_binomial,
        base_schema(Param("k", int, default=1), Param("p", float, default=1.0)),
        aliases=["random_negative_binomial"])

    def _gen_neg_binomial(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        mu = attrs.get("mu", 1.0)
        alpha = attrs.get("alpha", 1.0)
        k1, k2 = jax.random.split(key)
        r = 1.0 / alpha
        lam = jax.random.gamma(k1, r, shape) * (mu * alpha)
        return jax.random.poisson(k2, lam, shape).astype(dt)

    reg("_sample_generalized_negative_binomial", _gen_neg_binomial,
        base_schema(Param("mu", float, default=1.0), Param("alpha", float, default=1.0)),
        aliases=["random_generalized_negative_binomial"])
