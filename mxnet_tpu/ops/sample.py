"""Random sampling operators.

Reference: `src/operator/tensor/sample_op.cc` (uniform/normal) and
`multisample_op.cc` (distribution family).  TPU-native: functional
``jax.random`` keyed from the global chain (`mxnet_tpu.random`), key passed
as a traced jit argument.
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op


def _shape_dtype(attrs, jnp):
    shape = tuple(attrs.get("shape", ()) or ())
    dt = attrs.get("dtype", "float32") or "float32"
    return shape, (jnp.bfloat16 if dt == "bfloat16" else np.dtype(dt))


def sample_tokens(key, logits, temperature=1.0, top_k=0):
    """Draw token ids from ``(..., V)`` logits (or log-probabilities).

    The decode-loop sampler (`mxnet_tpu.decode`): ``temperature == 0`` is
    greedy argmax (``key`` unused — fully deterministic); otherwise logits
    scale by ``1/temperature``, optionally truncate to the ``top_k``
    largest (top-k sampling), and draw via ``jax.random.categorical``.
    Traceable, so the whole sampler bakes into the jitted decode-step
    program; determinism under a fixed PRNGKey comes from jax's counter-
    based RNG.  Returns int32 ids with the leading logits dims.
    """
    import jax
    import jax.numpy as jnp

    if temperature == 0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / float(temperature)
    if top_k and 0 < top_k < logits.shape[-1]:
        vals = jax.lax.top_k(scaled, top_k)[0]
        kth = vals[..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


def register_all():
    import jax
    import jax.numpy as jnp

    base_schema = lambda *extra: ParamSchema(
        *extra,
        Param("shape", "shape", default=()),
        Param("ctx", str, default=""),
        Param("dtype", str, default="float32"),
    )

    def _sample_shape(attrs, in_shapes, aux_shapes):
        return [], [tuple(attrs.get("shape", ()) or ())], []

    def reg(name, fn, schema, aliases=()):
        def fcompute(attrs, inputs, aux, octx):
            return [fn(attrs, octx.rng)], []

        register_op(OpDef(name, fcompute, schema=schema, num_inputs=0,
                          needs_rng=True, infer_shape=_sample_shape,
                          hint=name.lstrip("_")),
                    aliases=aliases)

    def _uniform(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        return jax.random.uniform(key, shape, minval=attrs.get("low", 0.0),
                                  maxval=attrs.get("high", 1.0)).astype(dt)

    reg("uniform", _uniform,
        base_schema(Param("low", float, default=0.0), Param("high", float, default=1.0)),
        aliases=["random_uniform"])

    def _normal(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        loc = attrs.get("loc", 0.0)
        scale = attrs.get("scale", 1.0)
        return (jax.random.normal(key, shape) * scale + loc).astype(dt)

    reg("normal", _normal,
        base_schema(Param("loc", float, default=0.0), Param("scale", float, default=1.0)),
        aliases=["random_normal"])

    def _gamma(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        a = attrs.get("alpha", 1.0)
        b = attrs.get("beta", 1.0)
        return (jax.random.gamma(key, a, shape) * b).astype(dt)

    reg("random_gamma", _gamma,
        base_schema(Param("alpha", float, default=1.0), Param("beta", float, default=1.0)))

    def _exponential(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        lam = attrs.get("lam", 1.0)
        return (jax.random.exponential(key, shape) / lam).astype(dt)

    reg("random_exponential", _exponential,
        base_schema(Param("lam", float, default=1.0)))

    def _poisson(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        lam = attrs.get("lam", 1.0)
        return jax.random.poisson(key, lam, shape).astype(dt)

    reg("random_poisson", _poisson,
        base_schema(Param("lam", float, default=1.0)))

    def _neg_binomial(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        k = attrs.get("k", 1)
        p = attrs.get("p", 1.0)
        k1, k2 = jax.random.split(key)
        lam = jax.random.gamma(k1, k, shape) * (1 - p) / p
        return jax.random.poisson(k2, lam, shape).astype(dt)

    reg("random_negative_binomial", _neg_binomial,
        base_schema(Param("k", int, default=1), Param("p", float, default=1.0)))

    def _gen_neg_binomial(attrs, key):
        shape, dt = _shape_dtype(attrs, jnp)
        mu = attrs.get("mu", 1.0)
        alpha = attrs.get("alpha", 1.0)
        k1, k2 = jax.random.split(key)
        r = 1.0 / alpha
        lam = jax.random.gamma(k1, r, shape) * (mu * alpha)
        return jax.random.poisson(k2, lam, shape).astype(dt)

    reg("random_generalized_negative_binomial", _gen_neg_binomial,
        base_schema(Param("mu", float, default=1.0), Param("alpha", float, default=1.0)))

    # -----------------------------------------------------------------
    # Multisample family (ref: src/operator/tensor/multisample_op.cc):
    # distribution params are TENSORS; each element draws `shape` samples
    # -> output shape = param.shape + shape.
    # -----------------------------------------------------------------
    ms_schema = ParamSchema(Param("shape", "shape", default=()),
                            Param("dtype", str, default="float32"))

    def reg_ms(name, draw, num_inputs):
        def _ms_shape(attrs, in_shapes, aux_shapes):
            s = tuple(attrs.get("shape", ()) or ())
            base = tuple(in_shapes[0]) if in_shapes[0] is not None else ()
            return [tuple(base)] * num_inputs, [base + s], []

        def fcompute(attrs, inputs, aux, octx):
            s = tuple(attrs.get("shape", ()) or ())
            dt = attrs.get("dtype", "float32") or "float32"
            dt = jnp.bfloat16 if dt == "bfloat16" else np.dtype(dt)
            base = tuple(inputs[0].shape)
            out_shape = base + s
            ps = [p.reshape(base + (1,) * len(s)).astype(jnp.float32)
                  for p in inputs]
            return [draw(octx.rng, out_shape, *ps).astype(dt)], []

        register_op(OpDef(name, fcompute, schema=ms_schema,
                          num_inputs=num_inputs, needs_rng=True,
                          infer_shape=_ms_shape, hint=name.lstrip("_")))

    reg_ms("_sample_uniform",
           lambda key, sh, lo, hi:
           lo + jax.random.uniform(key, sh) * (hi - lo), 2)
    reg_ms("_sample_normal",
           lambda key, sh, mu, sigma:
           mu + jax.random.normal(key, sh) * sigma, 2)
    reg_ms("_sample_gamma",
           lambda key, sh, alpha, beta:
           jax.random.gamma(key, jnp.broadcast_to(alpha, sh)) * beta, 2)
    reg_ms("_sample_exponential",
           lambda key, sh, lam:
           jax.random.exponential(key, sh) / lam, 1)
    reg_ms("_sample_poisson",
           lambda key, sh, lam:
           jax.random.poisson(key, jnp.broadcast_to(lam, sh)), 1)

    def _ms_neg_binomial(key, sh, k, p):
        k1, k2 = jax.random.split(key)
        lam = jax.random.gamma(k1, jnp.broadcast_to(k, sh)) * (1 - p) / p
        return jax.random.poisson(k2, lam)

    reg_ms("_sample_negative_binomial", _ms_neg_binomial, 2)

    def _ms_gen_neg_binomial(key, sh, mu, alpha):
        k1, k2 = jax.random.split(key)
        r = 1.0 / alpha
        lam = jax.random.gamma(k1, jnp.broadcast_to(r, sh)) * (mu * alpha)
        return jax.random.poisson(k2, lam)

    reg_ms("_sample_generalized_negative_binomial", _ms_gen_neg_binomial, 2)
