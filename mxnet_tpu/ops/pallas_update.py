"""Fused multi-tensor Pallas optimizer update — the train step's HBM diet.

The compiled train step's optimizer phase today is a per-parameter chain
of XLA ops: cast the bf16 gradient up (``grads[i].astype``), scale by
``rescale_grad``, clip, run the SGD-momentum/Adam moment update, and
recast the weight for the next forward — every link reading and writing
every param, grad and slot tensor.  At engine-op granularity (the
reference's per-op kernel semantics, and the worst case XLA is allowed
to emit for a chain of separately-rooted elementwise fusions) that is
five HBM round trips per parameter per step on tensors that together
rival the activation traffic of the whole backward pass.

This module is the Apex-style *multi-tensor apply* answer (FusedAdam /
``multi_tensor_applier``): the donated param/grad/slot trees flatten
into dtype-homogeneous flat **slabs** — each parameter padded to a
whole number of (16, 128) blocks, concatenated, viewed as (rows, 128) —
and ONE Pallas pass per slab performs the entire chain:

    g32 = promote(g)                      # bf16 grad -> f32, in VMEM
    g32 = rescale/clip(g32)
    w', slots' = opt(w32, g32, slots32)   # SGD-mom or Adam, f32 math
    store w' (master dtype), slots', and w'.astype(compute_dtype)

The slabs are the step's PERSISTENT donated state (train_step.py):
masters and slots enter as slabs and leave as the kernel's aliased
outputs, so nothing re-packs per step.  The compute-dtype recast
output means the next step's program-entry cast pass disappears too:
the forward reads views sliced from the persistent compute slab and
differentiates against them, and the gradient slab's pack (the one
per-step assembly) fuses into the backward's own output writes — the
f32 convert sits directly on each backward dot (see ``grad_dtype``).

Per-parameter hyperparameters (lr — Adam's bias correction already
folded host-side at the TRUE update count t, matching the elastic
sidecar's resume semantics — and wd) ride in as scalar-prefetch arrays
indexed by grid block; ``rescale``/``clip`` and the optimizer extras
(momentum / betas / epsilon) ride in one scalar-prefetch hyper vector,
so post-compile hyper mutation is honored exactly like the XLA path.

Numerics: f32 math in the exact op order of the per-parameter XLA
``fused_kernel`` apply chain — SGD-momentum is BIT-identical; Adam's
sqrt/div parity is tolerance-documented at <= 1e-6 f32
(docs/performance.md).  Slot and master storage dtypes are preserved
(``s_new.astype(s_old.dtype)`` semantics).

Scope and fallback: SGD (with or without momentum) and Adam; float32 /
bfloat16 params; single-device masters (a mesh-sharded master store
keeps the per-param XLA path — slabs would force replication).
Anything else, and the eager ``opt_owner``, falls back unchanged; the
train step stamps ``meta['pallas_update']`` only when the kernel
actually lowered, and the mxlint flop-dtype pass's ``pallas-fallback``
tripwire errors if a stamped program quietly lost its ``pallas_call``.

``priced_update_cost`` prices both paths' optimizer-phase HBM bytes
through the PR-9/11 roofline machinery (``analysis.cost.program_cost``
on one program per phase): the per-parameter path at engine-op
granularity (each chain link one materialized round trip), the fused
path as its single pass — ``bench.py`` publishes both and the
``opt_update`` mfu_table row carries whichever path is armed.
"""
from __future__ import annotations

import functools

import numpy as np

# one grid block: (16, 128) = 2048 elements — the bf16 minimum tile,
# a multiple of the f32 (8, 128) tile, and small enough that per-param
# padding waste is negligible beside the slab it buys
BLOCK_ROWS = 16
LANES = 128
BLOCK = BLOCK_ROWS * LANES

# which update path the last fused-step build took ("pallas" | "xla") —
# path-selection tripwire, same pattern as ops.attention.PATH_TAKEN /
# ops.pallas_decode's DECODE_PATH
UPDATE_PATH = {"last": None}

_SUPPORTED_DTYPES = ("float32", "bfloat16")


def enabled():
    """``(armed, interpret)``: the kernel engages on TPU natively, or
    anywhere under ``MXNET_PALLAS_INTERPRET`` (the tier-1 CPU harness) —
    the same gate rule as ``MXNET_PALLAS_DECODE``."""
    import jax

    from .. import config as _config

    if not _config.get("MXNET_PALLAS_UPDATE"):
        return False, False
    if jax.default_backend() == "tpu":
        return True, False
    if _config.get("MXNET_PALLAS_INTERPRET"):
        return True, True
    return False, False


def kind_of(optimizer):
    """``("sgd", nslots)`` / ``("adam", 2)`` for optimizers the kernel
    implements, else None.  Exact-type checks: NAG subclasses SGD with
    different math and must fall back."""
    from ..optimizer import SGD, Adam, ccSGD

    if type(optimizer) in (SGD, ccSGD):
        return ("sgd", 1 if optimizer.momentum != 0.0 else 0)
    if type(optimizer) is Adam:
        return ("adam", 2)
    return None


# ---------------------------------------------------------------------------
# slab plan
# ---------------------------------------------------------------------------

class _Segment:
    __slots__ = ("name", "shape", "size", "row0", "nblocks")

    def __init__(self, name, shape, size, row0, nblocks):
        self.name = name
        self.shape = shape
        self.size = size
        self.row0 = row0
        self.nblocks = nblocks


class UpdatePlan:
    """The static flattening plan: which parameter lives where in which
    slab.  Built once per step compile; all methods are traceable."""

    def __init__(self, kind, nslots, segments_by_bucket, compute_dtype,
                 interpret, block_rows=BLOCK_ROWS):
        self.kind = kind
        self.nslots = nslots
        self.buckets = segments_by_bucket  # {dtype_name: [_Segment...]}
        self.cdtype = compute_dtype        # jnp dtype or None
        self.interpret = interpret
        # grid-block height: the tuning cache's winner for this param
        # population (plan_for resolves it); segments_by_bucket must have
        # been laid out with the SAME value
        self.block_rows = int(block_rows)
        self.block = self.block_rows * LANES

    # -- layout ---------------------------------------------------------
    def names(self):
        """Every parameter name the plan covers (== the trainable set)."""
        return frozenset(s.name for segs in self.buckets.values()
                         for s in segs)

    def rows(self, bucket):
        segs = self.buckets[bucket]
        last = segs[-1]
        return last.row0 + last.nblocks * self.block_rows

    def grad_dtype(self, bucket):
        """The dtype gradients cross the kernel boundary in: always
        float32.  The per-parameter XLA chain never actually rounds the
        backward dot to the compute dtype — XLA's excess-precision
        folding elides the ``convert(convert(dot_f32 -> bf16) -> f32)``
        pair, so the update there sees the raw f32 dot result.  A
        custom-call boundary can't be folded through, so a bf16 grad
        slab would quantize grads once per step (~1 bf16 ulp drift per
        step vs the XLA path); packing the grad slab in f32 lets the
        same folding fire on our side and keeps SGD-momentum
        bit-identical.  Costs 2x the grad slab's kernel-boundary bytes
        under bf16 compute — still one pass, still far under the
        per-parameter chain."""
        import jax.numpy as jnp

        del bucket
        return jnp.dtype(jnp.float32)

    def has_wc(self, bucket):
        """Whether this bucket keeps a separate compute-dtype slab (the
        in-kernel recast output the next forward reads)."""
        import jax.numpy as jnp

        return self.cdtype is not None and jnp.dtype(bucket) != self.cdtype

    # -- pack / unpack --------------------------------------------------
    def _pack_bucket(self, bk, tree, dt):
        """The names of ONE bucket -> its (rows, 128) slab (traceable)."""
        import jax.numpy as jnp

        parts = []
        for seg in self.buckets[bk]:
            # cast BEFORE reshape: the f32 convert then sits directly on
            # the producer (the backward dot, for grads), where XLA's
            # excess-precision folding elides the bf16 materialization —
            # the same fold the per-parameter chain's ``astype(master)``
            # gets, and the reason bf16-compute parity is bit-exact
            v = tree[seg.name].astype(dt).reshape(-1)
            pad = seg.nblocks * self.block - seg.size
            if pad:
                v = jnp.concatenate([v, jnp.zeros((pad,), dt)])
            parts.append(v)
        return jnp.concatenate(parts).reshape(-1, LANES)

    def pack(self, tree, dtype_of_bucket=None):
        """{name: array} -> {bucket: (rows, 128) slab} (traceable)."""
        import jax.numpy as jnp

        return {bk: self._pack_bucket(
            bk, tree, jnp.dtype(bk) if dtype_of_bucket is None
            else dtype_of_bucket(bk)) for bk in self.buckets}

    def pack_slots(self, slots):
        """{name: tuple} -> {bucket: tuple of slabs} (slot storage keeps
        the master dtype, ``jnp.zeros_like`` semantics)."""
        import jax.numpy as jnp

        return {bk: tuple(
            self._pack_bucket(bk, {s.name: slots[s.name][i]
                                   for s in self.buckets[bk]},
                              jnp.dtype(bk))
            for i in range(self.nslots)) for bk in self.buckets}

    def cast_slabs(self, w_slabs):
        """The compute-dtype slabs the forward reads (only for buckets
        whose master dtype differs from the compute dtype)."""
        return {bk: w_slabs[bk].astype(self.cdtype)
                for bk in self.buckets if self.has_wc(bk)}

    def unpack(self, bucket, slab):
        """One slab -> {name: array} views (traceable slices)."""
        flat = slab.reshape(-1)
        out = {}
        for seg in self.buckets[bucket]:
            start = seg.row0 * LANES
            out[seg.name] = flat[start:start + seg.size].reshape(seg.shape)
        return out

    def unpack_all(self, slabs):
        out = {}
        for bk in self.buckets:
            out.update(self.unpack(bk, slabs[bk]))
        return out

    def unpack_slots(self, slot_slabs):
        """{bucket: tuple of slabs} -> {name: tuple of arrays}."""
        out = {}
        for bk in self.buckets:
            per_slot = [self.unpack(bk, s) for s in slot_slabs[bk]]
            for seg in self.buckets[bk]:
                out[seg.name] = tuple(p[seg.name] for p in per_slot)
        return out

    # -- per-block hyperparameters --------------------------------------
    def lr_wd_blocks(self, lrs, wds):
        """Per-name lr/wd -> per-bucket per-block numpy arrays (host
        side; cached across steps by the step's hyper cache)."""
        lrb, wdb = {}, {}
        for bk, segs in self.buckets.items():
            lr = np.empty(self.rows(bk) // self.block_rows, np.float32)
            wd = np.empty_like(lr)
            for seg in segs:
                b0 = seg.row0 // self.block_rows
                lr[b0:b0 + seg.nblocks] = lrs[seg.name]
                wd[b0:b0 + seg.nblocks] = wds[seg.name]
            lrb[bk], wdb[bk] = lr, wd
        return lrb, wdb

    # -- the kernel -----------------------------------------------------
    def apply(self, w_slabs, g_slabs, slot_slabs, wc_slabs, lrb, wdb, hyp):
        """One fused Pallas pass per bucket; returns
        ``(new_w, new_slots, new_wc)`` slab dicts.

        ``wc_slabs`` may omit a has_wc bucket (the pricing path): the
        recast output is then allocated fresh instead of aliasing the
        old compute slab's buffer — the old slab is a never-READ operand
        either way, so the priced traffic is the same as the real
        kernel's; the alias only saves an allocation on the hot path."""
        new_w, new_slots, new_wc = {}, {}, {}
        for bk in self.buckets:
            has_wc = self.has_wc(bk)
            outs = _bucket_call(
                self.kind, self.nslots, has_wc,
                w_slabs[bk], g_slabs[bk], slot_slabs[bk],
                wc_slabs.get(bk) if has_wc else None, self.cdtype,
                lrb[bk], wdb[bk], hyp, self.interpret,
                block_rows=self.block_rows)
            new_w[bk] = outs[0]
            new_slots[bk] = tuple(outs[1:1 + self.nslots])
            if has_wc:
                new_wc[bk] = outs[-1]
        return new_w, new_slots, new_wc


def plan_for(optimizer, params, grad_names, compute_dtype, mesh=None,
             interpret=False):
    """Build an :class:`UpdatePlan`, or None when this configuration must
    stay on the per-parameter XLA path: unsupported optimizer, a
    non-f32/bf16 trainable param, or a mesh-sharded master store."""
    import jax.numpy as jnp

    if mesh is not None:
        return None
    kind = kind_of(optimizer)
    if kind is None or not grad_names:
        return None
    for name in grad_names:
        if jnp.dtype(params[name].dtype).name not in _SUPPORTED_DTYPES:
            return None
    # one layout rule: the pricing path (_segments_for) and the live
    # plan share it, so the priced slabs are the kernel's slabs
    total = sum(int(np.prod(params[n].shape)) or 1 for n in grad_names)
    br = _tuned_block_rows(total)
    segs = _segments_for({n: params[n] for n in grad_names},
                         block_rows=br)
    cdtype = None
    if compute_dtype is not None and \
            jnp.dtype(compute_dtype) != jnp.float32:
        cdtype = jnp.dtype(compute_dtype)
    return UpdatePlan(kind[0], kind[1], segs, cdtype, interpret,
                      block_rows=br)


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------

def _update_math(kind, nslots, w, g, slots, lr, wd, hyp):
    """The f32 update chain, in the exact op order of the per-parameter
    XLA ``fused_kernel`` applies (optimizer.py) — shared by the Pallas
    kernel body and the pricing reference."""
    import jax.numpy as jnp

    rescale, clip = hyp[0], hyp[1]
    g = g * rescale
    g = jnp.where(clip > 0, jnp.clip(g, -clip, clip), g)
    if kind == "sgd":
        if nslots:
            momentum = hyp[2]
            (m,) = slots
            m = momentum * m - lr * (g + wd * w)
            return w + m, (m,)
        return w - lr * (g + wd * w), ()
    beta1, beta2, eps = hyp[2], hyp[3], hyp[4]
    mean, var = slots
    g = g + wd * w
    mean = beta1 * mean + (1 - beta1) * g
    var = beta2 * var + (1 - beta2) * jnp.square(g)
    return w - lr * mean / (jnp.sqrt(var) + eps), (mean, var)


def _kernel(lrb_ref, wdb_ref, hyp_ref, w_ref, g_ref, *refs, kind, nslots,
            has_wc, wc_dummy):
    """One grid block: the whole cast+rescale+clip+update+recast chain
    over 2048 elements of one parameter's segment, f32 math in VMEM."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    lr = lrb_ref[i]
    wd = wdb_ref[i]
    slot_in = refs[:nslots]
    out_at = nslots + (1 if wc_dummy else 0)  # skip the wc alias dummy
    w_out = refs[out_at]
    slot_out = refs[out_at + 1:out_at + 1 + nslots]

    w = w_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    slots = tuple(s[...].astype(jnp.float32) for s in slot_in)
    hyp = tuple(hyp_ref[j] for j in range(5 if kind == "adam" else 3))
    new_w, new_slots = _update_math(kind, nslots, w, g, slots, lr, wd, hyp)
    w_out[...] = new_w.astype(w_out.dtype)
    for ref, s in zip(slot_out, new_slots):
        ref[...] = s.astype(ref.dtype)
    if has_wc:
        refs[-1][...] = new_w.astype(refs[-1].dtype)


def _bucket_call(kind, nslots, has_wc, w, g, slots, wc, cdtype, lrb, wdb,
                 hyp, interpret, block_rows=BLOCK_ROWS):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = w.shape[0]
    nb = rows // block_rows
    blk = lambda *_: (_[0], 0)          # block i of every slab operand
    bspec = lambda: pl.BlockSpec((block_rows, LANES), blk)

    in_specs = [bspec(), bspec()] + [bspec()] * nslots
    args = [w, g] + list(slots)
    out_specs = [bspec()] + [bspec()] * nslots
    out_shape = [jax.ShapeDtypeStruct(w.shape, w.dtype)] + [
        jax.ShapeDtypeStruct(s.shape, s.dtype) for s in slots]
    # input index of a slab operand = 3 scalar-prefetch args + position;
    # the slabs update in place (multi-tensor apply over donated buffers)
    aliases = {3: 0}
    for i in range(nslots):
        aliases[3 + 2 + i] = 1 + i
    if has_wc:
        out_specs.append(bspec())
        out_shape.append(jax.ShapeDtypeStruct((rows, LANES), cdtype))
        if wc is not None:
            # the old compute slab rides along as a never-read operand so
            # its buffer can host the recast output in place; wc=None
            # (the pricing path) allocates the output fresh instead —
            # identical traffic, one extra allocation
            in_specs.append(bspec())
            args.append(wc)
            aliases[3 + 2 + nslots] = 1 + nslots

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    return pl.pallas_call(
        functools.partial(_kernel, kind=kind, nslots=nslots, has_wc=has_wc,
                          wc_dummy=wc is not None),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        # every (16, 128) block is an independent segment of the update
        # — no cross-block reduction — so the grid axis fans out across
        # megacores (the same marking pallas_decode gives its
        # independent axes; 'arbitrary' would serialize the whole slab)
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(jnp.asarray(lrb), jnp.asarray(wdb), jnp.asarray(hyp), *args)


# ---------------------------------------------------------------------------
# priced HBM bytes per update path (the roofline machinery)
# ---------------------------------------------------------------------------

def priced_update_cost(param_specs, kind, nslots, compute_dtype,
                       interpret=True):
    """Optimizer-phase HBM bytes per path, priced with
    :func:`~mxnet_tpu.analysis.cost.program_cost`.

    ``param_specs`` maps trainable param name -> an object with
    ``.shape``/``.dtype`` (arrays or ShapeDtypeStructs).  The
    **per-parameter path** is priced at engine-op granularity — one
    program per chain link (grad cast, rescale, clip, the optimizer
    update, the compute-dtype recast), each link's operands and results
    a full HBM round trip, which is both the reference engine's per-op
    dispatch semantics and the materialization worst case for a chain
    of separately-rooted elementwise fusions.  The **fused path** is
    one program: the per-bucket Pallas pass over the slabs.  Returns
    ``{"per_param_bytes", "fused_bytes", "ratio", "phases"}``.
    """
    import jax
    import jax.numpy as jnp

    from ..analysis.cost import program_cost

    sds = {n: jax.ShapeDtypeStruct(tuple(v.shape), jnp.dtype(v.dtype))
           for n, v in param_specs.items()}
    cdtype = None
    if compute_dtype is not None and \
            jnp.dtype(compute_dtype) != jnp.float32:
        cdtype = jnp.dtype(compute_dtype)

    def tree(dtype_of=None):
        return {n: jax.ShapeDtypeStruct(
            v.shape, v.dtype if dtype_of is None else dtype_of(v))
            for n, v in sds.items()}

    def jmap(f):
        import jax.tree_util as jtu

        return jax.jit(lambda t, *s: jtu.tree_map(f, t, *s))

    phases = {}
    grads_in = tree(lambda v: cdtype or v.dtype)
    # 1. grad cast up to the master dtype (skipped where it is a no-op)
    cast_set = {n: v for n, v in grads_in.items()
                if v.dtype != sds[n].dtype}
    if cast_set:
        fn = jax.jit(lambda t: {n: t[n].astype(sds[n].dtype)
                                for n in t})
        phases["cast"] = program_cost(fn, (cast_set,))["bytes"]
    # 2. rescale  3. clip — runtime scalars, always-traced ops
    gtree = tree()
    scal = jax.ShapeDtypeStruct((), jnp.float32)
    phases["rescale"] = program_cost(
        jmap(lambda g: g * 1.5), (gtree,))["bytes"]
    fn = jax.jit(lambda t, c: {n: jnp.where(c > 0, jnp.clip(v, -c, c), v)
                               for n, v in t.items()})
    phases["clip"] = program_cost(fn, (gtree, scal))["bytes"]
    # 4. the optimizer update proper (per-param XLA apply chain)
    slots_t = tuple(tree() for _ in range(nslots))
    hyp = jax.ShapeDtypeStruct((5,), jnp.float32)

    def upd(w, g, slots, hyp):
        out_w, out_s = {}, [dict() for _ in range(nslots)]
        for n in w:
            nw, ns = _update_math(kind, nslots, w[n], g[n],
                                  tuple(s[n] for s in slots),
                                  jnp.float32(0.1), jnp.float32(1e-4),
                                  tuple(hyp[i] for i in range(5)))
            out_w[n] = nw.astype(w[n].dtype)
            for i, s in enumerate(ns):
                out_s[i][n] = s.astype(slots[i][n].dtype)
        return out_w, out_s

    phases["update"] = program_cost(
        jax.jit(upd), (tree(), tree(), slots_t, hyp))["bytes"]
    # 5. the next forward's program-entry compute cast
    recast_set = {n: v for n, v in sds.items()
                  if cdtype is not None and v.dtype != cdtype}
    if recast_set:
        fn = jax.jit(lambda t: {n: v.astype(cdtype)
                                for n, v in t.items()})
        phases["recast"] = program_cost(fn, (recast_set,))["bytes"]
    per_param = sum(phases.values())

    # fused: ONE pass (per bucket) over the slabs
    plan = UpdatePlan(kind, nslots, _segments_for(sds), cdtype, interpret)

    def slab_sds(dtype):
        return {bk: jax.ShapeDtypeStruct((plan.rows(bk), LANES),
                                         jnp.dtype(dtype or bk))
                for bk in plan.buckets}

    w_s = slab_sds(None)
    g_s = {bk: jax.ShapeDtypeStruct((plan.rows(bk), LANES),
                                    plan.grad_dtype(bk))
           for bk in plan.buckets}
    slots_s = {bk: tuple(
        jax.ShapeDtypeStruct((plan.rows(bk), LANES), jnp.dtype(bk))
        for _ in range(nslots)) for bk in plan.buckets}
    lrb_s = {bk: jax.ShapeDtypeStruct((plan.rows(bk) // plan.block_rows,),
                                      jnp.float32) for bk in plan.buckets}
    hyp_s = jax.ShapeDtypeStruct((5,), jnp.float32)
    # no wc input operand: the real kernel's old compute slab is an
    # aliased NEVER-READ dummy (its bytes are not traffic), so the
    # honest price allocates the recast output fresh (plan.apply with
    # wc_slabs={})
    fn = jax.jit(lambda w, g, s, lrb, wdb, hyp:
                 plan.apply(w, g, s, {}, lrb, wdb, hyp))
    fused = program_cost(
        fn, (w_s, g_s, slots_s, lrb_s, lrb_s, hyp_s))["bytes"]
    return {"per_param_bytes": int(per_param), "fused_bytes": int(fused),
            "ratio": round(fused / per_param, 4) if per_param else None,
            "phases": {k: int(v) for k, v in phases.items()}}


def _segments_for(sds, block_rows=BLOCK_ROWS):
    segs = {}
    import jax.numpy as jnp

    block = block_rows * LANES
    buckets = {}
    for name, v in sds.items():
        buckets.setdefault(jnp.dtype(v.dtype).name, []).append(
            (name, tuple(v.shape)))
    for bk, entries in buckets.items():
        row = 0
        out = []
        for name, shape in entries:
            size = int(np.prod(shape)) if shape else 1
            nblocks = max(1, -(-size // block))
            out.append(_Segment(name, shape, size, row, nblocks))
            row += nblocks * block_rows
        segs[bk] = out
    return segs


def priced_update_cost_for_step(step):
    """Convenience wrapper: price both update paths at a live
    :class:`~mxnet_tpu.train_step.CompiledTrainStep`'s shapes (None when
    the step's optimizer is outside the kernel's scope)."""
    kind = kind_of(step._optimizer)
    if kind is None or not step._grad_names:
        return None
    params = step.params   # one slab unpack, not one per name
    specs = {n: params[n] for n in step._grad_names}
    return priced_update_cost(specs, kind[0], kind[1],
                              step._cdtype, interpret=True)


# ---------------------------------------------------------------------------
# tunable space (ops/tuning.py): grid-block height per param-count class
# ---------------------------------------------------------------------------

def _tuned_block_rows(total):
    """The tuning cache's grid-block height for a trainable population of
    ``total`` elements (:data:`BLOCK_ROWS` when cold and no sweep armed),
    clamped to the bf16 minimum sublane tile."""
    from . import tuning

    br = int(tuning.resolve("pallas_update",
                            tuning.shape_class_for(n=max(int(total), 1)),
                            "any").get("block_rows", BLOCK_ROWS))
    return max(16, (br // 16) * 16)


def _tuning_candidates(shape_class, interpret):
    if interpret:
        # 2-candidate toy space for the tier-1 CPU sweep
        return [{"block_rows": 16}, {"block_rows": 32}]
    return [{"block_rows": br} for br in (16, 32, 64, 128)]


def _tuning_runner(params, shape_class, dtype, interpret):
    import jax
    import jax.numpy as jnp

    from . import tuning

    n = tuning.parse_shape_class(shape_class).get("n", 1 << 16)
    br = params["block_rows"]
    if br <= 0 or br % 16:
        raise tuning.SpaceError("block_rows %r not a multiple of the "
                                "bf16 sublane tile" % (br,))
    block = br * LANES
    nb = max(1, -(-n // block))
    rows = nb * br
    w = jnp.zeros((rows, LANES), jnp.float32)
    g = jnp.ones((rows, LANES), jnp.float32)
    m = jnp.zeros((rows, LANES), jnp.float32)
    lrb = np.full((nb,), 0.1, np.float32)
    wdb = np.zeros((nb,), np.float32)
    hyp = np.array([1.0, 0.0, 0.9], np.float32)

    @jax.jit
    def probe(w, g, m):
        return _bucket_call("sgd", 1, False, w, g, (m,), None, None,
                            lrb, wdb, hyp, interpret, block_rows=br)

    def run():
        jax.block_until_ready(probe(w, g, m))

    return run


def _register_space():
    from . import tuning

    tuning.register_space(
        "pallas_update", version=1,
        defaults={"block_rows": BLOCK_ROWS},
        constants=("BLOCK_ROWS", "BLOCK"),
        candidates=_tuning_candidates, runner=_tuning_runner)


_register_space()
