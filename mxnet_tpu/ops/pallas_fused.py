"""Fused BN-apply + ReLU + 1x1-conv Pallas kernels (TPU) — forward AND backward.

The BN-ResNet traffic lever identified by ``benchmarks/ROOFLINE.md``: on a
bandwidth-bound model, every elementwise pass over an activation tensor is
~1 full HBM round trip.  XLA's graph runs, per BN -> ReLU -> 1x1-conv link:

    stats(x): R(x) | apply: R(x), W(a) | conv: R(a), W(y) | next stats: R(y)

The fused kernel here collapses the whole link into ONE pass:

    y = relu(x * scale + shift) @ W (+ residual)      [matmul prologue]
    ysum, ysumsq = per-channel sums of y              [matmul epilogue]

reading x once and writing y once — scale/shift application and ReLU ride
the MXU matmul's operand load, the *output's* BN statistics ride its result
store, and the residual add rides the epilogue.  The next link receives
(ysum, ysumsq) as tensors, so its BatchNorm is per-channel scalar math.

Backward is one combined kernel per link (plus a small XLA prologue that
folds the stats outputs' cotangents into an effective dy): it reads x and
dy once and emits dx, dW, dscale, dshift together, recomputing the ReLU
mask from x instead of storing the activation — the activation tensor `a`
never exists in HBM in either pass.

This is the TPU-shaped analog of the reference's fused-kernel perf work
(its conv/BN go through cuDNN fused paths and hand-written epilogues —
``docs/how_to/perf.md:107-190``); a 1x1 conv over NHWC is exactly a matmul,
so the kernel is a tiled MXU matmul with a custom prologue/epilogue.

**Measured outcome (round 4, benchmarks/ROOFLINE.md)**: on the bench chip
the traffic saved does NOT beat XLA — its conv emitters are ~1.7× faster
than this kernel's matmul at ResNet's shapes, so the full fused trunk runs
0.63× the XLA step.  The op is kept as a correct, tested, opt-in fused
kernel (`benchmarks/rn50_raw.py FUSED=1` reproduces the measurement) and as
the worked example of the Pallas custom-kernel extension point; the
framework's default ResNet path stays on XLA convs with one-pass BN stats.

Numerics: matmul accumulates f32; y is cast to the compute dtype and the
statistics are computed from the *cast* values, so (ysum, ysumsq) equal
what a separate pass over the stored y would produce.

``interpret=True`` runs the same kernels on CPU for tests.
"""
from __future__ import annotations

import functools

import numpy as np

# swept on the bench chip (TPU v5 lite); see benchmarks/proto_fused.py
BLOCK_M = 512
BLOCK_N = 256
BLOCK_M_BWD = 256


def supported(m, k, n, dtype):
    """Shapes the kernel handles without padding: all dims tile-aligned."""
    import jax.numpy as jnp

    if dtype not in (jnp.bfloat16, np.dtype("bfloat16"), jnp.float32,
                     np.dtype("float32")):
        return False
    # whole-K/whole-N VMEM budget (weights + one x/dx/dy block each way,
    # double-buffered) — stay well under the ~16MB/core budget
    itemsize = 2 if dtype in (jnp.bfloat16, np.dtype("bfloat16")) else 4
    if k * n * itemsize > 4 * 1024 * 1024:
        return False
    if m % 256 or k % 8 or n % 64:
        return False
    return True


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(x_ref, scale_ref, shift_ref, w_ref, *rest, relu, has_res):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if has_res:
        r_ref, y_ref, s1_ref, s2_ref = rest
    else:
        (y_ref, s1_ref, s2_ref) = rest
        r_ref = None

    i = pl.program_id(0)

    a = x_ref[...].astype(jnp.float32) * scale_ref[...] + shift_ref[...]
    if relu:
        a = jnp.maximum(a, 0.0)
    acc = jax.lax.dot_general(
        a.astype(x_ref.dtype), w_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if r_ref is not None:
        acc = acc + r_ref[...].astype(jnp.float32)
    y = acc.astype(y_ref.dtype)
    y_ref[...] = y

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    y32 = y.astype(jnp.float32)  # stats of the *stored* values
    s1_ref[...] += jnp.sum(y32, axis=0, keepdims=True)
    s2_ref[...] += jnp.sum(jnp.square(y32), axis=0, keepdims=True)


def _fwd_call(x, scale, shift, w, residual, relu, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    m, k = x.shape
    n = w.shape[1]
    # 1-D grid over row blocks, whole K and N per step: x is read exactly
    # once, the weight stays VMEM-resident (supported() bounds k*n), y is
    # written exactly once, and the stats accumulators live in VMEM across
    # the whole grid — minimum possible HBM traffic for this op.  Row block
    # as large as a ~2.5MB/operand VMEM budget allows (fewer grid steps =
    # less per-step overhead; double-buffered x and y dominate usage)
    bm = max(256, min(8192, (2560 * 1024 // (2 * max(k, n))) // 256 * 256))
    while m % bm:
        bm //= 2
    grid = (m // bm,)

    in_specs = [
        pl.BlockSpec((bm, k), lambda i: (i, 0)),
        pl.BlockSpec((1, k), lambda i: (0, 0)),
        pl.BlockSpec((1, k), lambda i: (0, 0)),
        pl.BlockSpec((k, n), lambda i: (0, 0)),
    ]
    args = [x, scale.reshape(1, k), shift.reshape(1, k), w]
    if residual is not None:
        in_specs.append(pl.BlockSpec((bm, n), lambda i: (i, 0)))
        args.append(residual)

    y, s1, s2 = pl.pallas_call(
        functools.partial(_fwd_kernel, relu=relu,
                          has_res=residual is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return y, s1[0], s2[0]


# ---------------------------------------------------------------------------
# backward: one combined kernel -> dx, dW, dscale, dshift
# ---------------------------------------------------------------------------
def _bwd_kernel(x_ref, dy_ref, scale_ref, shift_ref, w_ref,
                dx_ref, dw_ref, dscale_ref, dshift_ref, *, relu):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        dscale_ref[...] = jnp.zeros_like(dscale_ref)
        dshift_ref[...] = jnp.zeros_like(dshift_ref)

    x = x_ref[...].astype(jnp.float32)
    u = x * scale_ref[...] + shift_ref[...]
    a = jnp.maximum(u, 0.0) if relu else u
    dy = dy_ref[...]

    # dW += a^T @ dy   (contraction over the row block)
    dw_ref[...] += jax.lax.dot_general(
        a.astype(dy.dtype), dy,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    # du = (dy @ W^T) * relu'(u)
    dz = jax.lax.dot_general(
        dy, w_ref[...],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    du = jnp.where(u > 0.0, dz, 0.0) if relu else dz

    dx_ref[...] = (du * scale_ref[...]).astype(dx_ref.dtype)
    dscale_ref[...] += jnp.sum(du * x, axis=0, keepdims=True)
    dshift_ref[...] += jnp.sum(du, axis=0, keepdims=True)


def _bwd_call(x, dy, scale, shift, w, relu, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    m, k = x.shape
    n = w.shape[1]
    bm = min(BLOCK_M_BWD, m)
    while m % bm:  # same shrink rule as _fwd_call: never drop trailing rows
        bm //= 2

    dx, dw, ds, db = pl.pallas_call(
        functools.partial(_bwd_kernel, relu=relu),
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, k), x.dtype),
            jax.ShapeDtypeStruct((k, n), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
        ],
        interpret=interpret,
    )(x, dy, scale.reshape(1, k), shift.reshape(1, k), w)
    return dx, dw, ds[0], db[0]


# ---------------------------------------------------------------------------
# public op: custom_vjp (built lazily, cached per (relu, has_res, interpret))
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def _build(relu, has_res, interpret):
    import jax
    import jax.numpy as jnp

    @jax.custom_vjp
    def fused(x, scale, shift, w, *res_arg):
        return _fwd_call(x, scale, shift, w,
                         res_arg[0] if has_res else None, relu, interpret)

    def fwd(x, scale, shift, w, *res_arg):
        out = _fwd_call(x, scale, shift, w,
                        res_arg[0] if has_res else None, relu, interpret)
        return out, (x, scale, shift, w, out[0])

    def bwd(saved, cts):
        x, scale, shift, w, y = saved
        dy, dysum, dysumsq = cts
        # fold the stats outputs' cotangents into an effective dy:
        #   d/dy [ sum(y).dysum + sum(y^2).dysumsq ] = dysum + 2 y dysumsq
        dy_eff = (dy.astype(jnp.float32) + dysum[None, :]
                  + 2.0 * y.astype(jnp.float32) * dysumsq[None, :])
        dy_eff = dy_eff.astype(x.dtype)
        dx, dw, dscale, dshift = _bwd_call(x, dy_eff, scale, shift, w, relu,
                                           interpret)
        grads = (dx, dscale, dshift, dw.astype(w.dtype))
        if has_res:
            grads = grads + (dy_eff,)
        return grads

    fused.defvjp(fwd, bwd)
    return fused


def fused_scale_relu_matmul(x, scale, shift, w, residual=None, relu=True,
                            interpret=False):
    """y = relu(x*scale + shift) @ w (+ residual); returns (y, ysum, ysumsq).

    x: (M, K); scale, shift: (K,) f32; w: (K, N); residual: (M, N) or None.
    ysum/ysumsq are per-output-channel sums over M of the stored y — the
    next BatchNorm's sufficient statistics, produced in the epilogue so no
    later pass re-reads y.  Differentiable (custom_vjp); the stats outputs'
    cotangents are folded into the backward, so BN's backward-through-
    statistics terms arrive through ordinary autodiff composition.
    """
    fn = _build(bool(relu), residual is not None, bool(interpret))
    args = (x, scale, shift, w) + ((residual,) if residual is not None else ())
    return fn(*args)


def reference_impl(x, scale, shift, w, residual=None, relu=True):
    """Plain-XLA composition with identical semantics, for tests/fallback."""
    import jax
    import jax.numpy as jnp

    a = x.astype(jnp.float32) * scale + shift
    if relu:
        a = jnp.maximum(a, 0.0)
    y = jax.lax.dot_general(
        a.astype(x.dtype), w, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    y = y.astype(x.dtype)
    y32 = y.astype(jnp.float32)
    return y, jnp.sum(y32, axis=0), jnp.sum(jnp.square(y32), axis=0)
