"""Fused affine + ReLU + matmul Pallas kernels (TPU) — forward AND backward.

The BN-ResNet traffic lever identified by ``benchmarks/ROOFLINE.md``: on a
bandwidth-bound model, every elementwise pass over an activation tensor is
~1 full HBM round trip.  XLA's graph runs, per BN -> ReLU -> 1x1-conv link:

    stats(x): R(x) | apply: R(x), W(a) | conv: R(a), W(y) | next stats: R(y)

The fused kernel here collapses the whole link into ONE pass:

    y = relu(x * scale + shift) @ W (+ bias) (+ residual)   [matmul prologue]
    ysum, ysumsq = per-channel sums of y                    [matmul epilogue]

reading x once and writing y once — scale/shift application and ReLU ride
the MXU matmul's operand load, the *output's* BN statistics ride its result
store, and the bias/residual adds ride the epilogue.  The next link receives
(ysum, ysumsq) as tensors, so its BatchNorm is per-channel scalar math.

Backward is one combined kernel per link (plus a small XLA prologue that
folds the stats outputs' cotangents into an effective dy): it reads x and
dy once and emits dx, dW, dscale, dshift together, recomputing the ReLU
mask from x instead of storing the activation — the activation tensor `a`
never exists in HBM in either pass.

This is the TPU-shaped analog of the reference's fused-kernel perf work
(its conv/BN go through cuDNN fused paths and hand-written epilogues —
``docs/how_to/perf.md:107-190``); a 1x1 conv over NHWC is exactly a matmul,
so the kernel is a tiled MXU matmul with a custom prologue/epilogue.

**ResNet outcome (round 4, benchmarks/ROOFLINE.md)**: on the bench chip
the traffic saved does NOT beat XLA at ResNet's conv shapes — its conv
emitters are ~1.7x faster than this kernel's matmul there, so the full
fused trunk runs 0.63x the XLA step (`benchmarks/rn50_raw.py FUSED=1`
reproduces it).  **The LM training path is the shape where it pays**:
``models/attention_lm.py``'s pre-norm blocks dispatch their LN->QKV and
LN->MLP segments here under ``MXNET_PALLAS_FUSED`` (ops/fused_lm.py) —
``bias`` rides the epilogue, ``wt=True`` takes FullyConnected's
(num_hidden, K) weight layout without materializing a transpose, and the
residual add rides along; :func:`priced_fused_cost` prices the HBM diet
against the engine-op einsum chain for the mfu_table.

Block shapes resolve through the persistent tuning cache
(:mod:`~mxnet_tpu.ops.tuning`): the module constants below are the
interpret/CPU defaults; an ``MXNET_PALLAS_TUNE`` sweep on the live
device persists per-(generation, shape-class, dtype) winners that later
processes read with zero probes.

Numerics: matmul accumulates f32; y is cast to the compute dtype and the
statistics are computed from the *cast* values, so (ysum, ysumsq) equal
what a separate pass over the stored y would produce.

``interpret=True`` runs the same kernels on CPU for tests.
"""
from __future__ import annotations

import functools

import numpy as np

# interpret/CPU-mode defaults (swept on the TPU v5 lite bench chip; see
# benchmarks/proto_fused.py).  On the live device the tuning cache
# (ops/tuning.py) overrides them per (generation, shape-class, dtype);
# block_m = 0 means "derive from the VMEM budget" (_auto_block_m).
BLOCK_M = 512
BLOCK_N = 256
BLOCK_M_BWD = 256
MIN_BLOCK_M = 8


def supported(m, k, n, dtype):
    """Shapes the kernel handles without padding: all dims tile-aligned."""
    import jax.numpy as jnp

    if dtype not in (jnp.bfloat16, np.dtype("bfloat16"), jnp.float32,
                     np.dtype("float32")):
        return False
    # whole-K/whole-N VMEM budget (weights + one x/dx/dy block each way,
    # double-buffered) — stay well under the ~16MB/core budget
    itemsize = 2 if dtype in (jnp.bfloat16, np.dtype("bfloat16")) else 4
    if k * n * itemsize > 4 * 1024 * 1024:
        return False
    if m % 256 or k % 8 or n % 64:
        return False
    return True


def _auto_block_m(k, n):
    """Row block as large as a ~2.5MB/operand VMEM budget allows (fewer
    grid steps = less per-step overhead; double-buffered x and y
    dominate usage)."""
    return max(256, min(8192, (2560 * 1024 // (2 * max(k, n))) // 256 * 256))


def _tuned(m, k, n, dtype):
    """The tuning-cache resolution for this shape class — {"block_m",
    "block_m_bwd"}, defaults when the cache is cold and no sweep armed."""
    import jax.numpy as jnp

    from . import tuning

    return tuning.resolve(
        "pallas_fused", tuning.shape_class_for(m=m, k=k, n=n),
        jnp.dtype(dtype).name)


def _fit_block(bm, m):
    """Clamp a block preference onto divisor-of-m; the grid drops whole
    rows otherwise."""
    bm = max(MIN_BLOCK_M, min(int(bm), m))
    while m % bm and bm > MIN_BLOCK_M:
        bm //= 2
    return bm


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(x_ref, scale_ref, shift_ref, w_ref, *rest, relu, has_res,
                has_bias, wt):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    rest = list(rest)
    b_ref = rest.pop(0) if has_bias else None
    r_ref = rest.pop(0) if has_res else None
    y_ref, s1_ref, s2_ref = rest

    i = pl.program_id(0)

    a = x_ref[...].astype(jnp.float32) * scale_ref[...] + shift_ref[...]
    if relu:
        a = jnp.maximum(a, 0.0)
    # wt: the weight arrives in FullyConnected's (N, K) layout and the
    # contraction runs over its trailing axis — no transpose materializes
    dims = (((1,), (1,)), ((), ())) if wt else (((1,), (0,)), ((), ()))
    acc = jax.lax.dot_general(
        a.astype(x_ref.dtype), w_ref[...],
        dimension_numbers=dims,
        preferred_element_type=jnp.float32)
    if b_ref is not None:
        acc = acc + b_ref[...]
    if r_ref is not None:
        acc = acc + r_ref[...].astype(jnp.float32)
    y = acc.astype(y_ref.dtype)
    y_ref[...] = y

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    y32 = y.astype(jnp.float32)  # stats of the *stored* values
    s1_ref[...] += jnp.sum(y32, axis=0, keepdims=True)
    s2_ref[...] += jnp.sum(jnp.square(y32), axis=0, keepdims=True)


def _fwd_call(x, scale, shift, w, residual, bias, relu, wt, interpret,
              block_m=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    m, k = x.shape
    n = w.shape[0] if wt else w.shape[1]
    # 1-D grid over row blocks, whole K and N per step: x is read exactly
    # once, the weight stays VMEM-resident (supported() bounds k*n), y is
    # written exactly once, and the stats accumulators live in VMEM across
    # the whole grid — minimum possible HBM traffic for this op.
    if block_m is None:
        block_m = _tuned(m, k, n, x.dtype).get("block_m", 0)
    bm = _fit_block(block_m or _auto_block_m(k, n), m)
    grid = (m // bm,)

    wshape = (n, k) if wt else (k, n)
    in_specs = [
        pl.BlockSpec((bm, k), lambda i: (i, 0)),
        pl.BlockSpec((1, k), lambda i: (0, 0)),
        pl.BlockSpec((1, k), lambda i: (0, 0)),
        pl.BlockSpec(wshape, lambda i: (0, 0)),
    ]
    args = [x, scale.reshape(1, k), shift.reshape(1, k), w]
    if bias is not None:
        in_specs.append(pl.BlockSpec((1, n), lambda i: (0, 0)))
        args.append(bias.astype(jnp.float32).reshape(1, n))
    if residual is not None:
        in_specs.append(pl.BlockSpec((bm, n), lambda i: (i, 0)))
        args.append(residual)

    y, s1, s2 = pl.pallas_call(
        functools.partial(_fwd_kernel, relu=relu,
                          has_res=residual is not None,
                          has_bias=bias is not None, wt=wt),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return y, s1[0], s2[0]


# ---------------------------------------------------------------------------
# backward: one combined kernel -> dx, dW, dscale, dshift
# ---------------------------------------------------------------------------
def _bwd_kernel(x_ref, dy_ref, scale_ref, shift_ref, w_ref,
                dx_ref, dw_ref, dscale_ref, dshift_ref, *, relu, wt):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        dscale_ref[...] = jnp.zeros_like(dscale_ref)
        dshift_ref[...] = jnp.zeros_like(dshift_ref)

    x = x_ref[...].astype(jnp.float32)
    u = x * scale_ref[...] + shift_ref[...]
    a = jnp.maximum(u, 0.0) if relu else u
    dy = dy_ref[...]

    # dW += a^T @ dy (K, N) — or dy^T @ a for the (N, K) wt layout —
    # (contraction over the row block either way)
    if wt:
        dw_ref[...] += jax.lax.dot_general(
            dy, a.astype(dy.dtype),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        dw_ref[...] += jax.lax.dot_general(
            a.astype(dy.dtype), dy,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # du = (dy @ W^T) * relu'(u)
    dims = (((1,), (0,)), ((), ())) if wt else (((1,), (1,)), ((), ()))
    dz = jax.lax.dot_general(
        dy, w_ref[...],
        dimension_numbers=dims,
        preferred_element_type=jnp.float32)
    du = jnp.where(u > 0.0, dz, 0.0) if relu else dz

    dx_ref[...] = (du * scale_ref[...]).astype(dx_ref.dtype)
    dscale_ref[...] += jnp.sum(du * x, axis=0, keepdims=True)
    dshift_ref[...] += jnp.sum(du, axis=0, keepdims=True)


def _bwd_call(x, dy, scale, shift, w, relu, wt, interpret, block_m=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    m, k = x.shape
    n = w.shape[0] if wt else w.shape[1]
    if block_m is None:
        block_m = _tuned(m, k, n, x.dtype).get("block_m_bwd", BLOCK_M_BWD)
    bm = _fit_block(block_m or BLOCK_M_BWD, m)

    wshape = (n, k) if wt else (k, n)
    dx, dw, ds, db = pl.pallas_call(
        functools.partial(_bwd_kernel, relu=relu, wt=wt),
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec(wshape, lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec(wshape, lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, k), x.dtype),
            jax.ShapeDtypeStruct(wshape, jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
        ],
        interpret=interpret,
    )(x, dy, scale.reshape(1, k), shift.reshape(1, k), w)
    return dx, dw, ds[0], db[0]


# ---------------------------------------------------------------------------
# public op: custom_vjp (built lazily, cached per variant)
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def _build(relu, has_res, has_bias, wt, interpret):
    import jax
    import jax.numpy as jnp

    def unpack(extra):
        extra = list(extra)
        bias = extra.pop(0) if has_bias else None
        res = extra.pop(0) if has_res else None
        return bias, res

    @jax.custom_vjp
    def fused(x, scale, shift, w, *extra):
        bias, res = unpack(extra)
        return _fwd_call(x, scale, shift, w, res, bias, relu, wt,
                         interpret)

    def fwd(x, scale, shift, w, *extra):
        bias, res = unpack(extra)
        out = _fwd_call(x, scale, shift, w, res, bias, relu, wt,
                        interpret)
        return out, (x, scale, shift, w, out[0])

    def bwd(saved, cts):
        x, scale, shift, w, y = saved
        dy, dysum, dysumsq = cts
        # fold the stats outputs' cotangents into an effective dy:
        #   d/dy [ sum(y).dysum + sum(y^2).dysumsq ] = dysum + 2 y dysumsq
        dy_eff32 = (dy.astype(jnp.float32) + dysum[None, :]
                    + 2.0 * y.astype(jnp.float32) * dysumsq[None, :])
        dy_eff = dy_eff32.astype(x.dtype)
        dx, dw, dscale, dshift = _bwd_call(x, dy_eff, scale, shift, w,
                                           relu, wt, interpret)
        grads = (dx, dscale, dshift, dw.astype(w.dtype))
        if has_bias:
            # column sums of the effective dy; XLA fuses this into the
            # dy_eff fold above (one elementwise producer, one reduce)
            grads = grads + (jnp.sum(dy_eff32, axis=0),)
        if has_res:
            grads = grads + (dy_eff,)
        return grads

    fused.defvjp(fwd, bwd)
    return fused


def fused_scale_relu_matmul(x, scale, shift, w, residual=None, relu=True,
                            bias=None, wt=False, interpret=False):
    """y = relu(x*scale + shift) @ w (+ bias) (+ residual); returns
    (y, ysum, ysumsq).

    x: (M, K); scale, shift: (K,) f32; w: (K, N) — or (N, K) under
    ``wt=True`` (FullyConnected's weight layout, contracted in place);
    bias: (N,) or None; residual: (M, N) or None.  ysum/ysumsq are
    per-output-channel sums over M of the stored y — the next
    BatchNorm's sufficient statistics, produced in the epilogue so no
    later pass re-reads y.  Differentiable (custom_vjp); the stats
    outputs' cotangents are folded into the backward, so BN's backward-
    through-statistics terms arrive through ordinary autodiff
    composition.
    """
    fn = _build(bool(relu), residual is not None, bias is not None,
                bool(wt), bool(interpret))
    extra = ()
    if bias is not None:
        extra = extra + (bias,)
    if residual is not None:
        extra = extra + (residual,)
    return fn(x, scale, shift, w, *extra)


def reference_impl(x, scale, shift, w, residual=None, relu=True, bias=None,
                   wt=False):
    """Plain-XLA composition with identical semantics, for tests/fallback."""
    import jax
    import jax.numpy as jnp

    a = x.astype(jnp.float32) * scale + shift
    if relu:
        a = jnp.maximum(a, 0.0)
    dims = (((1,), (1,)), ((), ())) if wt else (((1,), (0,)), ((), ()))
    y = jax.lax.dot_general(
        a.astype(x.dtype), w, dimension_numbers=dims,
        preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    y = y.astype(x.dtype)
    y32 = y.astype(jnp.float32)
    return y, jnp.sum(y32, axis=0), jnp.sum(jnp.square(y32), axis=0)


# ---------------------------------------------------------------------------
# priced HBM bytes per path (the roofline machinery)
# ---------------------------------------------------------------------------

def priced_fused_cost(m, k, n, dtype, relu=False, has_res=False,
                      has_bias=True, interpret=True):
    """HBM bytes of one LN->linear segment per path, priced with
    :func:`~mxnet_tpu.analysis.cost.program_cost`.

    The **einsum path** is priced at engine-op granularity — one program
    per graph op of the fallback composition (the affine scale, the
    affine shift, the ReLU prologue when present, the matmul+bias, the
    residual add), each op's operands and results a full HBM round trip
    — which is both the reference engine's per-op dispatch semantics
    and the materialization worst case for separately-rooted
    elementwise fusions.  The **fused path** is ONE program: the Pallas
    kernel's operands in, y (+ the two (N,) stats rows) out.  Returns
    ``{"einsum_bytes", "fused_bytes", "ratio", "phases"}``.
    """
    import jax
    import jax.numpy as jnp

    from ..analysis.cost import program_cost

    dt = jnp.dtype(dtype)
    x_s = jax.ShapeDtypeStruct((m, k), dt)
    g_s = jax.ShapeDtypeStruct((k,), jnp.float32)
    w_s = jax.ShapeDtypeStruct((n, k), dt)
    b_s = jax.ShapeDtypeStruct((n,), dt)
    y_s = jax.ShapeDtypeStruct((m, n), dt)

    phases = {}
    # 1./2. the affine scale and shift (two broadcast ops in the graph)
    phases["affine_mul"] = program_cost(
        jax.jit(lambda x, g: x * g), (x_s, g_s))["bytes"]
    phases["affine_add"] = program_cost(
        jax.jit(lambda x, g: x + g), (x_s, g_s))["bytes"]
    # 3. the ReLU prologue (its own Activation op when present)
    if relu:
        phases["relu"] = program_cost(
            jax.jit(lambda x: jnp.maximum(x, 0)), (x_s,))["bytes"]
    # 4. the matmul (+bias — one FullyConnected op)
    if has_bias:
        fn = jax.jit(lambda x, w, b: jnp.dot(x, w.T) + b)
        phases["matmul"] = program_cost(fn, (x_s, w_s, b_s))["bytes"]
    else:
        phases["matmul"] = program_cost(
            jax.jit(lambda x, w: jnp.dot(x, w.T)), (x_s, w_s))["bytes"]
    # 5. the residual add (its own elemwise op)
    if has_res:
        phases["residual"] = program_cost(
            jax.jit(lambda y, r: y + r), (y_s, y_s))["bytes"]
    einsum = sum(phases.values())

    # fused: ONE pass — kernel operands in, y + two (N,) stat rows out
    scale_s = jax.ShapeDtypeStruct((k,), jnp.float32)
    args = [x_s, scale_s, scale_s, w_s]
    kw = {"relu": relu, "wt": True, "interpret": interpret}
    if has_bias:
        args.append(b_s)
    if has_res:
        args.append(y_s)

    def fused_fn(x, scale, shift, w, *extra):
        extra = list(extra)
        bias = extra.pop(0) if has_bias else None
        res = extra.pop(0) if has_res else None
        return fused_scale_relu_matmul(x, scale, shift, w, residual=res,
                                       bias=bias, **kw)

    fused = program_cost(jax.jit(fused_fn), tuple(args))["bytes"]
    return {"einsum_bytes": int(einsum), "fused_bytes": int(fused),
            "ratio": round(fused / einsum, 4) if einsum else None,
            "phases": {p: int(v) for p, v in phases.items()}}


# ---------------------------------------------------------------------------
# tunable space (ops/tuning.py): block_m / block_m_bwd per shape class
# ---------------------------------------------------------------------------

def _tuning_candidates(shape_class, interpret):
    if interpret:
        # a toy 2-candidate space: tier-1 sweeps run the real machinery
        # on CPU without paying for a grid search
        return [{"block_m": 256, "block_m_bwd": 256},
                {"block_m": 512, "block_m_bwd": 128}]
    out = []
    for bm in (256, 512, 1024, 2048, 4096):
        for bmb in (128, 256, 512):
            out.append({"block_m": bm, "block_m_bwd": bmb})
    return out


def _tuning_runner(params, shape_class, dtype, interpret):
    import jax
    import jax.numpy as jnp

    from . import tuning

    dims = tuning.parse_shape_class(shape_class)
    m, k, n = dims["m"], dims["k"], dims["n"]
    if params["block_m"] and m % min(params["block_m"], m):
        raise tuning.SpaceError("block_m %d does not tile m=%d"
                                % (params["block_m"], m))
    dt = jnp.dtype(dtype)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (m, k), dt)
    w = jax.random.normal(key, (n, k), dt) * 0.05
    scale = jnp.ones((k,), jnp.float32)
    shift = jnp.zeros((k,), jnp.float32)
    bias = jnp.zeros((n,), dt)
    dy = jnp.ones((m, n), dt)

    bm, bmb = params["block_m"], params["block_m_bwd"]

    @jax.jit
    def probe(x, scale, shift, w, bias, dy):
        y, s1, s2 = _fwd_call(x, scale, shift, w, None, bias, False, True,
                              interpret, block_m=bm or None)
        dx, dw, ds, db = _bwd_call(x, dy, scale, shift, w, False, True,
                                   interpret, block_m=bmb)
        return y, dx, dw

    def run():
        outs = probe(x, scale, shift, w, bias, dy)
        jax.block_until_ready(outs)

    return run


def _register_space():
    from . import tuning

    tuning.register_space(
        "pallas_fused", version=1,
        defaults={"block_m": 0, "block_m_bwd": BLOCK_M_BWD},
        constants=("BLOCK_M", "BLOCK_N", "BLOCK_M_BWD"),
        candidates=_tuning_candidates, runner=_tuning_runner)


_register_space()
