"""FusedLNLinear — the LM training path's LN->linear segment as ONE op.

``models/attention_lm.py``'s pre-norm blocks are chains of exactly this
segment: LayerNorm's affine tail (gamma/beta), an optional ReLU
prologue, a FullyConnected, an optional residual add.  The stock graph
runs it as five registry ops — five HBM round trips over (B*T, E)-class
tensors on a bandwidth-bound model.  This op is the segment as one
node, so the trace-time dispatch below can hand the WHOLE chain to the
fused Pallas epilogue kernel (:mod:`~mxnet_tpu.ops.pallas_fused`,
``wt=True`` — FullyConnected's (num_hidden, K) weight layout contracts
in place): affine + ReLU ride the MXU operand load, bias + residual
ride the epilogue, x read once, y written once, forward AND backward
(the kernel is custom-VJP end to end, so ``train_step.py``'s compiled
donated program runs it both ways).

The LN *statistics* (mean/variance normalize) stay graph ops: they are
a cheap per-row reduction XLA fuses well, and keeping them out makes
the op a pure scale/shift->matmul — the exact kernel contract.

Dispatch (the ``paged_attend`` idiom): ``MXNET_PALLAS_FUSED`` armed AND
the backend can run it (TPU natively, anything else under
``MXNET_PALLAS_INTERPRET``) AND the executor is not mesh-sharded
(Pallas is GSPMD-opaque) AND :func:`pallas_fused.supported` accepts the
(M, K, N, dtype).  Otherwise the einsum fallback composition — the same
five-op math XLA sees today — with :data:`FUSED_PATH` recording which
path traced ("pallas" / "einsum-gated" / "einsum") so tests pin the
kernel actually running instead of silently regressing to 100%-einsum.

Parameter names and shapes are checkpoint-identical to the unfused
graph: gamma/beta keep their ``*_ln_gamma``/``*_ln_beta`` (1, 1, E)
Variables, weight/bias keep FullyConnected's ``(num_hidden, K)`` /
``(num_hidden,)``.
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op

# Which path the last FusedLNLinear dispatch traced — "pallas" when the
# fused kernel traced, "einsum-gated" when armed but the shape gate
# refused, "einsum" when the knob is off or the executor is
# mesh-sharded.  Written at trace time (the PATH_TAKEN idiom of
# ops/attention.py).
FUSED_PATH = {"last": None}


def fused_kernel_mode():
    """``(engage, interpret)`` for the fused LN->linear kernel under the
    current config and backend: engaged when ``MXNET_PALLAS_FUSED`` is
    set AND the backend can run it (TPU natively, anything else only
    under ``MXNET_PALLAS_INTERPRET``)."""
    from .. import config as _config

    if not _config.get("MXNET_PALLAS_FUSED"):
        return False, False
    import jax

    interpret = bool(_config.get("MXNET_PALLAS_INTERPRET"))
    on_tpu = jax.default_backend() == "tpu"
    return (on_tpu or interpret), (interpret and not on_tpu)


def _arg_names(attrs):
    # residual sits BEFORE weight/bias: Symbol composition auto-creates
    # missing trailing arguments as Variables, and callers pass the
    # residual explicitly while weight/bias auto-create
    args = ["data"]
    if not attrs.get("no_affine", False):
        args += ["gamma", "beta"]
    if attrs.get("has_residual", False):
        args.append("residual")
    args += ["weight", "bias"]
    return args


def _flnl_shape(attrs, in_shapes, aux_shapes):
    dshape = in_shapes[0]
    nh = attrs["num_hidden"]
    e = dshape[-1]
    out = tuple(dshape[:-1]) + (nh,)
    shapes = [dshape]
    if not attrs.get("no_affine", False):
        # LayerNorm's broadcast affine params, unchanged from the
        # unfused graph's layer_norm Variables
        shapes += [(1, 1, e), (1, 1, e)]
    if attrs.get("has_residual", False):
        shapes.append(out)
    shapes += [(nh, e), (nh,)]
    return shapes, [out], []


def _flnl(attrs, inputs, aux, octx):
    import jax.numpy as jnp

    ins = list(inputs)
    data = ins.pop(0)
    gamma = beta = None
    if not attrs.get("no_affine", False):
        gamma = ins.pop(0)
        beta = ins.pop(0)
    residual = ins.pop(0) if attrs.get("has_residual", False) else None
    weight = ins.pop(0)
    bias = ins.pop(0)
    relu = attrs.get("relu", False)

    lead = data.shape[:-1]
    k = data.shape[-1]
    n = weight.shape[0]
    m = 1
    for s in lead:
        m *= int(s)

    engage, interp = fused_kernel_mode()
    if engage and not octx.mesh_active:
        from . import pallas_fused as pf

        if pf.supported(m, k, n, data.dtype):
            FUSED_PATH["last"] = "pallas"
            scale = (gamma.reshape(-1).astype(jnp.float32)
                     if gamma is not None else jnp.ones((k,), jnp.float32))
            shift = (beta.reshape(-1).astype(jnp.float32)
                     if beta is not None else jnp.zeros((k,), jnp.float32))
            res2 = residual.reshape(m, n) if residual is not None else None
            # the (N,) stats outputs ride the epilogue for free; this
            # segment does not consume them, and their zero cotangents
            # fold out of the backward
            y, _s1, _s2 = pf.fused_scale_relu_matmul(
                data.reshape(m, k), scale, shift, weight, residual=res2,
                relu=relu, bias=bias, wt=True, interpret=interp)
            return [y.reshape(lead + (n,))], list(aux)
        FUSED_PATH["last"] = "einsum-gated"
    else:
        FUSED_PATH["last"] = "einsum"

    # fallback: the unfused five-op composition, numerically the graph
    # XLA ran before this op existed
    a = data
    if gamma is not None:
        a = a * gamma.reshape(-1) + beta.reshape(-1)
    if relu:
        a = jnp.maximum(a, 0)
    y = jnp.dot(a.reshape(m, k), weight.T) + bias
    y = y.reshape(lead + (n,))
    if residual is not None:
        y = y + residual
    return [y], list(aux)


def register_all():
    register_op(OpDef(
        "FusedLNLinear", _flnl,
        schema=ParamSchema(Param("num_hidden", int, required=True),
                           Param("relu", bool, default=False),
                           Param("no_affine", bool, default=False),
                           Param("has_residual", bool, default=False)),
        num_inputs=lambda a: len(_arg_names(a)),
        arguments=_arg_names,
        infer_shape=_flnl_shape, hint="fusedlnlinear",
        doc="LayerNorm-affine -> (ReLU) -> linear (+bias) (+residual) as "
            "one op; dispatches to the fused Pallas epilogue kernel "
            "under MXNET_PALLAS_FUSED (einsum fallback otherwise)."))


# ---------------------------------------------------------------------------
# roofline pricing (the train_step prober's data source)
# ---------------------------------------------------------------------------

def _fused_nodes(step):
    try:
        exec_ = step._group.exec_
        symbol = exec_._symbol
    except AttributeError:
        return None, []
    nodes = [nd for nd in symbol._topo()
             if nd.op is not None and nd.op.name == "FusedLNLinear"]
    return exec_, nodes


def step_has_fused_segments(step):
    """Whether the step's graph contains FusedLNLinear nodes at all —
    the train-step run() registers the lm_fused roofline row only then
    (ResNet-class steps keep their tables clean)."""
    return bool(_fused_nodes(step)[1])


def priced_fused_cost_for_step(step):
    """Aggregate :func:`pallas_fused.priced_fused_cost` over every
    FusedLNLinear segment in a compiled step's graph, on the shapes the
    step actually binds — `{"fused_path", "fused_kernel_bytes",
    "fused_einsum_bytes", "segments"}`, or None for steps without fused
    segments.  ``fused_path`` reflects the CURRENT knob/backend/shape
    gate, so arming ``MXNET_PALLAS_FUSED`` visibly moves the row."""
    import jax.numpy as jnp

    from . import pallas_fused as pf

    exec_, nodes = _fused_nodes(step)
    if not nodes:
        return None
    try:
        # every segment shares the flattened token count of the LM data
        # batch (B, T): m = B*T
        m = int(np.prod(exec_.arg_dict["data"].shape))
    except (KeyError, AttributeError):
        return None

    engage, _ = fused_kernel_mode()
    kernel_bytes = einsum_bytes = 0
    all_supported = True
    for nd in nodes:
        attrs = nd.parsed_attrs()
        args = _arg_names(attrs)
        wnode = nd.inputs[args.index("weight")][0]
        warr = exec_.arg_dict[wnode.name]
        n, k = warr.shape
        # the bound weight carries the step's compute dtype
        dtype = jnp.dtype(warr.dtype)
        priced = pf.priced_fused_cost(
            m, int(k), int(n), dtype, relu=attrs.get("relu", False),
            has_res=attrs.get("has_residual", False), has_bias=True,
            interpret=True)
        kernel_bytes += priced["fused_bytes"]
        einsum_bytes += priced["einsum_bytes"]
        if not pf.supported(m, int(k), int(n), dtype):
            all_supported = False
    if engage and all_supported:
        path = "pallas"
    elif engage:
        path = "einsum-gated"
    else:
        path = "einsum"
    return {"fused_path": path,
            "fused_kernel_bytes": int(kernel_bytes),
            "fused_einsum_bytes": int(einsum_bytes),
            "segments": len(nodes)}
