"""Pallas flash-attention kernels (TPU) — forward AND backward.

The hot-op kernel the einsum formulation can't match at long sequence:
``ops.attention.sdpa`` materializes the (T, T) logits in HBM — O(T²)
memory traffic — while these kernels stream K/V blocks through VMEM with
a running (max, sum, acc) softmax, O(T) memory, logits never leaving the
chip (flash-attention schedule; same numerics as the streaming
accumulator in ``parallel/ring.py``, here at the kernel level).

``flash_attention`` is differentiable: a ``jax.custom_vjp`` pairs the
forward kernel (which saves a per-row logsumexp residual) with two
backward kernels — one accumulating dQ over key blocks, one accumulating
dK/dV over query blocks — recomputing the (T, T) probabilities blockwise
from the residual instead of storing them.  This is the TPU analog of the
reference's fused-kernel-that-trains precedent (its cuDNN RNN op
implements forward *and* backward in one fused device kernel,
``src/operator/cudnn_rnn-inl.h``): long-context *training* runs the fast
path, not just inference.

Per-row residuals (logsumexp, and delta = rowsum(dO·O)) are stored
broadcast across a 128-lane minor dimension — ``(BH, T, LANES)`` — so the
backward kernels consume them with the same (rows, lanes) layout the MXU
tiles want, and no kernel ever transposes a vector.

Used by ``dot_product_attention`` when ``MXNET_PALLAS_ATTENTION`` enables
it and shapes divide the block size; anything else falls back to the
einsum path.  ``interpret=True`` runs the same kernels on CPU for tests.
"""
from __future__ import annotations

import functools

import numpy as np

# Block-size defaults for interpret/CPU mode (swept once on the bench
# chip — TPU v5 lite, T=2k-8k: fwd favors small-Q/large-K streaming; bwd
# favors a fatter Q block that amortizes the dQ/dK/dV accumulator
# read-modify-writes).  On a live device the tuning cache
# (ops/tuning.py) resolves per-(generation, shape-class, dtype) winners.
BLOCK_Q = 128
BLOCK_K = 512
BLOCK_Q_BWD = 256
BLOCK_K_BWD = 512
LANES = 128
MIN_BLOCK = 8


def _pick_block(pref, t):
    """Largest power-of-two shrink of ``pref`` that divides ``t``, or 0
    when the shrink degenerates below :data:`MIN_BLOCK` (odd/prime T
    used to walk all the way to a pathological 1-row kernel, and a prime
    T <= pref used to come back verbatim as a tile-misaligned full-T
    block) — callers treat 0 as "unsupported, take the einsum path"."""
    b = min(pref, t)
    b = 1 << (b.bit_length() - 1)   # power-of-two floor, never t itself
    while b >= MIN_BLOCK and t % b:
        b //= 2
    return b if b >= MIN_BLOCK and t % b == 0 else 0


# grouped shape classes whose stale-MHA-record check already ran (the
# warned-miss fires once per shape class per process, not per trace)
_STALE_GROUP_CHECKED = set()


def _tuned(t, d, dtype, groups=1):
    """Tuning-cache block resolution for this shape class ({"block_q",
    "block_k", "block_q_bwd", "block_k_bwd"}; the module constants when
    cold and no sweep armed).

    The kv-head group factor is part of the content-addressed key
    (``g<G>`` joins the shape class) — a grouped kernel's winning blocks
    see G× narrower K/V streams than the MHA kernel's at the same (t, d),
    so GQA shapes must never collide with MHA winners.  A persisted
    MHA-keyed record encountered for a grouped shape reads as a WARNED
    miss, never as a hit."""
    import jax.numpy as jnp

    from . import tuning

    name = jnp.dtype(dtype).name
    if groups <= 1:
        return tuning.resolve("pallas_attention",
                              tuning.shape_class_for(t=t, d=d), name)
    sc = tuning.shape_class_for(t=t, d=d, g=groups)
    if sc not in _STALE_GROUP_CHECKED:
        _STALE_GROUP_CHECKED.add(sc)
        if tuning.get("pallas_attention", sc, name, version=1) is None \
                and tuning.get("pallas_attention",
                               tuning.shape_class_for(t=t, d=d), name,
                               version=1) is not None:
            import warnings

            warnings.warn(
                "tuning cache holds an MHA-keyed pallas_attention record "
                "for t=%d d=%d but the shape is grouped (G=%d); the MHA "
                "winner does not apply — treating as a miss" %
                (t, d, groups))
    return tuning.resolve("pallas_attention", sc, name)


def _out_sds(shape, dtype, *inputs):
    """ShapeDtypeStruct for a pallas output, carrying the union of the
    inputs' varying-mesh-axes (vma) when tracing inside shard_map — the
    ring path calls these kernels per-device with 'seq'-varying blocks,
    and shard_map's vma checking requires outputs to declare it."""
    import jax

    try:
        vma = frozenset().union(*[jax.typeof(a).vma for a in inputs])
    except (AttributeError, TypeError):
        vma = frozenset()
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _lane_tile(x, n):
    """(rows, LANES) residual with all lanes equal -> (rows, n)."""
    import jax.numpy as jnp

    if n == LANES:
        return x
    if n % LANES == 0:
        return jnp.tile(x, (1, n // LANES))
    return x[:, :n]


def _kernel(q_ref, k_ref, v_ref, o_ref, *rest, scale, causal, block_q,
            block_k, with_lse=False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if with_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        lse_ref = None
        m_scr, l_scr, acc_scr = rest

    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _update():
        q = q_ref[0]                                # (BQ, D)
        k = k_ref[0]                                # (BK, D)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(scale)

        if causal:
            qi = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kj = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s_masked = jnp.where(qi >= kj, s, -jnp.inf)
        else:
            s_masked = s
        s = s_masked

        m_prev = m_scr[:, :1]                       # (BQ, 1)
        blk_m = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_m)
        # rows with every key masked so far keep m = -inf; normalize safely
        m_safe = jnp.where(m_new == -jnp.inf, 0.0, m_new)
        p = jnp.exp(s - m_safe)
        p = jnp.where(s == -jnp.inf, 0.0, p)
        corr = jnp.where(m_prev == -jnp.inf, 0.0, jnp.exp(m_prev - m_safe))

        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc = acc_scr[:] * corr + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = acc

    if causal:
        # skip K/V blocks entirely above the diagonal (~2x on long T)
        @pl.when(j * block_k <= i * block_q + block_q - 1)
        def _masked_update():
            _update()
    else:
        _update()

    @pl.when(j == nj - 1)
    def _finish():
        denom = l_scr[:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)
        if lse_ref is not None:
            m_fin = jnp.where(m_scr[:] == -jnp.inf, 0.0, m_scr[:])
            d_fin = jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:])
            lse_ref[0] = m_fin + jnp.log(d_fin)


def _fwd_call(q, k, v, scale, causal, interpret, with_lse, block_q=None,
              block_k=None, groups=1):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    g = int(groups)
    if k.shape[0] * g != bh:
        raise ValueError(
            "flash_attention fwd: folded K/V batch %d * groups=%d != "
            "folded Q batch %d" % (k.shape[0], g, bh))
    if block_q is None or block_k is None:
        cfg = _tuned(t, d, q.dtype, groups=g)
        block_q = block_q or cfg.get("block_q", BLOCK_Q)
        block_k = block_k or cfg.get("block_k", BLOCK_K)
    bq = _pick_block(block_q, t)
    bk = _pick_block(block_k, t)
    if not bq or not bk:
        raise ValueError("flash_attention fwd blocks degenerate for T=%d "
                         "(callers must gate on supported())" % t)
    grid = (bh, t // bq, t // bk)

    # grouped K/V: folded Q batch index b encodes (batch, q-head) as
    # b = batch*H + h, so its kv block lives at folded index
    # batch*H_kv + h//G == b // G — the h // G group map, in the
    # BlockSpec index map (never a materialized broadcast)
    if g == 1:
        kv_map = lambda b, i, j: (b, j, 0)          # noqa: E731
    else:
        kv_map = lambda b, i, j: (b // g, j, 0)     # noqa: E731

    kernel = functools.partial(_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk, with_lse=with_lse)
    out_shape = [_out_sds(q.shape, q.dtype, q, k, v)]
    out_specs = [pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))]
    if with_lse:
        out_shape.append(
            _out_sds((bh, t, LANES), jnp.float32, q, k, v))
        out_specs.append(
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)))
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), kv_map),
            pl.BlockSpec((1, bk, d), kv_map),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max
            pltpu.VMEM((bq, 128), jnp.float32),   # running sum
            pltpu.VMEM((bq, d), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return (res[0], res[1]) if with_lse else (res[0], None)


def _recompute_p_ds(refs, i, j, *, scale, causal, block_q, block_k):
    """Shared backward-recompute math: rebuild this (i, j) block's softmax
    probabilities p and the logit cotangent ds from the forward residuals.
    One copy keeps dQ's and dK/dV's numerics (mask convention, scale
    application) in lockstep with each other and with the forward."""
    import jax
    import jax.numpy as jnp

    q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref = refs
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * jnp.float32(scale)
    if causal:
        qi = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kj = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(qi >= kj, s, -jnp.inf)
    lse = _lane_tile(lse_ref[0], block_k)
    p = jnp.exp(s - lse)                        # masked lanes -> 0
    dp = jax.lax.dot_general(
        do, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dta = _lane_tile(dta_ref[0], block_k)
    ds = p * (dp - dta) * jnp.float32(scale)
    return p, ds


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref, dq_ref,
                   dq_scr, *, scale, causal, block_q, block_k):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _update():
        _, ds = _recompute_p_ds(
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref), i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k)
        k = k_ref[0]
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(j * block_k <= i * block_q + block_q - 1)
        def _masked_update():
            _update()
    else:
        _update()

    @pl.when(j == nj - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref, dk_ref,
                    dv_ref, dk_scr, dv_scr, *, scale, causal, block_q,
                    block_k):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(1)   # key block (outer)
    i = pl.program_id(2)   # query block (inner, accumulated)
    ni = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _update():
        p, ds = _recompute_p_ds(
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref), i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k)
        q = q_ref[0]
        do = do_ref[0]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # query blocks strictly above this key block see none of it
        @pl.when(i * block_q + block_q - 1 >= j * block_k)
        def _masked_update():
            _update()
    else:
        _update()

    @pl.when(i == ni - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dkv_kernel_grouped(q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref,
                            dk_ref, dv_ref, dk_scr, dv_scr, *, scale,
                            causal, block_q, block_k):
    """Grouped twin of :func:`_bwd_dkv_kernel`: the grid grows a trailing
    group dim (B*H_kv, T/bk, T/bq, G) and the VMEM scratch accumulates
    every one of a kv head's G q-heads' contributions before the single
    write-back — dK/dV land at the GROUPED width, no q-width gradient is
    ever materialized."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(1)   # key block (outer)
    i = pl.program_id(2)   # query block (accumulated)
    gi = pl.program_id(3)  # q-head within the kv group (accumulated)
    ni = pl.num_programs(2)
    ng = pl.num_programs(3)

    @pl.when((i == 0) & (gi == 0))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _update():
        import jax

        p, ds = _recompute_p_ds(
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dta_ref), i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k)
        q = q_ref[0]
        do = do_ref[0]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(i * block_q + block_q - 1 >= j * block_k)
        def _masked_update():
            _update()
    else:
        _update()

    @pl.when((i == ni - 1) & (gi == ng - 1))
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, scale, causal, interpret, block_q=None,
              block_k=None, groups=1):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    g = int(groups)
    bh_kv = k.shape[0]
    if bh_kv * g != bh:
        raise ValueError(
            "flash_attention bwd: folded K/V batch %d * groups=%d != "
            "folded Q batch %d" % (bh_kv, g, bh))
    if block_q is None or block_k is None:
        cfg = _tuned(t, d, q.dtype, groups=g)
        block_q = block_q or cfg.get("block_q_bwd", BLOCK_Q_BWD)
        block_k = block_k or cfg.get("block_k_bwd", BLOCK_K_BWD)
    bq = _pick_block(block_q, t)
    bk = _pick_block(block_k, t)
    if not bq or not bk:
        raise ValueError("flash_attention bwd blocks degenerate for T=%d "
                         "(callers must gate on supported())" % t)

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (bh, t, LANES))

    if g == 1:
        kv_map = lambda b, i, j: (b, j, 0)          # noqa: E731
    else:
        kv_map = lambda b, i, j: (b // g, j, 0)     # noqa: E731

    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale,
                                  causal=causal, block_q=bq, block_k=bk)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=_out_sds(q.shape, q.dtype, q, k, v, do, lse, delta),
        grid=(bh, t // bq, t // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),       # q
            pl.BlockSpec((1, bk, d), kv_map),                          # k
            pl.BlockSpec((1, bk, d), kv_map),                          # v
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),       # do
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),   # lse
            pl.BlockSpec((1, bq, LANES), lambda b, i, j: (b, i, 0)),   # dta
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    if g == 1:
        dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale,
                                       causal=causal, block_q=bq,
                                       block_k=bk)
        dk, dv = pl.pallas_call(
            dkv_kernel,
            out_shape=[
                _out_sds(k.shape, k.dtype, q, k, v, do, lse, delta),
                _out_sds(v.shape, v.dtype, q, k, v, do, lse, delta)],
            grid=(bh, t // bk, t // bq),
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, bq, LANES),
                             lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, bq, LANES),
                             lambda b, j, i: (b, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                            pltpu.VMEM((bk, d), jnp.float32)],
            interpret=interpret,
        )(q, k, v, do, lse, delta)
        return dq, dk, dv

    # grouped dK/dV: grid walks (kv batch, key block, query block, group
    # member) — the b axis is the FOLDED KV batch, q/do/residual blocks
    # index q-head b*G + gi, and the scratch accumulates across both i
    # and gi before one grouped-width write-back
    dkv_kernel = functools.partial(_bwd_dkv_kernel_grouped, scale=scale,
                                   causal=causal, block_q=bq, block_k=bk)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=[_out_sds(k.shape, k.dtype, q, k, v, do, lse, delta),
                   _out_sds(v.shape, v.dtype, q, k, v, do, lse, delta)],
        grid=(bh_kv, t // bk, t // bq, g),
        in_specs=[
            pl.BlockSpec((1, bq, d),
                         lambda b, j, i, gi: (b * g + gi, i, 0)),      # q
            pl.BlockSpec((1, bk, d), lambda b, j, i, gi: (b, j, 0)),   # k
            pl.BlockSpec((1, bk, d), lambda b, j, i, gi: (b, j, 0)),   # v
            pl.BlockSpec((1, bq, d),
                         lambda b, j, i, gi: (b * g + gi, i, 0)),      # do
            pl.BlockSpec((1, bq, LANES),
                         lambda b, j, i, gi: (b * g + gi, i, 0)),      # lse
            pl.BlockSpec((1, bq, LANES),
                         lambda b, j, i, gi: (b * g + gi, i, 0)),      # dta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i, gi: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i, gi: (b, j, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


_VJP_CACHE = {}


def _flash_vjp():
    """Build (once) the custom_vjp-wrapped kernel entry point."""
    if "fn" in _VJP_CACHE:
        return _VJP_CACHE["fn"]
    import jax

    @functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
    def _flash(q, k, v, scale, causal, interpret, groups):
        out, _ = _fwd_call(q, k, v, scale, causal, interpret,
                           with_lse=False, groups=groups)
        return out

    def _fwd_rule(q, k, v, scale, causal, interpret, groups):
        out, lse = _fwd_call(q, k, v, scale, causal, interpret,
                             with_lse=True, groups=groups)
        return out, (q, k, v, out, lse)

    def _bwd_rule(scale, causal, interpret, groups, res, do):
        q, k, v, out, lse = res
        return _bwd_call(q, k, v, out, lse, do, scale, causal, interpret,
                         groups=groups)

    _flash.defvjp(_fwd_rule, _bwd_rule)
    _VJP_CACHE["fn"] = _flash
    return _flash


def _einsum_fallback(q, k, v, scale, causal, groups=1):
    """Plain-XLA attention with the kernel's numerics contract, for
    shapes whose blocks degenerate (odd/prime T); differentiable through
    ordinary autodiff.  ``groups`` > 1 maps folded q row ``b`` onto K/V
    row ``b // groups`` via reshape, like the kernel's index maps."""
    import jax
    import jax.numpy as jnp

    if groups > 1:
        bh, t, d = q.shape
        qg = q.reshape(bh // groups, groups, t, d)
        s = jnp.einsum("bgqd,bkd->bgqk", qg.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        if causal:
            mask = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(mask[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bgqk,bkd->bgqd", p, v.astype(jnp.float32))
        return out.reshape(bh, t, d).astype(q.dtype)
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)) \
        .astype(q.dtype)


def flash_attention(q, k, v, scale, causal=False, interpret=False,
                    groups=1):
    """(BH, T, D) q vs (BH_kv, T, D) k/v -> (BH, T, D) attention output
    (``BH_kv == BH`` at ``groups=1``).  Differentiable (custom_vjp over
    the backward kernels — training runs the flash path).

    T whose block shrink degenerates below :data:`MIN_BLOCK` (odd or
    prime T — formerly a pathological 1-row kernel) takes the einsum
    fallback instead; tile-aligned T runs the kernels."""
    t = q.shape[1]
    if not (_pick_block(BLOCK_Q, t) and _pick_block(BLOCK_K, t)
            and _pick_block(BLOCK_Q_BWD, t)
            and _pick_block(BLOCK_K_BWD, t)):
        return _einsum_fallback(q, k, v, float(scale), bool(causal),
                                groups=int(groups))
    return _flash_vjp()(q, k, v, float(scale), bool(causal),
                        bool(interpret), int(groups))


def supported(q_shape, k_shape, causal, num_heads=1, num_kv_heads=0):
    """Whether the kernel handles these shapes (self-attention, T a
    multiple of the 128 sublane/lane tile, lane-friendly head dim).
    ``_pick_block`` shrinks the preferred block sizes to divide any such
    T, so 128-alignment is the only sequence-length constraint.  The lane
    check is on the PER-HEAD dim (E/num_heads) — the kernel operates on
    head-folded (B*H, T, E/H) blocks, so E=512/H=16 (head_dim 32) must
    fall back even though E itself is lane-aligned.  Grouped configs
    (``num_kv_heads < num_heads``) additionally require the K width to be
    exactly H_kv head slices."""
    bh, tq, d = q_shape
    tk = k_shape[1]
    if tq != tk:                       # cross-attention: fallback
        return False
    if tq % 128:                       # tile-aligned T only
        return False
    if num_heads <= 0 or d % num_heads:
        return False
    kvh = int(num_kv_heads) or int(num_heads)
    if kvh <= 0 or num_heads % kvh:
        return False
    if k_shape[2] != kvh * (d // num_heads):
        return False
    if (d // num_heads) % 64 != 0:     # lane-unfriendly heads: fallback
        return False
    # degenerate block shrink (odd/prime T below the tile check above
    # can't happen, but keep the gate self-sufficient for direct callers)
    if not (_pick_block(BLOCK_Q, tq) and _pick_block(BLOCK_K, tq)
            and _pick_block(BLOCK_Q_BWD, tq)
            and _pick_block(BLOCK_K_BWD, tq)):
        return False
    return True


def sdpa_flash(q, k, v, num_heads, causal, scale, interpret=False,
               num_kv_heads=0):
    """Multi-head wrapper matching ops.attention.sdpa's contract:
    (B, T, E) -> (B, T, E) with heads folded into the batch dim.
    Grouped configs fold K/V at their physical H_kv count — the kernels
    map q-head ``h`` to kv block ``h // G`` in their index maps."""
    b, t, e = q.shape
    kvh = int(num_kv_heads) or int(num_heads)
    g = num_heads // kvh
    hd = e // num_heads
    scale = scale or 1.0 / np.sqrt(hd)

    def fold(x, h):
        return x.reshape(b, t, h, x.shape[2] // h).transpose(0, 2, 1, 3) \
            .reshape(b * h, t, x.shape[2] // h)

    out = flash_attention(fold(q, num_heads), fold(k, kvh), fold(v, kvh),
                          scale=float(scale), causal=bool(causal),
                          interpret=bool(interpret), groups=g)
    return out.reshape(b, num_heads, t, hd).transpose(0, 2, 1, 3) \
        .reshape(b, t, e)


# ---------------------------------------------------------------------------
# tunable space (ops/tuning.py): fwd/bwd Q/K blocks per shape class
# ---------------------------------------------------------------------------

def _tuning_candidates(shape_class, interpret):
    if interpret:
        # 2-candidate toy space: tier-1 exercises the sweep machinery on
        # CPU without a grid search
        return [{"block_q": 128, "block_k": 128},
                {"block_q": 128, "block_k": 256}]
    out = []
    for bq in (128, 256):
        for bk in (256, 512, 1024):
            for bqb in (128, 256):
                out.append({"block_q": bq, "block_k": bk,
                            "block_q_bwd": bqb, "block_k_bwd": 512})
    return out


def _tuning_runner(params, shape_class, dtype, interpret):
    import jax
    import jax.numpy as jnp

    from . import tuning

    dims = tuning.parse_shape_class(shape_class)
    t, d = dims["t"], dims["d"]
    for key in ("block_q", "block_k", "block_q_bwd", "block_k_bwd"):
        if not _pick_block(params[key], t):
            raise tuning.SpaceError("%s=%d degenerates for T=%d"
                                    % (key, params[key], t))
    dt = jnp.dtype(dtype)
    rng = jax.random.PRNGKey(0)
    bh = 4
    q = jax.random.normal(rng, (bh, t, d), dt)
    k = jax.random.normal(jax.random.fold_in(rng, 1), (bh, t, d), dt)
    v = jax.random.normal(jax.random.fold_in(rng, 2), (bh, t, d), dt)
    do = jnp.ones((bh, t, d), dt)
    scale = 1.0 / float(np.sqrt(d))

    bq, bk = params["block_q"], params["block_k"]
    bqb, bkb = params["block_q_bwd"], params["block_k_bwd"]

    @jax.jit
    def probe(q, k, v, do):
        o, lse = _fwd_call(q, k, v, scale, True, interpret, with_lse=True,
                           block_q=bq, block_k=bk)
        grads = _bwd_call(q, k, v, o, lse, do, scale, True, interpret,
                          block_q=bqb, block_k=bkb)
        return (o,) + tuple(grads)

    def run():
        jax.block_until_ready(probe(q, k, v, do))

    return run


def _register_space():
    from . import tuning

    tuning.register_space(
        "pallas_attention", version=1,
        defaults={"block_q": BLOCK_Q, "block_k": BLOCK_K,
                  "block_q_bwd": BLOCK_Q_BWD, "block_k_bwd": BLOCK_K_BWD},
        constants=("BLOCK_Q", "BLOCK_K", "BLOCK_Q_BWD", "BLOCK_K_BWD"),
        candidates=_tuning_candidates, runner=_tuning_runner)


_register_space()
