"""Pallas flash-attention forward kernel (TPU).

The hot-op kernel the einsum formulation can't match at long sequence:
``ops.attention.sdpa`` materializes the (T, T) logits in HBM — O(T²)
memory traffic — while this kernel streams K/V blocks through VMEM with a
running (max, sum, acc) softmax, O(T) memory, logits never leaving the
chip (flash-attention schedule; same numerics as the streaming
accumulator in ``parallel/ring.py``, here at the kernel level).

Used by ``dot_product_attention`` when ``MXNET_PALLAS_ATTENTION`` enables
it and shapes divide the block size; anything else falls back to the
einsum path.  ``interpret=True`` runs the same kernel on CPU for tests.
"""
from __future__ import annotations

import functools

import numpy as np

BLOCK_Q = 128
BLOCK_K = 128


def _kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
            scale, causal, block_q, block_k):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    i = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _update():
        q = q_ref[0]                                # (BQ, D)
        k = k_ref[0]                                # (BK, D)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(scale)

        if causal:
            qi = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kj = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s_masked = jnp.where(qi >= kj, s, -jnp.inf)
        else:
            s_masked = s
        s = s_masked

        m_prev = m_scr[:, :1]                       # (BQ, 1)
        blk_m = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_m)
        # rows with every key masked so far keep m = -inf; normalize safely
        m_safe = jnp.where(m_new == -jnp.inf, 0.0, m_new)
        p = jnp.exp(s - m_safe)
        p = jnp.where(s == -jnp.inf, 0.0, p)
        corr = jnp.where(m_prev == -jnp.inf, 0.0, jnp.exp(m_prev - m_safe))

        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc = acc_scr[:] * corr + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = acc

    if causal:
        # skip K/V blocks entirely above the diagonal (~2x on long T)
        @pl.when(j * block_k <= i * block_q + block_q - 1)
        def _masked_update():
            _update()
    else:
        _update()

    @pl.when(j == nj - 1)
    def _finish():
        denom = l_scr[:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def flash_attention(q, k, v, scale, causal=False, interpret=False):
    """(BH, T, D) q/k/v -> (BH, T, D) attention output.

    T must divide BLOCK_Q/BLOCK_K (the caller checks and falls back)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    bq = min(BLOCK_Q, t)
    bk = min(BLOCK_K, t)
    grid = (bh, t // bq, t // bk)

    kernel = functools.partial(_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),   # running max
            pltpu.VMEM((bq, 128), jnp.float32),   # running sum
            pltpu.VMEM((bq, d), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)


def supported(q_shape, k_shape, causal):
    """Whether the kernel handles these shapes (self-attention, block-
    divisible T, lane-friendly head dim)."""
    bh, tq, d = q_shape
    tk = k_shape[1]
    if tq != tk:                       # cross-attention: fallback
        return False
    if tq % BLOCK_Q or tq % BLOCK_K:   # block-divisible T only
        return False
    if d % 64 != 0:                    # lane-unfriendly heads: fallback
        return False
    return True


def sdpa_flash(q, k, v, num_heads, causal, scale, interpret=False):
    """Multi-head wrapper matching ops.attention.sdpa's contract:
    (B, T, E) -> (B, T, E) with heads folded into the batch dim."""
    b, t, e = q.shape
    hd = e // num_heads
    scale = scale or 1.0 / np.sqrt(hd)

    def fold(x):
        return x.reshape(b, t, num_heads, hd).transpose(0, 2, 1, 3) \
            .reshape(b * num_heads, t, hd)

    out = flash_attention(fold(q), fold(k), fold(v), scale=float(scale),
                          causal=bool(causal), interpret=bool(interpret))
    return out.reshape(b, num_heads, t, hd).transpose(0, 2, 1, 3) \
        .reshape(b, t, e)
