"""Contrib operators: CTC loss, SSD detection ops, Faster-RCNN proposals,
FFT, quantization.

TPU-native designs of `src/operator/contrib/`: the CTC forward recursion is
a ``lax.scan`` in log space (gradients via jax AD instead of warp-ctc's
hand-written alpha-beta kernels), box matching/NMS are dense IoU matrices +
masked scans (static shapes, no dynamic-size host loops), FFT rides
``jnp.fft`` with the reference's interleaved re/im packing, and quantize
mirrors the uint8 range-quantization contract.
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op, simple_compute

_NEG = -1e30  # log-space "minus infinity" that survives bf16/f32 arithmetic


# ---------------------------------------------------------------------------
# CTC loss
# ---------------------------------------------------------------------------

def _ctc_loss(attrs, data, label):
    """Connectionist temporal classification negative log-likelihood.

    data: (T, N, A) activations (A includes the blank at index 0);
    label: (N, L) target ids in 1..A-1, 0-padded.
    Output: (N,) loss.  Forward-only alpha recursion over the extended
    blank-interleaved label, scanned over time in log space; jax AD through
    the scan supplies the gradient (the reference vendors warp-ctc kernels,
    ctc_loss.cc).
    """
    import jax.numpy as jnp
    from jax import lax, nn

    t_len, n, alphabet = data.shape
    l_len = label.shape[1]
    logp = nn.log_softmax(data.astype(jnp.float32), axis=-1)

    lab = label.astype(jnp.int32)                       # (N, L)
    lengths = (lab > 0).sum(axis=1)                     # true label lengths
    s = 2 * l_len + 1

    # extended label: blank, l1, blank, l2, ... blank
    ext = jnp.zeros((n, s), jnp.int32)
    ext = ext.at[:, 1::2].set(lab)

    # a state s may skip from s-2 when both are non-blank and different
    prev_lab = jnp.pad(ext, ((0, 0), (2, 0)))[:, :s]
    can_skip = (ext != 0) & (ext != prev_lab)

    positions = jnp.arange(s)
    valid = positions[None, :] < (2 * lengths + 1)[:, None]

    init = jnp.full((n, s), _NEG, jnp.float32)
    init = init.at[:, 0].set(0.0).at[:, 1].set(0.0)
    # alpha_0 must respect emission at t=0
    emit0 = jnp.take_along_axis(logp[0], ext, axis=1)
    init = jnp.where(valid, init + emit0, _NEG)
    init = init.at[:, 2:].set(_NEG)

    def step(alpha, logp_t):
        stay = alpha
        from_prev = jnp.pad(alpha, ((0, 0), (1, 0)),
                            constant_values=_NEG)[:, :s]
        from_skip = jnp.pad(alpha, ((0, 0), (2, 0)),
                            constant_values=_NEG)[:, :s]
        from_skip = jnp.where(can_skip, from_skip, _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(stay, from_prev), from_skip)
        emit = jnp.take_along_axis(logp_t, ext, axis=1)
        alpha = jnp.where(valid, merged + emit, _NEG)
        return alpha, None

    alpha, _ = lax.scan(step, init, logp[1:])
    # final states: last blank or last symbol of each sequence
    last = 2 * lengths
    a_end = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_end2 = jnp.take_along_axis(
        alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
    loglike = jnp.logaddexp(a_end, jnp.where(lengths > 0, a_end2, _NEG))
    return (-loglike).astype(data.dtype)


def _ctc_shape(attrs, in_shapes, aux_shapes):
    dshape = in_shapes[0]
    return in_shapes, [(dshape[1],)], []


# ---------------------------------------------------------------------------
# box helpers
# ---------------------------------------------------------------------------

def _iou_matrix(a, b):
    """Pairwise IoU of corner-format boxes: a (A,4) x b (B,4) -> (A,B)."""
    import jax.numpy as jnp

    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum((a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]), 0.0)
    area_b = jnp.maximum((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), 0.0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _corner_to_center(boxes):
    import jax.numpy as jnp

    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    return jnp.stack([boxes[..., 0] + w / 2, boxes[..., 1] + h / 2, w, h],
                     axis=-1)


def _rank_desc(scores):
    """Each element's 0-based rank when sorting descending (rank < k
    selects the top-k) — the shared top-k-by-score primitive for mining
    and pre-NMS cuts."""
    import jax.numpy as jnp

    return jnp.argsort(jnp.argsort(-scores))


# ---------------------------------------------------------------------------
# MultiBox* (SSD)
# ---------------------------------------------------------------------------

def _multibox_prior(attrs, data):
    """Anchor boxes per feature-map cell (ref: multibox_prior.cc).

    Anchor count per cell = len(sizes) + len(ratios) - 1: all sizes at
    ratio[0], plus ratios[1:] at size[0].
    """
    import jax.numpy as jnp

    h, w = data.shape[2], data.shape[3]
    sizes = attrs["sizes"]
    ratios = attrs["ratios"]
    steps = attrs["steps"]
    offsets = attrs["offsets"]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w

    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")        # (h, w)

    half = []
    for s in sizes:
        half.append((s * np.sqrt(ratios[0]) / 2, s / np.sqrt(ratios[0]) / 2))
    for r in ratios[1:]:
        half.append((sizes[0] * np.sqrt(r) / 2, sizes[0] / np.sqrt(r) / 2))
    half = jnp.asarray(half, jnp.float32)               # (K, 2) = (hw, hh)

    centers = jnp.stack([gx, gy], axis=-1).reshape(-1, 1, 2)   # (hw, 1, 2)
    mins = centers - half[None]                                 # x1 y1
    maxs = centers + half[None]
    boxes = jnp.concatenate([mins, maxs], axis=-1)      # (hw, K, 4)
    if attrs["clip"]:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.reshape(1, -1, 4)


def _prior_count(attrs):
    return len(attrs["sizes"]) + len(attrs["ratios"]) - 1


def _multibox_prior_shape(attrs, in_shapes, aux_shapes):
    h, w = in_shapes[0][2], in_shapes[0][3]
    return in_shapes, [(1, h * w * _prior_count(attrs), 4)], []


def _multibox_target(attrs, anchors, labels, cls_preds):
    """Match anchors to ground truth (ref: multibox_target.cc).

    anchors (1,A,4); labels (N,O,5) rows [cls,x1,y1,x2,y2] with cls=-1
    padding; outputs loc_target (N,A*4), loc_mask (N,A*4), cls_target (N,A)
    where class 0 = background and gt classes shift by +1.
    """
    import jax
    import jax.numpy as jnp

    iou_thresh = attrs["overlap_threshold"]
    variances = attrs["variances"]
    mining_ratio = attrs.get("negative_mining_ratio", -1.0)
    mining_thresh = attrs.get("negative_mining_thresh", 0.5)
    ignore_label = attrs.get("ignore_label", -1.0)
    anc = anchors[0]                                    # (A, 4)

    def one(lab, cls_pred):
        valid = lab[:, 0] >= 0                          # (O,)
        iou = _iou_matrix(anc, lab[:, 1:5])             # (A, O)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_o = jnp.argmax(iou, axis=1)                # (A,)
        best_iou = jnp.take_along_axis(iou, best_o[:, None], axis=1)[:, 0]
        # force-match: each gt claims its best anchor.  scatter-max (not
        # set): padding rows all argmax to anchor 0 and a duplicate-index
        # set(False) could overwrite a real gt's True
        best_a = jnp.argmax(jnp.where(valid[None, :], iou, -1.0), axis=0)
        forced = jnp.zeros(anc.shape[0], bool).at[best_a].max(valid)
        matched = forced | (best_iou >= iou_thresh)

        gt = lab[best_o]                                # (A, 5)
        if mining_ratio > 0:
            # hard-negative mining (ref multibox_target.cc:162-221): only
            # unmatched anchors with IoU below negative_mining_thresh are
            # candidates; the hardest (lowest background probability from
            # cls_pred (classes, A)) num_positive*ratio become background,
            # every other unmatched anchor gets ignore_label
            num_pos = jnp.sum(matched)
            num_neg = jnp.minimum(
                (num_pos * mining_ratio).astype(jnp.int32),
                anc.shape[0] - num_pos)
            logits = cls_pred.astype(jnp.float32)       # (C, A)
            bg_prob = jax.nn.softmax(logits, axis=0)[0]  # (A,)
            cand = (~matched) & (best_iou < mining_thresh)
            hardness = jnp.where(cand, -bg_prob, -jnp.inf)
            rank = _rank_desc(hardness)
            neg = cand & (rank < num_neg)
            cls_t = jnp.where(
                matched, gt[:, 0] + 1.0,
                jnp.where(neg, 0.0, ignore_label))
        else:
            cls_t = jnp.where(matched, gt[:, 0] + 1.0, 0.0)

        a_c = _corner_to_center(anc)
        g_c = _corner_to_center(gt[:, 1:5])
        loc = jnp.stack([
            (g_c[:, 0] - a_c[:, 0]) / jnp.maximum(a_c[:, 2], 1e-8) / variances[0],
            (g_c[:, 1] - a_c[:, 1]) / jnp.maximum(a_c[:, 3], 1e-8) / variances[1],
            jnp.log(jnp.maximum(g_c[:, 2], 1e-8) /
                    jnp.maximum(a_c[:, 2], 1e-8)) / variances[2],
            jnp.log(jnp.maximum(g_c[:, 3], 1e-8) /
                    jnp.maximum(a_c[:, 3], 1e-8)) / variances[3],
        ], axis=-1)                                     # (A, 4)
        mask = matched[:, None].astype(jnp.float32)
        return (loc * mask).reshape(-1), \
            jnp.broadcast_to(mask, loc.shape).reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(labels, cls_preds)
    return loc_t, loc_m, cls_t


def _multibox_target_shape(attrs, in_shapes, aux_shapes):
    a = in_shapes[0][1]
    n = in_shapes[1][0]
    return in_shapes, [(n, a * 4), (n, a * 4), (n, a)], []


def _decode_boxes(anc_c, loc, variances):
    """Inverse of the target encoding -> corner boxes (A, 4)."""
    import jax.numpy as jnp

    cx = loc[:, 0] * variances[0] * anc_c[:, 2] + anc_c[:, 0]
    cy = loc[:, 1] * variances[1] * anc_c[:, 3] + anc_c[:, 1]
    w = jnp.exp(jnp.clip(loc[:, 2] * variances[2], -10, 10)) * anc_c[:, 2]
    h = jnp.exp(jnp.clip(loc[:, 3] * variances[3], -10, 10)) * anc_c[:, 3]
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def _greedy_nms(boxes, scores, thresh, class_ids=None):
    """Greedy non-max suppression with static shapes.

    Sort by score, then scan: box i is kept iff no higher-scoring kept box
    overlaps it above ``thresh``.  Returns the keep mask in sorted order —
    the iterative suppression as one masked pass over the dense IoU matrix
    instead of a dynamic host loop.  With ``class_ids``, suppression only
    applies between boxes of the same class (the reference's
    force_suppress=False mode).
    """
    import jax.numpy as jnp
    from jax import lax

    order = jnp.argsort(-scores)
    sorted_boxes = boxes[order]
    overlaps = _iou_matrix(sorted_boxes, sorted_boxes) > thresh
    if class_ids is not None:
        cls = class_ids[order]
        overlaps &= cls[:, None] == cls[None, :]

    def step(keep, i):
        above = (jnp.arange(keep.shape[0]) < i) & keep & overlaps[i]
        keep = keep.at[i].set(~above.any() & keep[i])
        return keep, None

    keep0 = jnp.ones(boxes.shape[0], bool)
    keep, _ = lax.scan(step, keep0, jnp.arange(boxes.shape[0]))
    return order, keep


def _multibox_detection(attrs, cls_prob, loc_pred, anchors):
    """Decode + per-class NMS (ref: multibox_detection.cc).

    cls_prob (N, classes+1, A) with background at 0; output (N, A, 6) rows
    [cls_id, score, x1, y1, x2, y2], suppressed rows cls_id = -1.
    """
    import jax
    import jax.numpy as jnp

    thresh = attrs["threshold"]
    nms_thresh = attrs["nms_threshold"]
    variances = attrs["variances"]
    force_suppress = attrs["force_suppress"]
    nms_topk = attrs.get("nms_topk", -1)
    anc_c = _corner_to_center(anchors[0])

    def one(probs, loc):
        boxes = _decode_boxes(anc_c, loc.reshape(-1, 4), variances)
        fg = probs[1:]                                  # (classes, A)
        cls_id = jnp.argmax(fg, axis=0)                 # (A,)
        score = jnp.max(fg, axis=0)
        keep_score = score > thresh
        a = boxes.shape[0]
        if 0 < nms_topk < a:
            # only the top-k candidates by score enter NMS (ref
            # multibox_detection.cc:125-127) — and the suppression scan
            # runs over the k-row slice, not all anchors (k steps, k x k
            # IoU: the detection-scale fast path, benchmarks/
            # bench_detection.py)
            order_full = jnp.argsort(
                -jnp.where(keep_score, score, -jnp.inf))
            top = order_full[:nms_topk]
            torder, tkeep = _greedy_nms(
                boxes[top], jnp.where(keep_score[top], score[top], 0.0),
                nms_thresh,
                class_ids=None if force_suppress else cls_id[top])
            sorted_ids = jnp.concatenate([top[torder],
                                          order_full[nms_topk:]])
            kept = jnp.concatenate([
                tkeep & keep_score[top][torder],
                jnp.zeros(a - nms_topk, bool)])
        else:
            torder, keep_nms = _greedy_nms(
                boxes, jnp.where(keep_score, score, 0.0), nms_thresh,
                class_ids=None if force_suppress else cls_id)
            sorted_ids = torder
            kept = keep_nms & keep_score[torder]
        out = jnp.concatenate([
            jnp.where(kept, cls_id[sorted_ids].astype(jnp.float32),
                      -1.0)[:, None],
            score[sorted_ids][:, None], boxes[sorted_ids]], axis=1)
        return out

    # vmap materializes every image's (A, A) IoU matrix at once — at SSD
    # scale (A=8732, bs 8) that is tens of GB; lax.map runs one image's
    # matrices at a time (A^2 fp32 ~ 300 MB at SSD300 scale)
    if anc_c.shape[0] > 2048:
        import jax.lax as lax

        return lax.map(lambda args: one(*args), (cls_prob, loc_pred))
    return jax.vmap(one)(cls_prob, loc_pred)


def _multibox_detection_shape(attrs, in_shapes, aux_shapes):
    n, _, a = in_shapes[0]
    return in_shapes, [(n, a, 6)], []


# ---------------------------------------------------------------------------
# Proposal (Faster-RCNN)
# ---------------------------------------------------------------------------

def _proposal(attrs, cls_prob, bbox_pred, im_info):
    """RPN proposals: anchors + deltas, clip, NMS, top-k (ref:
    src/operator/contrib/proposal.cc).  Output (rois_kept, 5) with batch
    index 0 — single-image RPN as in the reference."""
    import jax.numpy as jnp

    scales = attrs["scales"]
    ratios = attrs["ratios"]
    stride = attrs["feature_stride"]
    pre_top = attrs["rpn_pre_nms_top_n"]
    post_top = attrs["rpn_post_nms_top_n"]
    nms_thresh = attrs["threshold"]
    min_size = attrs["rpn_min_size"]

    _, _, h, w = cls_prob.shape
    k = len(scales) * len(ratios)

    # base anchors centered on each cell (vectorized meshgrid)
    base = []
    for r in ratios:
        for s in scales:
            ww = stride * s * np.sqrt(1.0 / r)
            hh = stride * s * np.sqrt(r)
            base.append([-ww / 2, -hh / 2, ww / 2, hh / 2])
    base = jnp.asarray(base, jnp.float32)               # (K, 4)
    sy = jnp.arange(h, dtype=jnp.float32) * stride
    sx = jnp.arange(w, dtype=jnp.float32) * stride
    gy, gx = jnp.meshgrid(sy, sx, indexing="ij")
    shifts = jnp.stack([gx, gy, gx, gy], axis=-1).reshape(-1, 1, 4)
    anchors = (shifts + base[None]).reshape(-1, 4)      # (h*w*K, 4)

    # deltas (1, 4K, h, w) -> (h*w*K, 4); scores: foreground half
    deltas = bbox_pred[0].reshape(k, 4, h, w).transpose(2, 3, 0, 1)
    deltas = deltas.reshape(-1, 4)
    scores = cls_prob[0, k:].transpose(1, 2, 0).reshape(-1)

    boxes = _decode_boxes(_corner_to_center(anchors), deltas,
                          (1.0, 1.0, 1.0, 1.0))
    im_h, im_w = im_info[0, 0], im_info[0, 1]
    boxes = jnp.stack([
        jnp.clip(boxes[:, 0], 0, im_w - 1), jnp.clip(boxes[:, 1], 0, im_h - 1),
        jnp.clip(boxes[:, 2], 0, im_w - 1), jnp.clip(boxes[:, 3], 0, im_h - 1),
    ], axis=-1)
    # reference scales the min-size filter by the image's resize factor
    # (proposal.cc: rpn_min_size * im_info[2])
    scaled_min = min_size * im_info[0, 2]
    big = ((boxes[:, 2] - boxes[:, 0] + 1) >= scaled_min) & \
          ((boxes[:, 3] - boxes[:, 1] + 1) >= scaled_min)
    scores = jnp.where(big, scores, 0.0)

    # pre-NMS cut: only the rpn_pre_nms_top_n highest-scoring candidates
    # enter NMS (ref proposal.cc:295-296)
    if pre_top > 0:
        pre_rank = _rank_desc(jnp.where(scores > 0, scores, -jnp.inf))
        scores = jnp.where(pre_rank < pre_top, scores, 0.0)

    order, keep = _greedy_nms(boxes, scores, nms_thresh)
    # survivors in score order; short outputs cycle the kept boxes, the
    # reference's padding rule (proposal.cc: keep[i % out_size]) so
    # downstream ROI consumers never see uninitialized rows
    valid = keep & (scores[order] > 0)
    rank = jnp.argsort(~valid, stable=True)
    nkept = jnp.maximum(jnp.sum(valid), 1)
    pos = jnp.arange(post_top) % nkept
    top = order[rank][pos]
    out = jnp.concatenate([jnp.zeros((post_top, 1), boxes.dtype),
                           boxes[top]], axis=1)
    return out


def _proposal_shape(attrs, in_shapes, aux_shapes):
    return in_shapes, [(attrs.get("rpn_post_nms_top_n", 300), 5)], []


# ---------------------------------------------------------------------------
# fft / ifft / quantization
# ---------------------------------------------------------------------------

def _fft(attrs, data):
    """Real -> interleaved re/im complex, matching contrib/fft.cc packing:
    (..., d) -> (..., 2d) with out[..., 2i]=Re, out[..., 2i+1]=Im."""
    import jax.numpy as jnp

    spec = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    out = jnp.stack([spec.real, spec.imag], axis=-1)
    return out.reshape(*data.shape[:-1], -1).astype(jnp.float32)


def _ifft(attrs, data):
    """Interleaved re/im -> real inverse FFT: (..., 2d) -> (..., d).

    Matches contrib/ifft.cc: no 1/d normalization (the reference leaves
    scaling to the caller)."""
    import jax.numpy as jnp

    pairs = data.reshape(*data.shape[:-1], -1, 2)
    spec = pairs[..., 0] + 1j * pairs[..., 1]
    return (jnp.fft.ifft(spec, axis=-1).real *
            pairs.shape[-2]).astype(jnp.float32)


def _quantize(attrs, data, min_range, max_range):
    """Affine uint8 quantization over [min_range, max_range]
    (ref: contrib/quantize.cc)."""
    import jax.numpy as jnp

    lo = min_range.reshape(())
    hi = max_range.reshape(())
    scale = 255.0 / jnp.maximum(hi - lo, 1e-8)
    q = jnp.clip(jnp.round((data - lo) * scale), 0, 255).astype(jnp.uint8)
    return q, lo, hi


def _dequantize(attrs, data, min_range, max_range):
    import jax.numpy as jnp

    lo = min_range.reshape(())
    hi = max_range.reshape(())
    scale = jnp.maximum(hi - lo, 1e-8) / 255.0
    return data.astype(jnp.float32) * scale + lo


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------

def register_all():
    register_op(OpDef(
        "CTCLoss", simple_compute(_ctc_loss),
        num_inputs=2, arguments=["data", "label"],
        infer_shape=_ctc_shape, hint="ctcloss",
        doc="CTC negative log-likelihood; blank=0, labels 0-padded "
            "(ref: src/operator/contrib/ctc_loss.cc)."),
        aliases=("_contrib_CTCLoss", "ctc_loss"))

    register_op(OpDef(
        "MultiBoxPrior", simple_compute(_multibox_prior),
        schema=ParamSchema(
            Param("sizes", "float_tuple", default=(1.0,)),
            Param("ratios", "float_tuple", default=(1.0,)),
            Param("clip", bool, default=False),
            Param("steps", "float_tuple", default=(-1.0, -1.0)),
            Param("offsets", "float_tuple", default=(0.5, 0.5))),
        num_inputs=1, arguments=["data"],
        infer_shape=_multibox_prior_shape, hint="multiboxprior",
        doc="SSD anchor generation "
            "(ref: src/operator/contrib/multibox_prior.cc)."),
        aliases=("_contrib_MultiBoxPrior",))

    register_op(OpDef(
        "MultiBoxTarget", simple_compute(_multibox_target, num_outputs=3),
        schema=ParamSchema(
            Param("overlap_threshold", float, default=0.5),
            Param("ignore_label", float, default=-1.0),
            Param("negative_mining_ratio", float, default=-1.0),
            Param("negative_mining_thresh", float, default=0.5),
            Param("variances", "float_tuple", default=(0.1, 0.1, 0.2, 0.2))),
        num_inputs=3, num_outputs=3,
        arguments=["anchor", "label", "cls_pred"],
        outputs=["loc_target", "loc_mask", "cls_target"],
        infer_shape=_multibox_target_shape, hint="multiboxtarget",
        doc="SSD anchor-to-ground-truth matching "
            "(ref: src/operator/contrib/multibox_target.cc)."),
        aliases=("_contrib_MultiBoxTarget",))

    register_op(OpDef(
        "MultiBoxDetection", simple_compute(_multibox_detection),
        schema=ParamSchema(
            Param("threshold", float, default=0.01),
            Param("nms_threshold", float, default=0.5),
            Param("force_suppress", bool, default=False),
            Param("variances", "float_tuple", default=(0.1, 0.1, 0.2, 0.2)),
            Param("nms_topk", int, default=-1)),
        num_inputs=3, arguments=["cls_prob", "loc_pred", "anchor"],
        infer_shape=_multibox_detection_shape, hint="multiboxdetection",
        doc="SSD decode + NMS "
            "(ref: src/operator/contrib/multibox_detection.cc)."),
        aliases=("_contrib_MultiBoxDetection",))

    register_op(OpDef(
        "Proposal", simple_compute(_proposal),
        schema=ParamSchema(
            Param("scales", "float_tuple", default=(4.0, 8.0, 16.0, 32.0)),
            Param("ratios", "float_tuple", default=(0.5, 1.0, 2.0)),
            Param("feature_stride", int, default=16),
            Param("threshold", float, default=0.7),
            Param("rpn_pre_nms_top_n", int, default=6000),
            Param("rpn_post_nms_top_n", int, default=300),
            Param("rpn_min_size", int, default=16)),
        num_inputs=3, arguments=["cls_prob", "bbox_pred", "im_info"],
        infer_shape=_proposal_shape, hint="proposal",
        doc="RPN region proposals: decode anchors + NMS + top-k "
            "(ref: src/operator/contrib/proposal.cc)."),
        aliases=("_contrib_Proposal",))

    register_op(OpDef(
        "fft", simple_compute(_fft), num_inputs=1,
        infer_shape=lambda a, i, x: (i, [i[0][:-1] + (2 * i[0][-1],)], []),
        hint="fft",
        doc="FFT along the last axis, interleaved re/im output "
            "(ref: src/operator/contrib/fft.cc)."),
        aliases=("_contrib_fft",))

    register_op(OpDef(
        "ifft", simple_compute(_ifft), num_inputs=1,
        infer_shape=lambda a, i, x: (i, [i[0][:-1] + (i[0][-1] // 2,)], []),
        hint="ifft",
        doc="Inverse FFT from interleaved re/im "
            "(ref: src/operator/contrib/ifft.cc)."),
        aliases=("_contrib_ifft",))

    f32 = np.dtype(np.float32)
    register_op(OpDef(
        "quantize", simple_compute(_quantize, num_outputs=3),
        num_inputs=3, num_outputs=3,
        arguments=["data", "min_range", "max_range"],
        outputs=["output", "min_output", "max_output"],
        infer_shape=lambda a, i, x: (i, [i[0], (), ()], []),
        infer_type=lambda a, i, x: (i, [np.dtype(np.uint8), f32, f32], x),
        hint="quantize",
        doc="uint8 range quantization "
            "(ref: src/operator/contrib/quantize.cc)."),
        aliases=("_contrib_quantize",))

    register_op(OpDef(
        "dequantize", simple_compute(_dequantize),
        num_inputs=3, arguments=["data", "min_range", "max_range"],
        infer_shape=lambda a, i, x: (i, [i[0]], []),
        infer_type=lambda a, i, x: (i, [f32], x),
        hint="dequantize",
        doc="Inverse of quantize "
            "(ref: src/operator/contrib/dequantize.cc)."),
        aliases=("_contrib_dequantize",))

    def _count_sketch(attrs, data, h, s):
        """Count-sketch projection: out[b, h[i]] += s[i] * data[b, i].

        A scatter-add over hashed indices (XLA lowers `.at[].add`
        efficiently); differentiable in data, so the compact-bilinear-
        pooling use case gets its backward from the executor's vjp."""
        import jax
        import jax.numpy as jnp

        out_dim = attrs["out_dim"]
        idx = h.reshape(-1).astype(jnp.int32)
        signed = data * s.reshape(1, -1).astype(data.dtype)
        return jax.vmap(
            lambda row: jnp.zeros((out_dim,), row.dtype).at[idx].add(row)
        )(signed)

    register_op(OpDef(
        "count_sketch", simple_compute(_count_sketch),
        schema=ParamSchema(Param("out_dim", int, required=True),
                           Param("processing_batch_size", int, default=32)),
        num_inputs=3, arguments=["data", "h", "s"],
        infer_shape=lambda a, i, x: (i, [(i[0][0], a["out_dim"])], []),
        hint="count_sketch",
        doc="Count-sketch random projection "
            "(ref: src/operator/contrib/count_sketch.cc); h = hash "
            "indices (in_dim,), s = signs (in_dim,)."),
        aliases=("_contrib_count_sketch",))
