"""Neural-network layer operators.

TPU-native equivalents of the reference's legacy `OperatorProperty` layer ops
(`src/operator/*-inl.h`): Convolution (reference builds im2col+dot,
`src/operator/convolution-inl.h:90-288` — here a single
`lax.conv_general_dilated`, which XLA tiles straight onto the MXU),
FullyConnected, Pooling, BatchNorm, Dropout, activations, normalizations,
loss-output heads, sequence ops.

Loss heads (SoftmaxOutput etc.) install ``jax.custom_vjp`` so that executor
backward == plain vjp with ones head-gradient reproduces the reference's
special backward semantics (softmax-minus-label, ignore_label, grad
normalization — `src/operator/softmax_output-inl.h`).
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op, simple_compute


def _jnp():
    import jax.numpy as jnp

    return jnp


def _pair(v, n=2):
    if isinstance(v, int):
        return (v,) * n
    if len(v) == 1:
        return tuple(v) * n
    return tuple(v)


# ---------------------------------------------------------------------------
# shape inference helpers
# ---------------------------------------------------------------------------

def _fc_shape(attrs, in_shapes, aux_shapes):
    dshape = in_shapes[0]
    nh = attrs["num_hidden"]
    flat = attrs.get("flatten", True)
    if flat:
        d = 1
        for s in dshape[1:]:
            d *= s
        wshape = (nh, d)
        out = (dshape[0], nh)
    else:
        wshape = (nh, dshape[-1])
        out = tuple(dshape[:-1]) + (nh,)
    shapes = [dshape, wshape]
    if not attrs.get("no_bias", False):
        shapes.append((nh,))
    return shapes, [out], []


def _conv_shape(attrs, in_shapes, aux_shapes):
    dshape = in_shapes[0]
    nhwc = attrs.get("layout") == "NHWC"
    if nhwc:
        n, h, w, c = dshape
    else:
        n, c, h, w = dshape
    kh, kw = _pair(attrs["kernel"])
    sh, sw = _pair(attrs.get("stride", (1, 1)))
    ph, pw = _pair(attrs.get("pad", (0, 0)))
    dh, dw = _pair(attrs.get("dilate", (1, 1)))
    nf = attrs["num_filter"]
    ng = attrs.get("num_group", 1)
    wshape = (nf, c // ng, kh, kw)
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    shapes = [dshape, wshape]
    if not attrs.get("no_bias", False):
        shapes.append((nf,))
    oshape = (n, oh, ow, nf) if nhwc else (n, nf, oh, ow)
    return shapes, [oshape], []


def _deconv_pad(attrs, h, w):
    """Resolve Deconvolution pad/adj; ``target_shape`` overrides both so the
    output spatial dims come out exactly as requested (reference:
    deconvolution-inl.h InferPad — pad = ceil(d/2), adj = d%2 where
    d = stride*(in-1)+kernel-target)."""
    kh, kw = _pair(attrs["kernel"])
    sh, sw = _pair(attrs.get("stride", (1, 1)))
    target = tuple(attrs.get("target_shape", ()) or ())
    if target:
        th, tw = _pair(target)
        dh = (h - 1) * sh + kh - th
        dw = (w - 1) * sw + kw - tw
        if dh < 0 or dw < 0:
            raise ValueError(
                "Deconvolution target_shape %s is larger than the maximum "
                "output %s for input %s" % (target, ((h - 1) * sh + kh,
                                                     (w - 1) * sw + kw),
                                            (h, w)))
        return (dh + 1) // 2, (dw + 1) // 2, dh % 2, dw % 2
    ph, pw = _pair(attrs.get("pad", (0, 0)))
    ah, aw = _pair(attrs.get("adj", (0, 0)))
    return ph, pw, ah, aw


def _deconv_shape(attrs, in_shapes, aux_shapes):
    dshape = in_shapes[0]
    n, c, h, w = dshape
    kh, kw = _pair(attrs["kernel"])
    sh, sw = _pair(attrs.get("stride", (1, 1)))
    ph, pw, ah, aw = _deconv_pad(attrs, h, w)
    nf = attrs["num_filter"]
    ng = attrs.get("num_group", 1)
    wshape = (c, nf // ng, kh, kw)
    oh = (h - 1) * sh - 2 * ph + kh + ah
    ow = (w - 1) * sw - 2 * pw + kw + aw
    shapes = [dshape, wshape]
    if not attrs.get("no_bias", True):
        shapes.append((nf,))
    return shapes, [(n, nf, oh, ow)], []


def _bn_type(attrs, in_types, aux_types):
    """Output follows data; statistics (gamma/beta/mean/var + moving aux)
    stay float32 for low-precision training (the cuDNN-BN convention)."""
    f32 = np.dtype(np.float32)
    d = in_types[0] if in_types[0] is not None else f32
    return [d, f32, f32], [d, f32, f32], [f32, f32]


def _bn_shape(attrs, in_shapes, aux_shapes):
    dshape = in_shapes[0]
    axis = attrs.get("axis", 1) if len(dshape) > 1 else 0
    c = dshape[axis]
    return [dshape, (c,), (c,)], [dshape, (c,), (c,)], [(c,), (c,)]


def register_all():
    jnp = _jnp()
    import jax
    from jax import lax

    # ---------------- Activation ----------------
    def _activation(attrs, x):
        act = attrs.get("act_type", "relu")
        if act == "relu":
            return jnp.maximum(x, 0)
        if act == "sigmoid":
            return jax.nn.sigmoid(x)
        if act == "tanh":
            return jnp.tanh(x)
        if act == "softrelu":
            return jnp.logaddexp(x, 0.0)
        if act == "softsign":
            return x / (1 + jnp.abs(x))
        raise ValueError("unknown act_type %s" % act)

    register_op(OpDef("Activation", simple_compute(_activation),
                      schema=ParamSchema(Param("act_type", str, required=True,
                                               enum=("relu", "sigmoid", "tanh",
                                                     "softrelu", "softsign"))),
                      num_inputs=1, hint="activation"))

    def _leaky_relu(attrs, x, *rest):
        act = attrs.get("act_type", "leaky")
        slope = attrs.get("slope", 0.25)
        if act == "leaky" or act == "rrelu":
            return jnp.where(x > 0, x, slope * x)
        if act == "elu":
            return jnp.where(x > 0, x, slope * (jnp.exp(x) - 1))
        if act == "prelu":
            gamma = rest[0].reshape((1, -1) + (1,) * (x.ndim - 2))
            return jnp.where(x > 0, x, gamma * x)
        raise ValueError(act)

    def _lrelu_shape(attrs, in_shapes, aux_shapes):
        d = in_shapes[0]
        if attrs.get("act_type", "leaky") == "prelu":
            return [d, (d[1],)], [d], []
        return [d], [d], []

    register_op(OpDef(
        "LeakyReLU", simple_compute(_leaky_relu),
        schema=ParamSchema(
            Param("act_type", str, default="leaky"),
            Param("slope", float, default=0.25),
            Param("lower_bound", float, default=0.125),
            Param("upper_bound", float, default=0.334)),
        num_inputs=lambda a: 2 if a.get("act_type") == "prelu" else 1,
        arguments=lambda a: ["data", "gamma"] if a.get("act_type") == "prelu" else ["data"],
        infer_shape=_lrelu_shape, hint="leakyrelu"))

    def _softmax_act(attrs, x):
        if attrs.get("mode", "instance") == "channel":
            return jax.nn.softmax(x, axis=1)
        return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)

    register_op(OpDef("SoftmaxActivation", simple_compute(_softmax_act),
                      schema=ParamSchema(Param("mode", str, default="instance")),
                      num_inputs=1, hint="softmaxactivation"))

    # ---------------- FullyConnected ----------------
    def _fc(attrs, data, weight, *bias):
        if attrs.get("flatten", True):
            x = data.reshape(data.shape[0], -1)
        else:
            x = data
        out = jnp.dot(x, weight.T)
        if bias:
            out = out + bias[0]
        return out

    fc_schema = ParamSchema(Param("num_hidden", int, required=True),
                            Param("no_bias", bool, default=False),
                            Param("flatten", bool, default=True))
    register_op(OpDef(
        "FullyConnected", simple_compute(_fc), schema=fc_schema,
        num_inputs=lambda a: 2 if a.get("no_bias") else 3,
        arguments=lambda a: ["data", "weight"] if a.get("no_bias")
        else ["data", "weight", "bias"],
        infer_shape=_fc_shape, hint="fullyconnected"))

    # ---------------- Convolution ----------------
    conv_schema = ParamSchema(
        Param("kernel", "shape", required=True),
        Param("stride", "shape", default=(1, 1)),
        Param("dilate", "shape", default=(1, 1)),
        Param("pad", "shape", default=(0, 0)),
        Param("num_filter", int, required=True),
        Param("num_group", int, default=1),
        Param("workspace", int, default=1024),
        Param("no_bias", bool, default=False),
        Param("cudnn_tune", str, default=None),
        Param("cudnn_off", bool, default=False),
        Param("layout", str, default=None))

    def _conv(attrs, data, weight, *bias):
        sh, sw = _pair(attrs.get("stride", (1, 1)))
        ph, pw = _pair(attrs.get("pad", (0, 0)))
        dh, dw = _pair(attrs.get("dilate", (1, 1)))
        ng = attrs.get("num_group", 1)
        nhwc = attrs.get("layout") == "NHWC"
        # weight stays OIHW in both layouts (checkpoint compatibility);
        # NHWC activations avoid layout churn around the Pallas fused ops
        dims = ("NHWC", "OIHW", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
        out = lax.conv_general_dilated(
            data, weight, window_strides=(sh, sw),
            padding=((ph, ph), (pw, pw)),
            rhs_dilation=(dh, dw),
            dimension_numbers=dims,
            feature_group_count=ng,
            preferred_element_type=jnp.float32 if data.dtype == jnp.float32 else None)
        if bias:
            bshape = (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
            out = out + bias[0].reshape(bshape)
        return out.astype(data.dtype)

    register_op(OpDef(
        "Convolution", simple_compute(_conv), schema=conv_schema,
        num_inputs=lambda a: 2 if a.get("no_bias") else 3,
        arguments=lambda a: ["data", "weight"] if a.get("no_bias")
        else ["data", "weight", "bias"],
        infer_shape=_conv_shape, hint="convolution"))

    # ---------------- Deconvolution ----------------
    deconv_schema = ParamSchema(
        Param("kernel", "shape", required=True),
        Param("stride", "shape", default=(1, 1)),
        Param("pad", "shape", default=(0, 0)),
        Param("adj", "shape", default=(0, 0)),
        Param("target_shape", "shape", default=()),
        Param("num_filter", int, required=True),
        Param("num_group", int, default=1),
        Param("workspace", int, default=512),
        Param("no_bias", bool, default=True),
        Param("cudnn_tune", str, default=None),
        Param("cudnn_off", bool, default=False),
        Param("layout", str, default=None))

    def _deconv(attrs, data, weight, *bias):
        kh, kw = _pair(attrs["kernel"])
        sh, sw = _pair(attrs.get("stride", (1, 1)))
        ph, pw, ah, aw = _deconv_pad(attrs, data.shape[2], data.shape[3])
        ng = attrs.get("num_group", 1)
        # deconv = gradient of conv: dilate lhs by stride, full-minus-pad padding,
        # kernel flipped spatially and IO-transposed (weight is (C, F/g, kh, kw))
        w = jnp.flip(weight, axis=(-2, -1))
        if ng > 1:
            c, fpg = w.shape[0], w.shape[1]
            w = w.reshape(ng, c // ng, fpg, kh, kw)
            w = jnp.moveaxis(w, 2, 1).reshape(ng * fpg, c // ng, kh, kw)
        else:
            w = jnp.swapaxes(w, 0, 1)
        out = lax.conv_general_dilated(
            data, w, window_strides=(1, 1),
            padding=((kh - 1 - ph, kh - 1 - ph + ah), (kw - 1 - pw, kw - 1 - pw + aw)),
            lhs_dilation=(sh, sw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=ng)
        if bias:
            out = out + bias[0].reshape(1, -1, 1, 1)
        return out.astype(data.dtype)

    register_op(OpDef(
        "Deconvolution", simple_compute(_deconv), schema=deconv_schema,
        num_inputs=lambda a: 2 if a.get("no_bias", True) else 3,
        arguments=lambda a: ["data", "weight"] if a.get("no_bias", True)
        else ["data", "weight", "bias"],
        infer_shape=_deconv_shape, hint="deconvolution"))

    # ---------------- Pooling ----------------
    pool_schema = ParamSchema(
        Param("kernel", "shape", required=True),
        Param("pool_type", str, default="max", enum=("max", "avg", "sum")),
        Param("global_pool", bool, default=False),
        Param("pooling_convention", str, default="valid"),
        Param("stride", "shape", default=(1, 1)),
        Param("pad", "shape", default=(0, 0)),
        Param("layout", str, default=None,
              doc="NCHW (default) or NHWC (match Convolution layout)"))

    def _pool_geometry(attrs, h, w):
        kh, kw = _pair(attrs["kernel"])
        sh, sw = _pair(attrs.get("stride", (1, 1)))
        ph, pw = _pair(attrs.get("pad", (0, 0)))
        if attrs.get("global_pool", False):
            return (h, w), (1, 1), (0, 0, 0, 0), (1, 1)
        if attrs.get("pooling_convention", "valid") == "full":
            oh = int(np.ceil((h + 2 * ph - kh) / sh)) + 1
            ow = int(np.ceil((w + 2 * pw - kw) / sw)) + 1
        else:
            oh = (h + 2 * ph - kh) // sh + 1
            ow = (w + 2 * pw - kw) // sw + 1
        eh = max(0, (oh - 1) * sh + kh - h - 2 * ph)
        ew = max(0, (ow - 1) * sw + kw - w - 2 * pw)
        return (kh, kw), (sh, sw), (ph, ph + eh, pw, pw + ew), (oh, ow)

    def _pooling(attrs, x):
        nhwc = attrs.get("layout") == "NHWC"
        if nhwc:
            n, h, w, c = x.shape
        else:
            n, c, h, w = x.shape
        (kh, kw), (sh, sw), (plo_h, phi_h, plo_w, phi_w), _ = _pool_geometry(attrs, h, w)
        ptype = attrs.get("pool_type", "max")
        if nhwc:
            pads = ((0, 0), (plo_h, phi_h), (plo_w, phi_w), (0, 0))
            window = (1, kh, kw, 1)
            strides = (1, sh, sw, 1)
        else:
            pads = ((0, 0), (0, 0), (plo_h, phi_h), (plo_w, phi_w))
            window = (1, 1, kh, kw)
            strides = (1, 1, sh, sw)
        if ptype == "max":
            init = -np.inf if jnp.issubdtype(x.dtype, jnp.floating) \
                else np.iinfo(np.dtype(x.dtype)).min
            return lax.reduce_window(x, init, lax.max, window, strides, pads)
        out = lax.reduce_window(x, 0.0 if jnp.issubdtype(x.dtype, jnp.floating)
                                else 0, lax.add, window, strides, pads)
        if ptype == "avg":
            out = out / (kh * kw)
        return out

    register_op(OpDef("Pooling", simple_compute(_pooling), schema=pool_schema,
                      num_inputs=1, hint="pooling"))

    # ---------------- BatchNorm ----------------
    bn_schema = ParamSchema(
        Param("eps", float, default=1e-3),
        Param("momentum", float, default=0.9),
        Param("fix_gamma", bool, default=True),
        Param("use_global_stats", bool, default=False),
        Param("output_mean_var", bool, default=False),
        Param("axis", int, default=1,
              doc="channel axis (1 = NCHW default; -1/3 for NHWC data, "
                  "e.g. downstream of Convolution(layout='NHWC'))"))

    def _bn_train_core(eps, caxis):
        """Training-mode BN as an explicit custom_vjp.

        The autodiff-derived backward of the naive formulation saves the
        float32-upcast activation as a residual — at bf16 compute that
        doubles BN's HBM traffic, and this op is memory-bound.  Here the
        residuals are the *compute-dtype* input plus the (C,)-sized fp32
        statistics; both passes do elementwise math in the compute dtype
        with only the channel reductions in fp32.
        """

        def stats(x, center):
            # mean and variance in ONE fused reduction pass: jnp.var's
            # two-pass formulation costs an extra full read of x per BN —
            # measured 9% of the whole ResNet-50 step on the bench chip
            # (benchmarks/ROOFLINE.md).  The shifted-data formulation
            # var = E[(x-c)^2] - (mean-c)^2 centers on c = moving_mean: a
            # CONSTANT, so the subtraction and both reductions fuse into
            # x's producer (a data-dependent center — e.g. a subsample
            # mean — would serialize a second pass over x, giving the
            # two-pass cost back).  Once the moving mean has warmed toward
            # the batch mean the fp32 sums stay O(var).  The cold-start
            # hole (moving_mean at its zero init + |mean| >> std ->
            # catastrophic cancellation, advisor round-4) is closed by a
            # DETECTED fallback: when the recovered variance is within
            # fp32 cancellation noise of the shifted mean square, a
            # lax.cond pays one corrective pass with the exact batch mean
            # as center.  The predicate only fires during those early
            # pathological steps, so the steady-state cost is the fused
            # single pass.
            red = tuple(i for i in range(x.ndim) if i != caxis)
            bshape = tuple(x.shape[caxis] if i == caxis else 1
                           for i in range(x.ndim))
            if not red:
                z = jnp.zeros(x.shape[caxis], jnp.float32)
                return x.astype(jnp.float32).reshape(-1), z
            xc = x.astype(jnp.float32) - center.reshape(bshape)
            mc = jnp.mean(xc, axis=red)
            var_fast = jnp.maximum(jnp.mean(jnp.square(xc), axis=red)
                                   - jnp.square(mc), 0.0)
            mean = mc + center
            # fp32 cancellation noise is ~1e-7 * (mean-c)^2; refine when it
            # could exceed ~1% of the recovered variance AND the variance
            # it may have destroyed matters relative to eps (noise below
            # eps can't move rsqrt(var + eps) meaningfully).  The second
            # term also retires the guard for legitimately-zero-variance
            # channels (dead ReLU features, constant pads): as the moving
            # mean converges onto them, mc^2 falls below eps/1e-7 and the
            # refine stops firing instead of paying the second pass on
            # every step forever.
            mc2 = jnp.square(mc)
            bad = jnp.any((var_fast <= 1e-5 * mc2) & (1e-7 * mc2 > eps))

            def refine(_):
                m = jax.lax.stop_gradient(mean).reshape(bshape)
                return jnp.mean(jnp.square(x.astype(jnp.float32) - m),
                                axis=red)

            var = jax.lax.cond(bad, refine, lambda _: var_fast, None)
            return mean, var

        def apply(x, gamma, beta, mean, inv):
            bshape = tuple(x.shape[caxis] if i == caxis else 1
                           for i in range(x.ndim))
            scale = (inv * gamma.astype(jnp.float32)).astype(x.dtype)
            shift = (beta.astype(jnp.float32)
                     - mean * inv * gamma.astype(jnp.float32)).astype(x.dtype)
            return x * scale.reshape(bshape) + shift.reshape(bshape)

        @jax.custom_vjp
        def bn(x, gamma, beta, center):
            mean, var = stats(x, center)
            inv = jax.lax.rsqrt(var + eps)
            return apply(x, gamma, beta, mean, inv), mean, var

        def bn_fwd(x, gamma, beta, center):
            mean, var = stats(x, center)
            inv = jax.lax.rsqrt(var + eps)
            return (apply(x, gamma, beta, mean, inv), mean, var), \
                (x, gamma, mean, inv)

        def bn_bwd(res, cts):
            x, gamma, mean, inv = res
            dy, dmean_ct, dvar_ct = cts
            red = tuple(i for i in range(x.ndim) if i != caxis)
            bshape = tuple(x.shape[caxis] if i == caxis else 1
                           for i in range(x.ndim))
            n = 1
            for i in red:
                n *= x.shape[i]
            xmu = x.astype(jnp.float32) - mean.reshape(bshape)
            xhat = xmu * inv.reshape(bshape)
            dy32 = dy.astype(jnp.float32)
            dbeta = jnp.sum(dy32, axis=red)
            dgamma = jnp.sum(dy32 * xhat, axis=red)
            g32 = gamma.astype(jnp.float32)
            dx = (inv * g32).reshape(bshape) \
                * (dy32 - (dbeta / n).reshape(bshape)
                   - xhat * (dgamma / n).reshape(bshape))
            # the mean/var outputs are separately consumable (output_mean_var,
            # user head_grads); fold their cotangents in as well
            dx = dx + (dmean_ct / n).reshape(bshape) \
                + (dvar_ct * 2.0 / n).reshape(bshape) * xmu
            return dx.astype(x.dtype), dgamma.astype(gamma.dtype), \
                dbeta.astype(gamma.dtype), jnp.zeros_like(mean)

        bn.defvjp(bn_fwd, bn_bwd)
        return bn

    def _batchnorm(attrs, inputs, aux, octx):
        data, gamma, beta = inputs
        moving_mean, moving_var = aux
        eps = attrs.get("eps", 1e-3)
        momentum = attrs.get("momentum", 0.9)
        caxis = attrs.get("axis", 1) if data.ndim > 1 else 0
        if caxis < 0:
            caxis += data.ndim
        bshape = tuple(data.shape[caxis] if i == caxis else 1 for i in range(data.ndim))
        if attrs.get("fix_gamma", True):
            gamma = jax.lax.stop_gradient(jnp.ones_like(gamma))
        use_global = attrs.get("use_global_stats", False) or not octx.is_train
        if use_global:
            mean, var = moving_mean, moving_var
            new_mm, new_mv = moving_mean, moving_var
            inv = jax.lax.rsqrt(var + eps)
            scale = (inv * gamma.astype(jnp.float32)).astype(data.dtype)
            shift = (beta.astype(jnp.float32)
                     - mean * inv * gamma.astype(jnp.float32)).astype(data.dtype)
            out = data * scale.reshape(bshape) + shift.reshape(bshape)
        else:
            out, mean, var = _bn_train_core(eps, caxis)(
                data, gamma, beta,
                jax.lax.stop_gradient(moving_mean.astype(jnp.float32)))
            new_mm = momentum * moving_mean + (1 - momentum) * jax.lax.stop_gradient(mean)
            new_mv = momentum * moving_var + (1 - momentum) * jax.lax.stop_gradient(var)
        return [out, mean, var], [new_mm, new_mv]

    register_op(OpDef(
        "BatchNorm", _batchnorm, schema=bn_schema,
        num_inputs=3, num_outputs=3,
        num_visible_outputs=lambda a: 3 if a.get("output_mean_var") else 1,
        arguments=["data", "gamma", "beta"],
        outputs=["output", "mean", "var"],
        aux=["moving_mean", "moving_var"],
        infer_shape=_bn_shape, infer_type=_bn_type, needs_train=True,
        hint="batchnorm"))

    # ---------------- Dropout ----------------
    def _dropout(attrs, inputs, aux, octx):
        (x,) = inputs
        p = attrs.get("p", 0.5)
        if not octx.is_train or p <= 0.0:
            return [x, jnp.ones_like(x)], []
        keep = 1.0 - p
        mask = jax.random.bernoulli(octx.rng, keep, x.shape).astype(x.dtype) / keep
        return [x * mask, mask], []

    register_op(OpDef(
        "Dropout", _dropout,
        schema=ParamSchema(Param("p", float, default=0.5),
                           Param("mode", str, default="training")),
        num_inputs=1, num_outputs=2, num_visible_outputs=1,
        outputs=["output", "mask"],
        needs_rng=True, needs_train=True, hint="dropout"))

    # ---------------- LRN ----------------
    def _lrn(attrs, x):
        n = attrs["nsize"]
        alpha = attrs.get("alpha", 1e-4)
        beta = attrs.get("beta", 0.75)
        knorm = attrs.get("knorm", 2.0)
        sq = jnp.square(x)
        half = n // 2
        padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
        win = sum(padded[:, i:i + x.shape[1]] for i in range(n))
        return x / jnp.power(knorm + (alpha / n) * win, beta)

    register_op(OpDef("LRN", simple_compute(_lrn),
                      schema=ParamSchema(Param("nsize", int, required=True),
                                         Param("alpha", float, default=1e-4),
                                         Param("beta", float, default=0.75),
                                         Param("knorm", float, default=2.0)),
                      num_inputs=1, hint="lrn"))

    # ---------------- InstanceNorm ----------------
    def _instance_norm(attrs, x, gamma, beta):
        eps = attrs.get("eps", 1e-3)
        red = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=red, keepdims=True)
        var = jnp.var(x, axis=red, keepdims=True)
        g = gamma.reshape((1, -1) + (1,) * (x.ndim - 2))
        b = beta.reshape((1, -1) + (1,) * (x.ndim - 2))
        return (x - mean) / jnp.sqrt(var + eps) * g + b

    def _in_shape(attrs, in_shapes, aux_shapes):
        d = in_shapes[0]
        return [d, (d[1],), (d[1],)], [d], []

    register_op(OpDef("InstanceNorm", simple_compute(_instance_norm),
                      schema=ParamSchema(Param("eps", float, default=1e-3)),
                      num_inputs=3, arguments=["data", "gamma", "beta"],
                      infer_shape=_in_shape, hint="instancenorm"))

    # ---------------- L2Normalization ----------------
    def _l2norm(attrs, x):
        eps = attrs.get("eps", 1e-10)
        mode = attrs.get("mode", "instance")
        if mode == "instance":
            red, keep = tuple(range(1, x.ndim)), True
        elif mode == "channel":
            red, keep = (1,), True
        else:  # spatial
            red, keep = tuple(range(2, x.ndim)), True
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=keep) + eps)
        return x / norm

    register_op(OpDef("L2Normalization", simple_compute(_l2norm),
                      schema=ParamSchema(Param("eps", float, default=1e-10),
                                         Param("mode", str, default="instance")),
                      num_inputs=1, hint="l2normalization"))

    # ---------------- loss heads ----------------
    _register_loss_heads()

    # ---------------- Pad ----------------
    def _pad(attrs, x):
        pw = attrs["pad_width"]
        pads = [(pw[2 * i], pw[2 * i + 1]) for i in range(x.ndim)]
        mode = attrs.get("mode", "constant")
        if mode == "constant":
            return jnp.pad(x, pads, constant_values=attrs.get("constant_value", 0.0))
        return jnp.pad(x, pads, mode="edge" if mode == "edge" else "reflect")

    register_op(OpDef("Pad", simple_compute(_pad),
                      schema=ParamSchema(Param("mode", str, default="constant"),
                                         Param("pad_width", "shape", required=True),
                                         Param("constant_value", float, default=0.0)),
                      num_inputs=1, hint="pad"),
                aliases=["pad"])

    # ---------------- UpSampling ----------------
    def _upsampling(attrs, *xs):
        scale = attrs["scale"]
        stype = attrs.get("sample_type", "nearest")
        x = xs[0]
        if stype == "nearest":
            return jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        # bilinear: resize (the learnable-deconv variant is Deconvolution-backed)
        import jax.image

        n, c, h, w = x.shape
        return jax.image.resize(x, (n, c, h * scale, w * scale), method="bilinear")

    register_op(OpDef("UpSampling", simple_compute(_upsampling),
                      schema=ParamSchema(Param("scale", int, required=True),
                                         Param("num_filter", int, default=0),
                                         Param("sample_type", str, default="nearest"),
                                         Param("multi_input_mode", str, default="concat"),
                                         Param("num_args", int, default=1),
                                         Param("workspace", int, default=512)),
                      num_inputs=lambda a: a.get("num_args", 1),
                      key_var_num_args="num_args", hint="upsampling"))

    # ---------------- Sequence ops (axis 0 = time, TNC) ----------------
    def _seq_last(attrs, data, *seq_len):
        if attrs.get("use_sequence_length", False) and seq_len:
            idx = (seq_len[0] - 1).astype(jnp.int32)
            return data[idx, jnp.arange(data.shape[1])]
        return data[-1]

    seq_schema = ParamSchema(Param("use_sequence_length", bool, default=False),
                             Param("value", float, default=0.0),
                             Param("axis", int, default=0))

    def _seq_args(a):
        return ["data", "sequence_length"] if a.get("use_sequence_length") else ["data"]

    def _seq_n(a):
        return 2 if a.get("use_sequence_length") else 1

    def _seqlast_shape(attrs, in_shapes, aux_shapes):
        d = in_shapes[0]
        out = tuple(d[1:])
        if attrs.get("use_sequence_length"):
            return [d, (d[1],)], [out], []
        return [d], [out], []

    register_op(OpDef("SequenceLast", simple_compute(_seq_last), schema=seq_schema,
                      num_inputs=_seq_n, arguments=_seq_args,
                      infer_shape=_seqlast_shape, hint="sequencelast"))

    def _seq_mask(attrs, data, *seq_len):
        if not attrs.get("use_sequence_length", False) or not seq_len:
            return data
        T = data.shape[0]
        mask = jnp.arange(T)[:, None] < seq_len[0][None, :].astype(jnp.int32)
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
        return jnp.where(mask, data, attrs.get("value", 0.0))

    register_op(OpDef("SequenceMask", simple_compute(_seq_mask), schema=seq_schema,
                      num_inputs=_seq_n, arguments=_seq_args, hint="sequencemask"))

    def _seq_reverse(attrs, data, *seq_len):
        if attrs.get("use_sequence_length", False) and seq_len:
            T = data.shape[0]
            sl = seq_len[0].astype(jnp.int32)
            t = jnp.arange(T)[:, None]
            src = jnp.where(t < sl[None, :], sl[None, :] - 1 - t, t)
            return jnp.take_along_axis(
                data, src.reshape(src.shape + (1,) * (data.ndim - 2)), axis=0)
        return jnp.flip(data, axis=0)

    register_op(OpDef("SequenceReverse", simple_compute(_seq_reverse), schema=seq_schema,
                      num_inputs=_seq_n, arguments=_seq_args, hint="sequencereverse"))

    # IdentityAttachKLSparseReg: forward identity (+ sparsity KL penalty on grad)
    register_op(OpDef("IdentityAttachKLSparseReg",
                      simple_compute(lambda attrs, x: x + 0),
                      schema=ParamSchema(Param("sparseness_target", float, default=0.1),
                                         Param("penalty", float, default=0.001),
                                         Param("momentum", float, default=0.9)),
                      num_inputs=1, hint="identityattachklsparsereg"))


def _register_loss_heads():
    import jax
    import jax.numpy as jnp

    # ---- SoftmaxOutput ----
    sm_schema = ParamSchema(
        Param("grad_scale", float, default=1.0),
        Param("ignore_label", float, default=-1.0),
        Param("multi_output", bool, default=False),
        Param("use_ignore", bool, default=False),
        Param("preserve_shape", bool, default=False),
        Param("normalization", str, default="null"),
        Param("out_grad", bool, default=False))

    def _softmax_output(attrs, inputs, aux, octx):
        data, label = inputs
        multi = attrs.get("multi_output", False)
        preserve = attrs.get("preserve_shape", False)

        def fwd_fn(d):
            # normalize in fp32: exp/sum in bf16 would be the one numerically
            # fragile spot in an otherwise-bf16 graph
            d32 = d.astype(jnp.float32)
            if multi:
                out = jax.nn.softmax(d32, axis=1)
            elif preserve:
                out = jax.nn.softmax(d32, axis=-1)
            else:
                out = jax.nn.softmax(d32.reshape(d.shape[0], -1),
                                     axis=-1).reshape(d.shape)
            return out.astype(d.dtype)

        @jax.custom_vjp
        def head(d, l):
            return fwd_fn(d)

        def head_fwd(d, l):
            out = fwd_fn(d)
            return out, (out, l)

        def head_bwd(res, g):
            out, l = res
            scale = attrs.get("grad_scale", 1.0)
            norm = attrs.get("normalization", "null")
            use_ignore = attrs.get("use_ignore", False)
            ignore = attrs.get("ignore_label", -1.0)
            if multi:
                # data (N, C, ...); label (N, ...)
                li = l.astype(jnp.int32)
                onehot = jax.nn.one_hot(li, out.shape[1], dtype=out.dtype, axis=1)
                grad = out - onehot
                mask = (l != ignore) if use_ignore else jnp.ones(l.shape, bool)
                grad = grad * mask[:, None].astype(out.dtype) if use_ignore else grad
                valid = jnp.sum(mask.astype(out.dtype))
            else:
                flat = out.reshape(out.shape[0], -1) if not preserve else out
                lflat = l.reshape(flat.shape[:-1]).astype(jnp.int32)
                onehot = jax.nn.one_hot(lflat, flat.shape[-1], dtype=out.dtype)
                grad = flat - onehot
                mask = (l.reshape(lflat.shape) != ignore) if use_ignore \
                    else jnp.ones(lflat.shape, bool)
                if use_ignore:
                    grad = grad * mask[..., None].astype(out.dtype)
                valid = jnp.sum(mask.astype(out.dtype))
                grad = grad.reshape(out.shape)
            if norm == "batch":
                grad = grad / out.shape[0]
            elif norm == "valid":
                grad = grad / jnp.maximum(valid, 1.0)
            return (grad * scale, jnp.zeros_like(l))

        head.defvjp(head_fwd, head_bwd)
        return [head(data, label)], []

    def _softmax_out_shape(attrs, in_shapes, aux_shapes):
        d = in_shapes[0]
        if attrs.get("multi_output", False):
            lshape = (d[0],) + tuple(d[2:])
        elif attrs.get("preserve_shape", False):
            lshape = tuple(d[:-1])
        else:
            lshape = (d[0],)
        return [d, lshape], [d], []

    register_op(OpDef("SoftmaxOutput", _softmax_output, schema=sm_schema,
                      num_inputs=2, arguments=["data", "label"],
                      infer_shape=_softmax_out_shape, hint="softmaxoutput"),
                aliases=["Softmax"])

    # ---- regression heads ----
    reg_schema = ParamSchema(Param("grad_scale", float, default=1.0))

    def _make_regression(name, fwd, grad):
        def fcompute(attrs, inputs, aux, octx):
            data, label = inputs
            scale = attrs.get("grad_scale", 1.0)

            @jax.custom_vjp
            def head(d, l):
                return fwd(d)

            def head_fwd(d, l):
                return fwd(d), (fwd(d), l)

            def head_bwd(res, g):
                out, l = res
                n = 1
                for s in out.shape[1:]:
                    n *= s
                return (grad(out, l.reshape(out.shape)) * scale / n,
                        jnp.zeros_like(l))

            head.defvjp(head_fwd, head_bwd)
            return [head(data, label)], []

        def _reg_shape(attrs, in_shapes, aux_shapes):
            d = in_shapes[0]
            return [d, d], [d], []

        register_op(OpDef(name, fcompute, schema=reg_schema, num_inputs=2,
                          arguments=["data", "label"], infer_shape=_reg_shape,
                          hint=name.lower()))

    _make_regression("LinearRegressionOutput", lambda d: d, lambda o, l: o - l)
    _make_regression("LogisticRegressionOutput", lambda d: jax.nn.sigmoid(d),
                     lambda o, l: o - l)
    _make_regression("MAERegressionOutput", lambda d: d,
                     lambda o, l: jnp.sign(o - l))

    # ---- MakeLoss ----
    ml_schema = ParamSchema(Param("grad_scale", float, default=1.0),
                            Param("valid_thresh", float, default=0.0),
                            Param("normalization", str, default="null"))

    def _make_loss(attrs, inputs, aux, octx):
        (data,) = inputs
        scale = attrs.get("grad_scale", 1.0)
        norm = attrs.get("normalization", "null")

        @jax.custom_vjp
        def head(d):
            return d

        def head_fwd(d):
            return d, d

        def head_bwd(d, g):
            grad = jnp.full_like(d, scale)
            if norm == "batch":
                grad = grad / d.shape[0]
            elif norm == "valid":
                valid = jnp.sum((d > attrs.get("valid_thresh", 0.0)).astype(d.dtype))
                grad = grad / jnp.maximum(valid, 1.0)
            return (grad,)

        head.defvjp(head_fwd, head_bwd)
        return [head(data)], []

    register_op(OpDef("MakeLoss", _make_loss, schema=ml_schema, num_inputs=1,
                      hint="makeloss"),
                aliases=["make_loss"])

    # ---- SVMOutput ----
    svm_schema = ParamSchema(Param("margin", float, default=1.0),
                             Param("regularization_coefficient", float, default=1.0),
                             Param("use_linear", bool, default=False))

    def _svm_output(attrs, inputs, aux, octx):
        data, label = inputs
        margin = attrs.get("margin", 1.0)
        reg = attrs.get("regularization_coefficient", 1.0)
        linear = attrs.get("use_linear", False)

        @jax.custom_vjp
        def head(d, l):
            return d

        def head_fwd(d, l):
            return d, (d, l)

        def head_bwd(res, g):
            d, l = res
            li = l.astype(jnp.int32)
            onehot = jax.nn.one_hot(li, d.shape[1], dtype=d.dtype)
            score_y = jnp.take_along_axis(d, li[:, None], axis=1)
            if linear:  # L1-SVM subgradient
                viol = ((margin - (2 * onehot - 1) * d) > 0).astype(d.dtype)
                grad = -(2 * onehot - 1) * viol * reg
            else:  # L2-SVM
                m = jnp.maximum(0.0, margin - (2 * onehot - 1) * d)
                grad = -2.0 * (2 * onehot - 1) * m * reg
            del score_y
            return (grad, jnp.zeros_like(l))

        head.defvjp(head_fwd, head_bwd)
        return [head(data, label)], []

    def _svm_shape(attrs, in_shapes, aux_shapes):
        d = in_shapes[0]
        return [d, (d[0],)], [d], []

    register_op(OpDef("SVMOutput", _svm_output, schema=svm_schema, num_inputs=2,
                      arguments=["data", "label"], infer_shape=_svm_shape,
                      hint="svmoutput"))
