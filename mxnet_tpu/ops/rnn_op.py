"""Fused multi-layer RNN operator (LSTM/GRU/vanilla, bidirectional).

Reference: the `RNN` op is cuDNN/MIOpen-only (`src/operator/cudnn_rnn-inl.h:
22-563`; the CPU body is an empty TODO, `rnn-inl.h:106-135`).  TPU-native:
`lax.scan` over time per layer/direction — XLA unrolls the cell matmuls onto
the MXU; there is no vendor-library escape hatch and none is needed.

Flat parameter layout (our packing convention, documented for
FusedRNNCell.pack/unpack_weights):
  for layer in 0..L-1: for direction in 0..D-1:
      W[gates*H, in_size]   (i2h)
      R[gates*H, H]         (h2h)
  then for layer: for direction:
      bW[gates*H]           (i2h bias)
      bR[gates*H]           (h2h bias)
with in_size = input_dim at layer 0, else H*D.  Gate order: LSTM i,f,g,o;
GRU r,z,n (cuDNN order, same as the reference's MIOpen path).

Inputs: data (T,N,I), parameters (flat,), state (L*D,N,H)[, state_cell].
Outputs: out (T,N,H*D)[, state_out[, statecell_out]] with only `out`
visible unless state_outputs=True.
"""
from __future__ import annotations

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op


def _gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def rnn_param_size(num_layers, state_size, mode, bidirectional, input_size):
    """Total flat parameter count (used by shape inference + FusedRNNCell)."""
    g = _gates(mode)
    d = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else state_size * d
        size += d * g * state_size * (in_size + state_size)  # W + R
    size += num_layers * d * 2 * g * state_size              # biases
    return size


def _layout(num_layers, state_size, mode, bidirectional, input_size):
    """Yield (name, offset, shape) for every packed tensor, in pack order."""
    g = _gates(mode)
    d = 2 if bidirectional else 1
    off = 0
    out = []
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else state_size * d
        for dr in range(d):
            out.append(("l%d_d%d_i2h_weight" % (layer, dr), off,
                        (g * state_size, in_size)))
            off += g * state_size * in_size
            out.append(("l%d_d%d_h2h_weight" % (layer, dr), off,
                        (g * state_size, state_size)))
            off += g * state_size * state_size
    for layer in range(num_layers):
        for dr in range(d):
            out.append(("l%d_d%d_i2h_bias" % (layer, dr), off, (g * state_size,)))
            off += g * state_size
            out.append(("l%d_d%d_h2h_bias" % (layer, dr), off, (g * state_size,)))
            off += g * state_size
    return out


def _cell_step(mode, H):
    import jax
    import jax.numpy as jnp

    if mode == "lstm":
        def step(carry, gates_x, R, bR):
            h, c = carry
            gates = gates_x + jnp.dot(h, R.T) + bR
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new
    elif mode == "gru":
        def step(carry, gates_x, R, bR):
            (h,) = carry
            gh = jnp.dot(h, R.T) + bR
            rx, zx, nx = jnp.split(gates_x, 3, axis=-1)
            rh, zh, nh = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(rx + rh)
            z = jax.nn.sigmoid(zx + zh)
            n = jnp.tanh(nx + r * nh)
            h_new = (1 - z) * n + z * h
            return (h_new,), h_new
    else:
        act = jnp.tanh if mode == "rnn_tanh" else (lambda x: jnp.maximum(x, 0))

        def step(carry, gates_x, R, bR):
            (h,) = carry
            h_new = act(gates_x + jnp.dot(h, R.T) + bR)
            return (h_new,), h_new
    return step


def register_all():
    import jax
    import jax.numpy as jnp
    from jax import lax

    schema = ParamSchema(
        Param("state_size", int, required=True),
        Param("num_layers", int, required=True),
        Param("bidirectional", bool, default=False),
        Param("mode", str, required=True,
              enum=("rnn_relu", "rnn_tanh", "lstm", "gru")),
        Param("p", float, default=0.0),
        Param("state_outputs", bool, default=False),
    )

    def _num_inputs(attrs):
        return 4 if attrs["mode"] == "lstm" else 3

    def _arguments(attrs):
        if attrs["mode"] == "lstm":
            return ["data", "parameters", "state", "state_cell"]
        return ["data", "parameters", "state"]

    def _num_outputs(attrs):
        if attrs["mode"] == "lstm":
            return 3
        return 2

    def _num_visible(attrs):
        if not attrs.get("state_outputs", False):
            return 1
        return _num_outputs(attrs)

    def _outputs(attrs):
        if attrs["mode"] == "lstm":
            return ["output", "state", "state_cell"]
        return ["output", "state"]

    def _infer_shape(attrs, in_shapes, aux_shapes):
        T, N, I = in_shapes[0]
        H = attrs["state_size"]
        L = attrs["num_layers"]
        D = 2 if attrs.get("bidirectional", False) else 1
        psize = rnn_param_size(L, H, attrs["mode"], D == 2, I)
        state_shape = (L * D, N, H)
        ins = [in_shapes[0], (psize,), state_shape]
        outs = [(T, N, H * D), state_shape]
        if attrs["mode"] == "lstm":
            ins.append(state_shape)
            outs.append(state_shape)
        return ins, outs, []

    def _rnn(attrs, inputs, aux, octx):
        data, params = inputs[0], inputs[1]
        state = inputs[2]
        mode = attrs["mode"]
        H = attrs["state_size"]
        L = attrs["num_layers"]
        D = 2 if attrs.get("bidirectional", False) else 1
        p_drop = attrs.get("p", 0.0)
        T, N, I = data.shape
        state_cell = inputs[3] if mode == "lstm" else None

        layout = {name: (off, shape)
                  for name, off, shape in _layout(L, H, mode, D == 2, I)}

        def get(name):
            off, shape = layout[name]
            n = 1
            for s in shape:
                n *= s
            return params[off:off + n].reshape(shape)

        step = _cell_step(mode, H)
        x = data
        out_states_h = []
        out_states_c = []
        for layer in range(L):
            dir_outs = []
            for dr in range(D):
                W = get("l%d_d%d_i2h_weight" % (layer, dr))
                R = get("l%d_d%d_h2h_weight" % (layer, dr))
                bW = get("l%d_d%d_i2h_bias" % (layer, dr))
                bR = get("l%d_d%d_h2h_bias" % (layer, dr))
                h0 = state[layer * D + dr]
                if mode == "lstm":
                    c0 = state_cell[layer * D + dr]
                    carry0 = (h0, c0)
                else:
                    carry0 = (h0,)
                seq = x if dr == 0 else jnp.flip(x, axis=0)
                # hoist the input matmul out of the scan: one big MXU matmul
                gates_x = jnp.einsum("tni,gi->tng", seq, W) + bW

                def scan_fn(carry, gx, R=R, bR=bR):
                    new_carry, h = step(carry, gx, R, bR)
                    return new_carry, h

                final_carry, hs = lax.scan(scan_fn, carry0, gates_x)
                if dr == 1:
                    hs = jnp.flip(hs, axis=0)
                dir_outs.append(hs)
                out_states_h.append(final_carry[0])
                if mode == "lstm":
                    out_states_c.append(final_carry[1])
            x = dir_outs[0] if D == 1 else jnp.concatenate(dir_outs, axis=-1)
            if p_drop > 0.0 and octx.is_train and layer < L - 1:
                keep = 1.0 - p_drop
                mask = jax.random.bernoulli(
                    jax.random.fold_in(octx.rng, layer), keep, x.shape)
                x = x * mask.astype(x.dtype) / keep

        outs = [x, jnp.stack(out_states_h)]
        if mode == "lstm":
            outs.append(jnp.stack(out_states_c))
        return outs, []

    # internal: zero initial state whose batch dim follows a reference input
    # (the reference's begin_state(func=sym.zeros) analog, shape-safe under
    # bucketing where batch is only known at bind)
    bs_schema = ParamSchema(Param("shape", "shape", required=True),
                            Param("batch_axis", int, default=0))

    def _begin_state(attrs, ref):
        shape = tuple(attrs["shape"])
        n = ref.shape[attrs.get("batch_axis", 0)]
        shape = tuple(n if s == 0 else s for s in shape)
        return jnp.zeros(shape, dtype=ref.dtype)

    def _begin_state_shape(attrs, in_shapes, aux_shapes):
        ref = in_shapes[0]
        shape = tuple(attrs["shape"])
        n = ref[attrs.get("batch_axis", 0)]
        return [ref], [tuple(n if s == 0 else s for s in shape)], []

    from ..registry import simple_compute

    register_op(OpDef("_rnn_begin_state", simple_compute(_begin_state),
                      schema=bs_schema, num_inputs=1,
                      infer_shape=_begin_state_shape, hint="begin_state",
                      visible=False))

    register_op(OpDef("RNN", _rnn, schema=schema,
                      num_inputs=_num_inputs, num_outputs=_num_outputs,
                      num_visible_outputs=_num_visible,
                      arguments=_arguments, outputs=_outputs,
                      infer_shape=_infer_shape,
                      needs_rng=True, needs_train=True, hint="rnn"))
