"""Tensor ops: reductions, matrix/shape manipulation, indexing, init,
ordering, sampling, control flow.

Covers reference `src/operator/tensor/`: broadcast_reduce_op_value.cc,
matrix_op.cc, indexing_op.cc, init_op.cc, ordering_op.cc, sample_op.cc,
control_flow_op.cc, loss_binary_op.cc and `src/operator/nn/softmax.cc`.
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..base import MXNetError
from ..registry import OpDef, register_op, simple_compute


def _jnp():
    import jax.numpy as jnp

    return jnp


def _norm_axis(attrs, ndim):
    """MXNet reduce-axis semantics: axis=() → all axes; exclude inverts."""
    axis = attrs.get("axis", ())
    if axis is None or axis == ():
        axes = tuple(range(ndim))
    elif isinstance(axis, int):
        axes = (axis % ndim,)
    else:
        axes = tuple(a % ndim for a in axis)
    if attrs.get("exclude", False):
        axes = tuple(i for i in range(ndim) if i not in axes)
    return axes


_REDUCE_SCHEMA = ParamSchema(
    Param("axis", "shape", default=()),
    Param("keepdims", bool, default=False),
    Param("exclude", bool, default=False),
)


def register_all():
    jnp = _jnp()
    import jax

    # ---------------- reductions ----------------
    def reduce_table():
        return {
            "sum": jnp.sum,
            "mean": jnp.mean,
            "prod": jnp.prod,
            "nansum": jnp.nansum,
            "nanprod": jnp.nanprod,
            "max": jnp.max,
            "min": jnp.min,
        }

    for name, fn in reduce_table().items():
        def _red(attrs, x, f=fn):
            axes = _norm_axis(attrs, x.ndim)
            return f(x, axis=axes, keepdims=attrs.get("keepdims", False))

        aliases = []
        if name == "sum":
            aliases = ["sum_axis"]
        if name == "max":
            aliases = ["max_axis"]
        if name == "min":
            aliases = ["min_axis"]
        register_op(OpDef(name, simple_compute(_red), schema=_REDUCE_SCHEMA,
                          num_inputs=1, hint=name), aliases=aliases)

    def _norm(attrs, x):
        return jnp.sqrt(jnp.sum(jnp.square(x))).reshape((1,))

    register_op(OpDef("norm", simple_compute(_norm), num_inputs=1))

    arg_schema = ParamSchema(Param("axis", int, default=None),
                             Param("keepdims", bool, default=False))

    for name, fn in (("argmax", jnp.argmax), ("argmin", jnp.argmin)):
        def _arg(attrs, x, f=fn):
            axis = attrs.get("axis", None)
            out = f(x, axis=axis)
            if attrs.get("keepdims", False) and axis is not None:
                out = jnp.expand_dims(out, axis)
            return out.astype(x.dtype)

        register_op(OpDef(name, simple_compute(_arg), schema=arg_schema, num_inputs=1))

    def _argmax_channel(attrs, x):
        return jnp.argmax(x, axis=1).astype(x.dtype)

    register_op(OpDef("argmax_channel", simple_compute(_argmax_channel), num_inputs=1))

    # ---------------- broadcast helpers ----------------
    def _broadcast_to(attrs, x):
        shape = attrs["shape"]
        shape = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
        return jnp.broadcast_to(x, shape)

    register_op(OpDef("broadcast_to", simple_compute(_broadcast_to),
                      schema=ParamSchema(Param("shape", "shape", required=True)),
                      num_inputs=1))

    def _broadcast_axis(attrs, x):
        axes = attrs["axis"]
        sizes = attrs["size"]
        if isinstance(axes, int):
            axes, sizes = (axes,), (sizes,)
        shape = list(x.shape)
        for a, s in zip(axes, sizes):
            shape[a] = s
        return jnp.broadcast_to(x, tuple(shape))

    register_op(OpDef("broadcast_axis", simple_compute(_broadcast_axis),
                      schema=ParamSchema(Param("axis", "shape", default=()),
                                         Param("size", "shape", default=())),
                      num_inputs=1, hint="broadcast_axis"),
                aliases=["broadcast_axes"])

    # ---------------- shape manipulation ----------------
    def _reshape(attrs, x):
        target = attrs.get("shape", ())
        if not target and "target_shape" in attrs and attrs.get("target_shape"):
            target = attrs["target_shape"]
        out_shape = _infer_reshape(tuple(target), x.shape, attrs.get("reverse", False))
        return jnp.reshape(x, out_shape)

    register_op(OpDef("Reshape", simple_compute(_reshape),
                      schema=ParamSchema(Param("shape", "shape", default=()),
                                         Param("reverse", bool, default=False),
                                         Param("target_shape", "shape", default=()),
                                         Param("keep_highest", bool, default=False)),
                      num_inputs=1, hint="reshape"),
                aliases=["reshape"])

    register_op(OpDef("Flatten",
                      simple_compute(lambda attrs, x: jnp.reshape(x, (x.shape[0], -1))),
                      num_inputs=1, hint="flatten"),
                aliases=["flatten"])

    def _transpose(attrs, x):
        axes = attrs.get("axes", ())
        return jnp.transpose(x, axes if axes else None)

    register_op(OpDef("transpose", simple_compute(_transpose),
                      schema=ParamSchema(Param("axes", "shape", default=())),
                      num_inputs=1))

    def _expand_dims(attrs, x):
        return jnp.expand_dims(x, attrs["axis"])

    register_op(OpDef("expand_dims", simple_compute(_expand_dims),
                      schema=ParamSchema(Param("axis", int, required=True)),
                      num_inputs=1))

    def _window(attrs):
        """[begin, end) index tuple shared by slice / slice-assign ops."""
        return tuple(slice(b, e) for b, e in zip(attrs["begin"],
                                                 attrs["end"]))

    def _slice(attrs, x):
        return x[_window(attrs)]

    register_op(OpDef("slice", simple_compute(_slice),
                      schema=ParamSchema(Param("begin", "shape", required=True),
                                         Param("end", "shape", required=True)),
                      num_inputs=1, hint="slice"),
                aliases=["crop"])

    # functional slice-assignment (reference matrix_op.cc:258,283 — the
    # kernels behind NDArray's sliced __setitem__): returns lhs with the
    # [begin, end) window replaced, XLA-friendly via .at[].set
    def _slice_assign(attrs, lhs, rhs):
        return lhs.at[_window(attrs)].set(rhs.astype(lhs.dtype))

    def _slice_assign_shape(attrs, in_shapes, aux_shapes):
        lhs = in_shapes[0]
        if lhs is None:
            raise MXNetError("_slice_assign cannot infer shapes without lhs")
        window = tuple(e - b for b, e in zip(attrs["begin"], attrs["end"]))
        return [tuple(lhs), window], [tuple(lhs)], []

    register_op(OpDef(
        "_slice_assign", simple_compute(_slice_assign),
        schema=ParamSchema(Param("begin", "shape", required=True),
                           Param("end", "shape", required=True)),
        num_inputs=2, arguments=["lhs", "rhs"],
        infer_shape=_slice_assign_shape,
        hint="slice_assign"),
        aliases=["_crop_assign"])

    def _crop_assign_scalar(attrs, data):
        value = jnp.asarray(attrs.get("scalar", 0.0), data.dtype)
        return data.at[_window(attrs)].set(value)

    register_op(OpDef(
        "_crop_assign_scalar", simple_compute(_crop_assign_scalar),
        schema=ParamSchema(Param("begin", "shape", required=True),
                           Param("end", "shape", required=True),
                           Param("scalar", float, default=0.0)),
        num_inputs=1,
        infer_shape=lambda a, i, x: (i, [i[0]], []),
        hint="crop_assign_scalar"),
        aliases=["_slice_assign_scalar"])

    def _slice_axis(attrs, x):
        axis = attrs["axis"] % x.ndim
        begin = attrs["begin"]
        end = attrs["end"]
        if end is None or end == 0 and begin > 0:
            end = x.shape[axis]
        if end is not None and end < 0:
            end = x.shape[axis] + end
        if begin < 0:
            begin = x.shape[axis] + begin
        idx = [slice(None)] * x.ndim
        idx[axis] = slice(begin, end)
        return x[tuple(idx)]

    register_op(OpDef("slice_axis", simple_compute(_slice_axis),
                      schema=ParamSchema(Param("axis", int, required=True),
                                         Param("begin", int, required=True),
                                         Param("end", lambda s: None if str(s) == "None" else int(float(s)), default=None)),
                      num_inputs=1))

    def _dot(attrs, a, b):
        ta, tb = attrs.get("transpose_a", False), attrs.get("transpose_b", False)
        if ta:
            a = jnp.transpose(a)
        if tb:
            b = jnp.transpose(b)
        if a.ndim == 1 and b.ndim == 1:
            return jnp.dot(a, b).reshape((1,))
        return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))

    dot_schema = ParamSchema(Param("transpose_a", bool, default=False),
                             Param("transpose_b", bool, default=False))
    register_op(OpDef("dot", simple_compute(_dot), schema=dot_schema, num_inputs=2))

    def _batch_dot(attrs, a, b):
        ta, tb = attrs.get("transpose_a", False), attrs.get("transpose_b", False)
        if ta:
            a = jnp.swapaxes(a, -1, -2)
        if tb:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)

    register_op(OpDef("batch_dot", simple_compute(_batch_dot), schema=dot_schema,
                      num_inputs=2))

    def _repeat(attrs, x):
        return jnp.repeat(x, attrs["repeats"], axis=attrs.get("axis", None))

    register_op(OpDef("repeat", simple_compute(_repeat),
                      schema=ParamSchema(Param("repeats", int, required=True),
                                         Param("axis", lambda s: None if str(s) == "None" else int(float(s)), default=None)),
                      num_inputs=1))

    def _tile(attrs, x):
        return jnp.tile(x, attrs["reps"])

    register_op(OpDef("tile", simple_compute(_tile),
                      schema=ParamSchema(Param("reps", "shape", required=True)),
                      num_inputs=1))

    def _reverse(attrs, x):
        out = x
        for a in attrs["axis"]:
            out = jnp.flip(out, axis=a)
        return out

    register_op(OpDef("reverse", simple_compute(_reverse),
                      schema=ParamSchema(Param("axis", "shape", required=True)),
                      num_inputs=1, hint="reverse"),
                aliases=["flip"])

    def _swapaxes(attrs, x):
        return jnp.swapaxes(x, attrs.get("dim1", 0), attrs.get("dim2", 0))

    register_op(OpDef("SwapAxis", simple_compute(_swapaxes),
                      schema=ParamSchema(Param("dim1", int, default=0),
                                         Param("dim2", int, default=0)),
                      num_inputs=1, hint="swapaxis"),
                aliases=["swapaxes"])

    # Concat (variadic)
    def _concat(attrs, *xs):
        return jnp.concatenate(xs, axis=attrs.get("dim", 1))

    concat_schema = ParamSchema(Param("num_args", int, required=True),
                                Param("dim", int, default=1))
    register_op(OpDef("Concat", simple_compute(_concat), schema=concat_schema,
                      num_inputs=lambda a: a["num_args"],
                      arguments=lambda a: ["arg%d" % i for i in range(a["num_args"])],
                      key_var_num_args="num_args", hint="concat"),
                aliases=["concat"])

    # SliceChannel / split (multi-output)
    def _split(attrs, x):
        n = attrs["num_outputs"]
        axis = attrs.get("axis", 1)
        parts = jnp.split(x, n, axis=axis)
        if attrs.get("squeeze_axis", False):
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)

    split_schema = ParamSchema(Param("num_outputs", int, required=True),
                               Param("axis", int, default=1),
                               Param("squeeze_axis", bool, default=False))
    register_op(OpDef("SliceChannel", simple_compute(_split), schema=split_schema,
                      num_inputs=1, num_outputs=lambda a: a["num_outputs"],
                      hint="slicechannel"),
                aliases=["split"])

    # ---------------- indexing ----------------
    def _embedding(attrs, data, weight):
        return weight[data.astype(jnp.int32)]

    def _embedding_shape(attrs, in_shapes, aux_shapes):
        dshape = in_shapes[0]
        wshape = (attrs["input_dim"], attrs["output_dim"])
        out = tuple(dshape) + (attrs["output_dim"],)
        return [dshape, wshape], [out], []

    def _embedding_type(attrs, in_types, aux_types):
        # indices keep their own dtype (ints stay ints); output follows the
        # weight table's dtype, defaulting to the op's dtype param
        w = in_types[1] if in_types[1] is not None \
            else np.dtype(attrs.get("dtype", "float32"))
        d = in_types[0] if in_types[0] is not None else np.dtype(np.float32)
        return [d, w], [w], aux_types

    register_op(OpDef("Embedding", simple_compute(_embedding),
                      schema=ParamSchema(Param("input_dim", int, required=True),
                                         Param("output_dim", int, required=True),
                                         Param("dtype", str, default="float32")),
                      num_inputs=2, arguments=["data", "weight"],
                      infer_shape=_embedding_shape, hint="embedding",
                      infer_type=_embedding_type))

    def _take(attrs, a, indices):
        return jnp.take(a, indices.astype(jnp.int32), axis=attrs.get("axis", 0),
                        mode=("clip" if attrs.get("mode", "clip") == "clip" else "wrap"))

    register_op(OpDef("take", simple_compute(_take),
                      schema=ParamSchema(Param("axis", int, default=0),
                                         Param("mode", str, default="clip")),
                      num_inputs=2, arguments=["a", "indices"]))

    def _batch_take(attrs, a, indices):
        return a[jnp.arange(a.shape[0]), indices.astype(jnp.int32)]

    register_op(OpDef("batch_take", simple_compute(_batch_take), num_inputs=2,
                      arguments=["a", "indices"]))

    def _one_hot(attrs, indices):
        import jax

        return jax.nn.one_hot(indices.astype(jnp.int32), attrs["depth"],
                              dtype=np.dtype(attrs.get("dtype", "float32"))) * \
            (attrs.get("on_value", 1.0) - attrs.get("off_value", 0.0)) + \
            attrs.get("off_value", 0.0)

    def _one_hot_type(attrs, in_types, aux_types):
        # output dtype comes from the op's dtype param, never the indices
        return in_types, [np.dtype(attrs.get("dtype", "float32"))], aux_types

    register_op(OpDef("one_hot", simple_compute(_one_hot),
                      schema=ParamSchema(Param("depth", int, required=True),
                                         Param("on_value", float, default=1.0),
                                         Param("off_value", float, default=0.0),
                                         Param("dtype", str, default="float32")),
                      num_inputs=1, arguments=["indices"],
                      infer_type=_one_hot_type))

    def _pick(attrs, data, index):
        axis = attrs.get("axis", -1)
        axis = axis % data.ndim
        idx = index.astype(jnp.int32)
        picked = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
        if not attrs.get("keepdims", False):
            picked = jnp.squeeze(picked, axis=axis)
        return picked

    register_op(OpDef("pick", simple_compute(_pick),
                      schema=ParamSchema(Param("axis", int, default=-1),
                                         Param("keepdims", bool, default=False)),
                      num_inputs=2, arguments=["data", "index"]))

    # ---------------- init ----------------
    def _shape_dtype(attrs):
        shape = attrs.get("shape", ())
        dt = attrs.get("dtype", "float32") or "float32"
        return tuple(shape), (jnp.bfloat16 if dt == "bfloat16" else np.dtype(dt))

    init_schema = ParamSchema(Param("shape", "shape", default=()),
                              Param("ctx", str, default=""),
                              Param("dtype", str, default="float32"))

    def _zeros_shape(attrs, in_shapes, aux_shapes):
        return [], [tuple(attrs.get("shape", ()))], []

    register_op(OpDef("_zeros",
                      simple_compute(lambda attrs: jnp.zeros(*_shape_dtype(attrs))),
                      schema=init_schema, num_inputs=0, infer_shape=_zeros_shape,
                      hint="zeros"))
    register_op(OpDef("_ones",
                      simple_compute(lambda attrs: jnp.ones(*_shape_dtype(attrs))),
                      schema=init_schema, num_inputs=0, infer_shape=_zeros_shape,
                      hint="ones"))

    def _arange_op(attrs):
        arr = np.arange(attrs["start"], attrs.get("stop", None), attrs.get("step", 1.0))
        if attrs.get("repeat", 1) != 1:
            arr = np.repeat(arr, attrs["repeat"])
        _, dt = _shape_dtype(attrs)
        return jnp.asarray(arr, dtype=dt)

    register_op(OpDef("_arange", simple_compute(_arange_op),
                      schema=ParamSchema(Param("start", float, default=0.0),
                                         Param("stop", lambda s: None if str(s) == "None" else float(s), default=None),
                                         Param("step", float, default=1.0),
                                         Param("repeat", int, default=1),
                                         Param("dtype", str, default="float32")),
                      num_inputs=0, hint="arange"))

    register_op(OpDef("zeros_like", simple_compute(lambda attrs, x: jnp.zeros_like(x)),
                      num_inputs=1))
    register_op(OpDef("ones_like", simple_compute(lambda attrs, x: jnp.ones_like(x)),
                      num_inputs=1))

    # ---------------- ordering ----------------
    def _topk(attrs, x):
        import jax

        axis = attrs.get("axis", -1)
        k = attrs.get("k", 1)
        is_ascend = attrs.get("is_ascend", False)
        ret = attrs.get("ret_typ", "indices")
        axis = x.ndim - 1 if axis is None else axis % x.ndim
        xm = jnp.moveaxis(x, axis, -1)
        vals, idxs = jax.lax.top_k(jnp.negative(xm) if is_ascend else xm, k)
        if is_ascend:
            vals = jnp.negative(vals)
        vals = jnp.moveaxis(vals, -1, axis)
        idxs = jnp.moveaxis(idxs, -1, axis).astype(x.dtype)
        if ret == "value":
            return vals
        if ret == "both":
            return vals, idxs
        if ret == "mask":
            oh = jnp.sum(jax.nn.one_hot(jnp.moveaxis(idxs, axis, -1).astype(jnp.int32),
                                        x.shape[axis], dtype=x.dtype), axis=-2)
            return jnp.moveaxis(oh, -1, axis)
        return idxs

    topk_schema = ParamSchema(Param("axis", lambda s: None if str(s) == "None" else int(float(s)), default=-1),
                              Param("k", int, default=1),
                              Param("ret_typ", str, default="indices"),
                              Param("is_ascend", bool, default=False))
    register_op(OpDef("topk", simple_compute(_topk), schema=topk_schema, num_inputs=1,
                      num_outputs=lambda a: 2 if a.get("ret_typ") == "both" else 1))

    def _sort(attrs, x):
        axis = attrs.get("axis", -1)
        out = jnp.sort(x, axis=axis)
        if not attrs.get("is_ascend", True):
            out = jnp.flip(out, axis=axis if axis is not None else 0)
        return out

    sort_schema = ParamSchema(Param("axis", lambda s: None if str(s) == "None" else int(float(s)), default=-1),
                              Param("is_ascend", bool, default=True))
    register_op(OpDef("sort", simple_compute(_sort), schema=sort_schema, num_inputs=1))

    def _argsort(attrs, x):
        axis = attrs.get("axis", -1)
        out = jnp.argsort(x, axis=axis)
        if not attrs.get("is_ascend", True):
            out = jnp.flip(out, axis=axis if axis is not None else 0)
        return out.astype(x.dtype)

    register_op(OpDef("argsort", simple_compute(_argsort), schema=sort_schema,
                      num_inputs=1))

    # ---------------- control flow ----------------
    def _where(attrs, cond, x, y):
        if cond.ndim == 1 and x.ndim > 1:
            cond = cond.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.where(cond != 0, x, y)

    register_op(OpDef("where", simple_compute(_where), num_inputs=3,
                      arguments=["condition", "x", "y"]))

    # ---------------- softmax family (stateless) ----------------
    def _softmax(attrs, x):
        import jax

        return jax.nn.softmax(x, axis=attrs.get("axis", -1))

    sm_schema = ParamSchema(Param("axis", int, default=-1),
                            Param("temperature", lambda s: None if str(s) == "None" else float(s), default=None))
    register_op(OpDef("softmax", simple_compute(_softmax), schema=sm_schema,
                      num_inputs=1))

    def _log_softmax(attrs, x):
        import jax

        return jax.nn.log_softmax(x, axis=attrs.get("axis", -1))

    register_op(OpDef("log_softmax", simple_compute(_log_softmax), schema=sm_schema,
                      num_inputs=1))

    def _softmax_cross_entropy(attrs, data, label):
        import jax

        logp = jax.nn.log_softmax(data, axis=-1)
        lbl = label.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, lbl[:, None], axis=-1)
        return -jnp.sum(picked).reshape((1,))

    register_op(OpDef("softmax_cross_entropy", simple_compute(_softmax_cross_entropy),
                      num_inputs=2, arguments=["data", "label"]))


def _infer_reshape(target, in_shape, reverse=False):
    """MXNet Reshape semantics: 0 copy-dim, -1 infer, -2 copy-rest, -3 merge,
    -4 split (src/operator/tensor/matrix_op.cc Reshape docs)."""
    if not target:
        return in_shape
    src = list(in_shape[::-1]) if reverse else list(in_shape)
    tgt = list(target[::-1]) if reverse else list(target)
    out = []
    src_i = 0
    i = 0
    while i < len(tgt):
        s = tgt[i]
        if s == 0:
            out.append(src[src_i]); src_i += 1
        elif s == -1:
            out.append(-1); src_i += 1
        elif s == -2:
            out.extend(src[src_i:]); src_i = len(src)
        elif s == -3:
            out.append(src[src_i] * src[src_i + 1]); src_i += 2
        elif s == -4:
            a, b = tgt[i + 1], tgt[i + 2]
            if a == -1:
                a = src[src_i] // b
            if b == -1:
                b = src[src_i] // a
            out.extend([a, b]); src_i += 1; i += 2
        else:
            out.append(s); src_i += 1
        i += 1
    if -1 in out:
        known = 1
        for v in out:
            if v != -1:
                known *= v
        total = 1
        for v in in_shape:
            total *= v
        out[out.index(-1)] = total // known
    return tuple(out[::-1]) if reverse else tuple(out)
