"""Pallas block-shape autotuner + persistent tuning cache.

The kernel modules ship block constants "swept on the bench chip" —
``pallas_attention.BLOCK_Q = 128``, ``pallas_fused.BLOCK_M_BWD = 256``
and friends — which are exactly wrong the day the fleet moves to the
next device generation.  This module closes the shape problem the way
AutoTVM closed it (Chen et al., 2018): each kernel module registers its
**tunable space** (the parameters, their hardcoded defaults, a
candidate enumerator and a probe runner), and the first armed process
sweeps the candidates ``benchmarks/layout_probe.py``-style — the SAME
jitted probe runs per candidate, only the block shape changes, so the
delta IS the shape — and persists the winner in a content-addressed
**tuning cache** riding the program-registry cache directory
(:func:`mxnet_tpu.programs.aot.cache_dir`).

Cache entries are small JSON sidecars keyed by
``(device generation, op, shape-class, dtype, space version)`` —
``tune_<sha256[:20]>.json`` — so a cold process resolves every
registered kernel's block shapes by reading files, with ZERO probe
executions (:data:`PROBE_COUNT` is the proof, asserted by the tier-1
subprocess round-trip in tests/test_tuning.py).  A corrupt or stale
entry warns visibly and reads as a miss; without ``MXNET_PALLAS_TUNE``
a miss resolves to the module's hardcoded defaults, which thereby
demote to mere interpret/CPU-mode fallbacks.

Shape classes bucket each dimension to its power-of-two ceiling
(:func:`shape_class_for`): block-shape winners depend on operand
magnitude, not exact row counts, and the bucketing keeps one sweep's
winner live for every batch size in its octave.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import time

log = logging.getLogger(__name__)

__all__ = ["register_space", "spaces", "resolve", "shape_class_for",
           "parse_shape_class", "sweep_mode", "cache_key", "put", "get",
           "reset_memo", "PROBE_COUNT", "SpaceError"]

# schema version of the cache entries themselves (bump to invalidate the
# whole cache format); per-space staleness rides the space's own version
_FORMAT = 1

# op -> _Space; populated by the kernel modules at import
_SPACES = {}

# (op, shape_class, dtype, device) -> params resolved this process
_MEMO = {}

# timed candidate executions this process — the zero-probes-on-cache-hit
# proof counter.  A dict (not an int) so tests can reset in place.
PROBE_COUNT = {"n": 0}


class SpaceError(ValueError):
    """A runner rejecting a candidate it cannot execute (bad shape for
    the probe, VMEM overflow...).  Sweeps skip the candidate; every
    other exception propagates."""


class _Space:
    __slots__ = ("op", "version", "defaults", "constants", "candidates",
                 "runner")

    def __init__(self, op, version, defaults, constants, candidates,
                 runner):
        self.op = op
        self.version = int(version)
        self.defaults = dict(defaults)
        self.constants = tuple(constants)
        self.candidates = candidates
        self.runner = runner


def register_space(op, version, defaults, constants, candidates, runner):
    """Register a kernel module's tunable space.

    ``op``          — the cache namespace (module name, e.g.
                      ``"pallas_attention"``);
    ``version``     — bump when the space's meaning changes (param
                      renames, kernel rewrites): older cache entries
                      then read as stale;
    ``defaults``    — ``{param: value}``, the module's hardcoded
                      constants (the interpret/CPU fallback);
    ``constants``   — the module-level constant NAMES the space governs
                      (``("BLOCK_Q", ...)``), audited by the mxlint
                      tuner-coverage pass;
    ``candidates``  — ``f(shape_class, interpret) -> [ {param: value},
                      ... ]`` partial overrides of ``defaults``;
    ``runner``      — ``f(params, shape_class, dtype, interpret) ->
                      g()`` where ``g`` executes ONE timed probe of the
                      kernel under ``params`` (build/jit outside ``g``
                      so the timing sees steady-state dispatch); raise
                      :class:`SpaceError` for candidates the kernel
                      cannot run.
    """
    _SPACES[op] = _Space(op, version, defaults, constants, candidates,
                         runner)
    return _SPACES[op]


def spaces():
    """{op: space} of every registered tunable space (imports the
    kernel modules so their registrations ran)."""
    from . import (pallas_attention, pallas_decode, pallas_fused,  # noqa
                   pallas_update)

    return dict(_SPACES)


def reset_memo():
    """Forget in-process resolutions (tests; cache files stay)."""
    _MEMO.clear()


# ---------------------------------------------------------------------------
# keys
# ---------------------------------------------------------------------------

def _pow2_ceil(v):
    v = int(v)
    if v <= 1:
        return 1
    return 1 << (v - 1).bit_length()


def shape_class_for(**dims):
    """Canonical shape-class string: each dim bucketed to its pow-2
    ceiling, sorted by name — ``shape_class_for(m=1000, k=64, n=256)``
    -> ``"k64,m1024,n256"``."""
    return ",".join("%s%d" % (k, _pow2_ceil(v))
                    for k, v in sorted(dims.items()))


def parse_shape_class(shape_class):
    """Back-parse a shape-class string into ``{dim: bucket}`` — sweep
    runners probe at the bucket sizes themselves (every shape in the
    octave shares the winner, so the ceiling is the representative)."""
    out = {}
    for part in shape_class.split(","):
        name = part.rstrip("0123456789")
        out[name] = int(part[len(name):])
    return out


def device_generation():
    """The cache's device axis: ``jax.devices()[0].device_kind``
    normalized, or ``"unknown"`` before/without a backend."""
    try:
        import jax

        return str(jax.devices()[0].device_kind).strip().replace(" ", "_")
    except Exception:
        return "unknown"


def cache_key(op, shape_class, dtype, version, device=None):
    """Content address of one tuning decision."""
    ident = json.dumps({
        "format": _FORMAT,
        "device": device or device_generation(),
        "op": op,
        "shape_class": shape_class,
        "dtype": str(dtype),
        "version": int(version),
    }, sort_keys=True)
    return "tune_" + hashlib.sha256(ident.encode()).hexdigest()[:20]


def _cache_path(key):
    from ..programs import aot

    return os.path.join(aot.cache_dir(), key + ".json")


# ---------------------------------------------------------------------------
# persistence
# ---------------------------------------------------------------------------

def put(op, shape_class, dtype, params, version=0, device=None,
        extra=None):
    """Persist one tuning decision (atomic tmp+rename, AOT-cache idiom).
    Returns the cache key; failures warn and are swallowed — the cache
    is an accelerator, never a correctness dependency."""
    from ..programs import aot

    device = device or device_generation()
    key = cache_key(op, shape_class, dtype, version, device=device)
    entry = {"format": _FORMAT, "op": op, "shape_class": shape_class,
             "dtype": str(dtype), "version": int(version),
             "device": device, "params": dict(params)}
    if extra:
        entry.update(extra)
    try:
        d = aot.cache_dir(create=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".tune_tmp_")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(entry, f, indent=2, sort_keys=True)
                f.write("\n")
            os.replace(tmp, os.path.join(d, key + ".json"))
        except BaseException:
            os.unlink(tmp)
            raise
    except Exception as exc:
        log.warning("tuning cache save failed for %s/%s (%s); the "
                    "winner stays in-process only", op, shape_class, exc)
    return key


def get(op, shape_class, dtype, version=0, device=None):
    """The persisted params for one key, or None on miss.  Corrupt or
    stale entries (unreadable JSON, wrong op/version, params that are
    not a dict) warn VISIBLY and read as a miss."""
    key = cache_key(op, shape_class, dtype, version, device=device)
    path = _cache_path(key)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            entry = json.load(f)
        if not isinstance(entry, dict):
            raise ValueError("entry is not an object")
        if entry.get("op") != op or entry.get("version") != int(version) \
                or entry.get("format") != _FORMAT:
            raise ValueError("key fields do not match (stale entry)")
        params = entry.get("params")
        if not isinstance(params, dict):
            raise ValueError("params missing")
        return params
    except Exception as exc:
        log.warning("tuning cache entry %s for %s/%s is corrupt or stale "
                    "(%s); falling back to defaults", key, op,
                    shape_class, exc)
        return None


# ---------------------------------------------------------------------------
# sweep + resolve
# ---------------------------------------------------------------------------

def sweep_mode():
    """``(armed, interpret)``: sweeps run when ``MXNET_PALLAS_TUNE`` is
    set AND the backend can execute probes (TPU natively, anything else
    under ``MXNET_PALLAS_INTERPRET``) — the same gate rule as the
    kernel knobs themselves."""
    from .. import config as _config

    if not _config.get("MXNET_PALLAS_TUNE"):
        return False, False
    import jax

    if jax.default_backend() == "tpu":
        return True, False
    if _config.get("MXNET_PALLAS_INTERPRET"):
        return True, True
    return False, False


def _sweep(space, shape_class, dtype, interpret, iters=3):
    """Time every candidate; return (winner_params, results list).
    Each timed execution bumps :data:`PROBE_COUNT`."""
    results = []
    for cand in space.candidates(shape_class, interpret):
        params = dict(space.defaults)
        params.update(cand)
        try:
            probe = space.runner(params, shape_class, dtype, interpret)
            probe()                      # warmup: compile outside timing
            PROBE_COUNT["n"] += 1
            tic = time.perf_counter()
            for _ in range(iters):
                probe()
                PROBE_COUNT["n"] += 1
            dt = (time.perf_counter() - tic) / iters
        except SpaceError as exc:
            log.info("tuning %s/%s: candidate %s unsupported (%s)",
                     space.op, shape_class, cand, exc)
            continue
        results.append((dt, params))
    if not results:
        return dict(space.defaults), []
    results.sort(key=lambda r: r[0])
    return dict(results[0][1]), results


def resolve(op, shape_class, dtype):
    """The tuned parameters for ``(op, shape_class, dtype)`` on this
    device generation — the ONE lookup the kernel modules call at
    trace time.

    Resolution order: in-process memo -> persisted cache entry ->
    sweep (when :func:`sweep_mode` arms, persisting the winner) ->
    the space's registered defaults.  Always returns a full params
    dict; unknown params in a cache entry are dropped so a tampered
    entry cannot inject keys the kernels never declared."""
    space = _SPACES.get(op)
    if space is None:
        raise KeyError("no tunable space registered for %r" % op)
    dtype = str(dtype)
    device = device_generation()
    memo_key = (op, shape_class, dtype, device)
    hit = _MEMO.get(memo_key)
    if hit is not None:
        return dict(hit)

    entry = get(op, shape_class, dtype, version=space.version,
                device=device)
    if entry is not None:
        params = dict(space.defaults)
        params.update({k: v for k, v in entry.items()
                       if k in space.defaults})
        _MEMO[memo_key] = params
        return dict(params)

    armed, interpret = sweep_mode()
    if armed:
        params, results = _sweep(space, shape_class, dtype, interpret)
        put(op, shape_class, dtype, params, version=space.version,
            device=device,
            extra={"swept": [{"ms": round(dt * 1e3, 4), "params": p}
                             for dt, p in results]})
        _MEMO[memo_key] = params
        return dict(params)

    _MEMO[memo_key] = dict(space.defaults)
    return dict(space.defaults)
