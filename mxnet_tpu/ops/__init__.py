"""Operator library — registers every op into the global registry on import.

Families mirror the reference inventory (SURVEY §2.3): elemwise (unary /
binary / broadcast / scalar / logic), tensor (reduce / matrix / indexing /
init / ordering / control / softmax), nn layer ops, sampling, fused
optimizer updates.  Contrib (detection / CTC / fft) and RNN register from
their own modules as they land.
"""
from . import (elemwise, tensor, nn, sample, optimizer_ops, rnn_op, spatial,
               contrib_ops, attention, moe, fused_lm)

_registered = False


def register_all():
    global _registered
    if _registered:
        return
    _registered = True
    elemwise.register_all()
    tensor.register_all()
    nn.register_all()
    sample.register_all()
    optimizer_ops.register_all()
    rnn_op.register_all()
    spatial.register_all()
    contrib_ops.register_all()
    attention.register_all()
    moe.register_all()
    fused_lm.register_all()


register_all()
