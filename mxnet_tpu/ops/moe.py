"""Mixture-of-Experts operator — the 'expert' mesh axis made real.

No reference analog (SURVEY §2.5: "Tensor/expert parallelism: not present
in any form") — this is a leapfrog op like attention.  ``MoEFFN`` is a
switch-routed (top-1) expert feed-forward layer:

    gate   = softmax(x @ Wg)                      # (N, E) router
    choice = argmax(gate)                         # top-1 switch routing
    y      = gate[choice] * FFN_choice(x)         # scaled expert output

Dispatch is DENSE (one-hot combine matmuls, no ragged gather): every token
multiplies against every expert with a 0/1 mask folded into the einsum.
That is the TPU-friendly formulation — static shapes, MXU-shaped einsums —
and under the mesh executor the expert-stacked weights (E, ...) shard on
the 'expert' axis (declared as OpDef ``mesh_axes`` metadata), so GSPMD
turns the combine einsums into the expert all-to-alls.

Load balancing: the Switch Transformer auxiliary loss (E · Σ_e f_e·P_e)
is folded into the op's own gradient through ``jax.custom_vjp`` with
weight ``aux_loss_coeff`` — backward computes the vjp of
``y + coeff * aux`` so the router receives balancing pressure without any
extra loss-head plumbing (set ``aux_loss_coeff=0`` to disable).
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op


def _moe_shape(attrs, in_shapes, aux_shapes):
    x, wg, w1, b1, w2, b2 = in_shapes
    e = attrs["num_experts"]
    h = attrs["hidden_size"]
    d = x[-1]
    want = [tuple(x), (d, e), (e, d, h), (e, h), (e, h, d), (e, d)]
    return want, [tuple(x)], []


def _moe_forward(x, wg, w1, b1, w2, b2, num_experts):
    """-> (y, aux_loss): switch-routed expert FFN + Switch balance term."""
    import jax
    import jax.numpy as jnp

    e = num_experts
    orig_shape = x.shape
    d = x.shape[-1]
    xt = x.reshape(-1, d)                       # (N, d) tokens

    probs = jax.nn.softmax(xt @ wg, axis=-1)    # (N, E) router
    choice = jnp.argmax(probs, axis=-1)         # (N,)
    onehot = jnp.eye(e, dtype=xt.dtype)[choice]  # (N, E) dispatch mask
    gate = (probs * onehot).sum(-1)             # (N,) chosen prob

    # dense dispatch: every expert sees the masked token batch; the
    # (E, ...) weight axis is what shards on the 'expert' mesh axis
    xe = jnp.einsum("nd,ne->end", xt, onehot)   # (E, N, d)
    h = jnp.einsum("end,edh->enh", xe, w1) + b1[:, None, :]
    h = jnp.maximum(h, 0.0)                     # relu expert FFN
    ye = jnp.einsum("enh,ehd->end", h, w2) + b2[:, None, :]
    y = jnp.einsum("end,ne->nd", ye, onehot)    # combine back to tokens
    y = y * gate[:, None]

    # Switch load-balance loss: E * sum_e f_e * P_e
    frac = onehot.mean(0)                       # tokens routed per expert
    imp = probs.mean(0)                         # mean router prob
    aux_loss = (frac * imp).sum() * e
    return y.reshape(orig_shape), aux_loss


def _moe_forward_sparse(x, wg, w1, b1, w2, b2, num_experts,
                        capacity_factor, mesh=None):
    """Capacity-based sparse dispatch: per-step FLOPs FLAT in num_experts.

    Each expert owns a fixed-capacity slot table C = ceil(cf * N / E); a
    token takes the next slot of its chosen expert and tokens past
    capacity are DROPPED (Switch Transformer semantics; the residual
    connection around the MoE layer carries them).  Dispatch and combine
    are gathers over a static (E*C) slot table — no (N, E) one-hot
    matmuls, so the expert FFN compute is 2*cf*N*(dh+hd) regardless of E,
    where the dense fallback pays E times that.

    Under a mesh with an 'expert' axis the expert-major tensors carry
    explicit sharding constraints, so each device computes only its own
    experts' slots and GSPMD inserts the token exchange (all-to-all /
    collective-permute family) at the dispatch/combine boundaries.
    """
    import jax
    import jax.numpy as jnp

    e = num_experts
    orig_shape = x.shape
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    n = xt.shape[0]
    c = max(1, int(np.ceil(capacity_factor * n / e)))

    probs = jax.nn.softmax(xt @ wg, axis=-1)
    choice = jnp.argmax(probs, axis=-1)
    onehot = jax.nn.one_hot(choice, e, dtype=xt.dtype)
    gate = (probs * onehot).sum(-1)

    # position of each token in its expert's queue (arrival order) —
    # counted in int32: a bf16 activation-dtype cumsum loses exact
    # integers past 256 and would silently collide slots on big batches
    oh32 = onehot.astype(jnp.int32)
    pos = ((jnp.cumsum(oh32, axis=0) - 1) * oh32).sum(-1)
    keep = pos < c
    flat_slot = choice.astype(jnp.int32) * c + jnp.minimum(pos, c - 1)

    # slot -> token table; sentinel n points at a zero pad row
    scatter_idx = jnp.where(keep, flat_slot, e * c)
    slot_tok = jnp.full((e * c,), n, jnp.int32) \
        .at[scatter_idx].set(jnp.arange(n, dtype=jnp.int32), mode="drop")
    xpad = jnp.concatenate([xt, jnp.zeros((1, d), xt.dtype)], axis=0)
    xd = jnp.take(xpad, slot_tok, axis=0).reshape(e, c, d)

    if mesh is not None and dict(mesh.shape).get("expert", 1) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        espec = NamedSharding(mesh, P("expert"))
        xd = jax.lax.with_sharding_constraint(xd, espec)
    h = jnp.einsum("ecd,edh->ech", xd, w1) + b1[:, None, :]
    h = jnp.maximum(h, 0.0)
    ye = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
    if mesh is not None and dict(mesh.shape).get("expert", 1) > 1:
        ye = jax.lax.with_sharding_constraint(ye, espec)

    # combine: each kept token reads back its slot; dropped tokens emit 0
    flat = ye.reshape(e * c, d)
    yt = jnp.take(flat, jnp.minimum(flat_slot, e * c - 1), axis=0)
    yt = yt * keep[:, None].astype(yt.dtype) * gate[:, None]

    frac = onehot.mean(0)
    imp = probs.mean(0)
    aux_loss = (frac * imp).sum() * e
    return yt.reshape(orig_shape), aux_loss


def register_all():
    import jax

    _wrapped = {}

    def _moe_with_aux_grad(num_experts, coeff, capacity_factor, mesh):
        """custom_vjp wrapper: forward value is y alone; backward is the
        vjp of (y + coeff * aux_loss), i.e. training minimizes
        task_loss + coeff * balance_loss with exact gradients."""
        # key by the mesh's VALUE (axes + device ids), not id(): id-keying
        # grows the cache (and pins a Mesh) for every rebind in a
        # long-running job; equal meshes share one traced closure
        mesh_key = None if mesh is None else (
            tuple(mesh.shape.items()),
            tuple(d.id for d in mesh.devices.flat))
        key = (num_experts, coeff, capacity_factor, mesh_key)
        fn = _wrapped.get(key)
        if fn is not None:
            return fn

        def fwd_impl(x, wg, w1, b1, w2, b2):
            if capacity_factor > 0:
                return _moe_forward_sparse(x, wg, w1, b1, w2, b2,
                                           num_experts, capacity_factor,
                                           mesh)
            return _moe_forward(x, wg, w1, b1, w2, b2, num_experts)

        @jax.custom_vjp
        def moe(x, wg, w1, b1, w2, b2):
            y, _ = fwd_impl(x, wg, w1, b1, w2, b2)
            return y

        def fwd(x, wg, w1, b1, w2, b2):
            y, _ = fwd_impl(x, wg, w1, b1, w2, b2)
            return y, (x, wg, w1, b1, w2, b2)

        def bwd(res, dy):
            import jax.numpy as jnp

            def total(x, wg, w1, b1, w2, b2):
                y, aux = fwd_impl(x, wg, w1, b1, w2, b2)
                return y, aux

            (_, aux), vjp = jax.vjp(total, *res)
            # cotangents must match the primal dtypes (aux follows inputs)
            return vjp((dy, jnp.asarray(coeff, dtype=aux.dtype)))

        moe.defvjp(fwd, bwd)
        _wrapped[key] = moe
        return moe

    def fcompute(attrs, inputs, aux, octx):
        fn = _moe_with_aux_grad(attrs["num_experts"],
                                float(attrs["aux_loss_coeff"]),
                                float(attrs["capacity_factor"]),
                                octx.mesh)
        return [fn(*inputs)], []

    register_op(OpDef(
        "MoEFFN", fcompute,
        schema=ParamSchema(
            Param("num_experts", int, required=True),
            Param("hidden_size", int, required=True),
            Param("aux_loss_coeff", float, default=0.01,
                  doc="weight of the Switch load-balancing loss folded "
                      "into the backward pass; 0 disables"),
            Param("capacity_factor", float, default=0.0,
                  doc="> 0 enables SPARSE capacity-based dispatch: each "
                      "expert processes at most ceil(cf*N/E) tokens "
                      "(overflow tokens drop, Switch semantics) and the "
                      "per-step FLOPs are flat in num_experts; 0 keeps "
                      "the dense all-expert oracle"),
        ),
        num_inputs=6,
        arguments=["data", "gate_weight", "expert1_weight",
                   "expert1_bias", "expert2_weight", "expert2_bias"],
        infer_shape=_moe_shape,
        mesh_axes={"expert1_weight": "expert", "expert1_bias": "expert",
                   "expert2_weight": "expert", "expert2_bias": "expert"},
        doc="Switch-routed (top-1) mixture-of-experts feed-forward.  "
            "Leapfrog op (SURVEY §2.5: expert parallelism 'not present'): "
            "expert-stacked weights (E, ...) shard on the 'expert' mesh "
            "axis; dense one-hot dispatch keeps shapes static for XLA; "
            "the Switch balance loss rides the backward pass "
            "(aux_loss_coeff)."),
        aliases=("_contrib_MoEFFN",))
