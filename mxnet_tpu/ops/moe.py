"""Mixture-of-Experts operator — the 'expert' mesh axis made real.

No reference analog (SURVEY §2.5: "Tensor/expert parallelism: not present
in any form") — this is a leapfrog op like attention.  ``MoEFFN`` is a
switch-routed (top-1) expert feed-forward layer:

    gate   = softmax(x @ Wg)                      # (N, E) router
    choice = argmax(gate)                         # top-1 switch routing
    y      = gate[choice] * FFN_choice(x)         # scaled expert output

Dispatch is DENSE (one-hot combine matmuls, no ragged gather): every token
multiplies against every expert with a 0/1 mask folded into the einsum.
That is the TPU-friendly formulation — static shapes, MXU-shaped einsums —
and under the mesh executor the expert-stacked weights (E, ...) shard on
the 'expert' axis (declared as OpDef ``mesh_axes`` metadata), so GSPMD
turns the combine einsums into the expert all-to-alls.

Load balancing: the Switch Transformer auxiliary loss (E · Σ_e f_e·P_e)
is folded into the op's own gradient through ``jax.custom_vjp`` with
weight ``aux_loss_coeff`` — backward computes the vjp of
``y + coeff * aux`` so the router receives balancing pressure without any
extra loss-head plumbing (set ``aux_loss_coeff=0`` to disable).
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op


def _moe_shape(attrs, in_shapes, aux_shapes):
    x, wg, w1, b1, w2, b2 = in_shapes
    e = attrs["num_experts"]
    h = attrs["hidden_size"]
    d = x[-1]
    want = [tuple(x), (d, e), (e, d, h), (e, h), (e, h, d), (e, d)]
    return want, [tuple(x)], []


def _moe_forward(x, wg, w1, b1, w2, b2, num_experts):
    """-> (y, aux_loss): switch-routed expert FFN + Switch balance term."""
    import jax
    import jax.numpy as jnp

    e = num_experts
    orig_shape = x.shape
    d = x.shape[-1]
    xt = x.reshape(-1, d)                       # (N, d) tokens

    probs = jax.nn.softmax(xt @ wg, axis=-1)    # (N, E) router
    choice = jnp.argmax(probs, axis=-1)         # (N,)
    onehot = jnp.eye(e, dtype=xt.dtype)[choice]  # (N, E) dispatch mask
    gate = (probs * onehot).sum(-1)             # (N,) chosen prob

    # dense dispatch: every expert sees the masked token batch; the
    # (E, ...) weight axis is what shards on the 'expert' mesh axis
    xe = jnp.einsum("nd,ne->end", xt, onehot)   # (E, N, d)
    h = jnp.einsum("end,edh->enh", xe, w1) + b1[:, None, :]
    h = jnp.maximum(h, 0.0)                     # relu expert FFN
    ye = jnp.einsum("enh,ehd->end", h, w2) + b2[:, None, :]
    y = jnp.einsum("end,ne->nd", ye, onehot)    # combine back to tokens
    y = y * gate[:, None]

    # Switch load-balance loss: E * sum_e f_e * P_e
    frac = onehot.mean(0)                       # tokens routed per expert
    imp = probs.mean(0)                         # mean router prob
    aux_loss = (frac * imp).sum() * e
    return y.reshape(orig_shape), aux_loss


def register_all():
    import jax

    _wrapped = {}

    def _moe_with_aux_grad(num_experts, coeff):
        """custom_vjp wrapper: forward value is y alone; backward is the
        vjp of (y + coeff * aux_loss), i.e. training minimizes
        task_loss + coeff * balance_loss with exact gradients."""
        key = (num_experts, coeff)
        fn = _wrapped.get(key)
        if fn is not None:
            return fn

        @jax.custom_vjp
        def moe(x, wg, w1, b1, w2, b2):
            y, _ = _moe_forward(x, wg, w1, b1, w2, b2, num_experts)
            return y

        def fwd(x, wg, w1, b1, w2, b2):
            y, _ = _moe_forward(x, wg, w1, b1, w2, b2, num_experts)
            return y, (x, wg, w1, b1, w2, b2)

        def bwd(res, dy):
            import jax.numpy as jnp

            def total(x, wg, w1, b1, w2, b2):
                y, aux = _moe_forward(x, wg, w1, b1, w2, b2, num_experts)
                return y, aux

            (_, aux), vjp = jax.vjp(total, *res)
            # cotangents must match the primal dtypes (aux follows inputs)
            return vjp((dy, jnp.asarray(coeff, dtype=aux.dtype)))

        moe.defvjp(fwd, bwd)
        _wrapped[key] = moe
        return moe

    def fcompute(attrs, inputs, aux, octx):
        fn = _moe_with_aux_grad(attrs["num_experts"],
                                float(attrs["aux_loss_coeff"]))
        return [fn(*inputs)], []

    register_op(OpDef(
        "MoEFFN", fcompute,
        schema=ParamSchema(
            Param("num_experts", int, required=True),
            Param("hidden_size", int, required=True),
            Param("aux_loss_coeff", float, default=0.01,
                  doc="weight of the Switch load-balancing loss folded "
                      "into the backward pass; 0 disables"),
        ),
        num_inputs=6,
        arguments=["data", "gate_weight", "expert1_weight",
                   "expert1_bias", "expert2_weight", "expert2_bias"],
        infer_shape=_moe_shape,
        mesh_axes={"expert1_weight": "expert", "expert1_bias": "expert",
                   "expert2_weight": "expert", "expert2_bias": "expert"},
        doc="Switch-routed (top-1) mixture-of-experts feed-forward.  "
            "Leapfrog op (SURVEY §2.5: expert parallelism 'not present'): "
            "expert-stacked weights (E, ...) shard on the 'expert' mesh "
            "axis; dense one-hot dispatch keeps shapes static for XLA; "
            "the Switch balance loss rides the backward pass "
            "(aux_loss_coeff)."),
        aliases=("_contrib_MoEFFN",))
