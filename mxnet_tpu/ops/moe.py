"""Mixture-of-Experts operator — the 'expert' mesh axis made real.

No reference analog (SURVEY §2.5: "Tensor/expert parallelism: not present
in any form") — this is a leapfrog op like attention.  ``MoEFFN`` is a
routed expert feed-forward layer:

    gate   = softmax(x @ Wg)                      # (N, E) router
    choice = top-k(gate)                          # k = num_experts_per_tok
    y      = sum_k gate_k * FFN_{choice_k}(x)     # gated expert mixture

Top-1 (the default) is switch routing with the raw chosen probability as
the gate; k > 1 renormalizes the chosen gates to sum to one (the
Mixtral/GShard convention), so k = 1 semantics are unchanged from the
original switch formulation.

Three dispatch shapes, one routing rule:

* **dense** (``capacity_factor == 0``): one-hot combine matmuls, every
  expert sees every token — static shapes, MXU-shaped einsums, E× the
  FFN compute.  The oracle the sparse paths are benchmarked against.
* **sparse reference** (``capacity_factor > 0``, no 'expert' mesh):
  capacity-slot dispatch — each expert owns ``C = ceil(cf*k*N_g/E)``
  slots per token group, tokens past capacity DROP (Switch semantics)
  unless ``overflow='dropless'`` stretches the capacity to the
  worst case with a padding mask.  ``num_groups`` splits the tokens into
  contiguous groups with independent capacity quotas — group g of the
  reference IS device g of the sharded path, so the two drop identical
  token sets by construction.
* **sharded** (``capacity_factor > 0`` under a mesh whose 'expert' axis
  is > 1): an explicit ``shard_map`` program — each device routes its
  local tokens, packs them into per-(destination-expert) capacity slots
  of static shape (E, C_loc, d), exchanges them with
  ``jax.lax.all_to_all``, runs only its own experts' FFNs (hidden dim
  optionally Megatron-split over 'model' with one psum), and
  all-to-alls the outputs back for the combine.  The backward pass —
  the op-level ``jax.custom_vjp`` below — differentiates through the
  region, so the two exchanges reappear reversed (an all-to-all's
  transpose is the opposite-direction all-to-all) instead of hoping
  GSPMD synthesizes them from sharding hints.  The mxlint collective
  pass budgets the resulting all-to-all count/bytes per program
  (benchmarks/budgets.json; docs/moe.md has the workflow).

Load balancing: the Switch auxiliary loss (E · Σ_e f_e·P_e) is folded
into the op's own gradient through ``jax.custom_vjp`` with weight
``aux_loss_coeff`` — backward computes the vjp of ``y + coeff * aux`` so
the router receives balancing pressure without any extra loss-head
plumbing (set ``aux_loss_coeff=0`` to disable).
"""
from __future__ import annotations

import numpy as np

from ..attrs import Param, ParamSchema
from ..registry import OpDef, register_op

# which dispatch shape the last MoEFFN trace used ("dense" | "sparse" |
# "sparse_a2a") — path-selection tripwire, same pattern as
# ops.attention.PATH_TAKEN / parallel.ring.RING_PATH
MOE_PATH = {"last": None}

# which capacity-slot assignment algorithm the last sparse trace used
# ("sort" | "onehot") — the MXNET_MOE_DISPATCH tripwire; None until a
# capacity path traces
MOE_DISPATCH = {"last": None}


def _moe_shape(attrs, in_shapes, aux_shapes):
    x, wg, w1, b1, w2, b2 = in_shapes
    e = attrs["num_experts"]
    h = attrs["hidden_size"]
    d = x[-1]
    want = [tuple(x), (d, e), (e, d, h), (e, h), (e, h, d), (e, d)]
    return want, [tuple(x)], []


# ---------------------------------------------------------------------------
# routing + slot assignment — ONE implementation shared by the sparse
# reference and the shard_map region, so drop sets cannot drift apart
# ---------------------------------------------------------------------------

def _route(probs, k):
    """Top-k routing: ``(choice, gate)`` both (n, k).

    k = 1 is switch routing (argmax; gate = the raw chosen probability).
    k > 1 takes the k highest-probability experts per token and
    renormalizes the chosen gates to sum to one.
    """
    import jax
    import jax.numpy as jnp

    if k == 1:
        choice = jnp.argmax(probs, axis=-1)[:, None]
        gate = jnp.take_along_axis(probs, choice, axis=-1)
        return choice, gate
    gate, choice = jax.lax.top_k(probs, k)
    gate = gate / gate.sum(-1, keepdims=True)
    return choice, gate


def _positions_onehot(choice, e):
    """Capacity positions via the one-hot cumsum pack (the historical
    algorithm, kept for A/B pricing): materializes a (k*n, E) int32
    one-hot and its running cumsum — E x the index traffic of the sort
    path.  Counting runs in int32: an activation-dtype cumsum loses
    exact integers past 256 and would silently collide slots on big
    batches."""
    import jax
    import jax.numpy as jnp

    n, k = choice.shape
    oh = jax.nn.one_hot(choice, e, dtype=jnp.int32)        # (n, k, E)
    oh_rank_major = oh.transpose(1, 0, 2).reshape(k * n, e)
    return ((jnp.cumsum(oh_rank_major, axis=0) - 1) * oh_rank_major) \
        .sum(-1).reshape(k, n).T                           # (n, k)


def _positions_sort(choice, e):
    """Capacity positions via sort-based dispatch (MegaBlocks, Gale et
    al. 2022): a STABLE argsort of the rank-major flattened choices is
    exactly an argsort over the composite (expert, priority) key — same-
    expert entries keep rank-major order — so each entry's position
    within its expert group is its sorted index minus the group start
    (an exclusive cumsum of the per-expert histogram).  No (k*n, E)
    one-hot ever materializes: the intermediates are O(k*n) sort keys
    and one length-E histogram, priced by the analysis sort/scatter
    accounting."""
    import jax.numpy as jnp

    n, k = choice.shape
    flat = choice.transpose(1, 0).reshape(-1)              # rank-major (k*n,)
    order = jnp.argsort(flat, stable=True)
    counts = jnp.bincount(flat, length=e).astype(jnp.int32)
    starts = jnp.cumsum(counts) - counts                   # exclusive
    pos_sorted = jnp.arange(k * n, dtype=jnp.int32) \
        - starts[jnp.take(flat, order)]
    return jnp.zeros((k * n,), jnp.int32).at[order].set(pos_sorted) \
        .reshape(k, n).T                                   # (n, k)


def _slot_assign(choice, e, cap):
    """Capacity-slot assignment for one token group.

    Positions are PRIORITY-MAJOR: every token's rank-0 choice is counted
    before any rank-1 choice (GShard order — a token's second expert can
    never evict another token's first).  Returns ``(pos, keep, slot)``,
    all (n, k); ``slot = choice*cap + pos`` clipped into [0, e*cap).

    ``MXNET_MOE_DISPATCH`` selects the position algorithm at trace time:
    'sort' (default — argsort over the composite (expert, priority) key)
    or 'onehot' (the one-hot cumsum pack).  Both are BIT-IDENTICAL in
    (pos, keep, slot) — and therefore in outputs, grads and drop sets —
    differing only in the dispatch intermediates they materialize
    (tier-1 asserts the identity; the sparse reference and the sharded
    all-to-all path share this one implementation so the knob can never
    split them).
    """
    import jax.numpy as jnp

    from .. import config as _config

    algo = (str(_config.get("MXNET_MOE_DISPATCH")) or "sort").lower()
    if algo not in ("sort", "onehot"):
        raise ValueError("MXNET_MOE_DISPATCH must be 'sort' or 'onehot'; "
                         "got %r" % algo)
    MOE_DISPATCH["last"] = algo
    pos = (_positions_sort if algo == "sort"
           else _positions_onehot)(choice, e)
    keep = pos < cap
    slot = choice * cap + jnp.minimum(pos, cap - 1)
    return pos, keep, slot


def _pack_slots(xt, slot, keep, e, cap):
    """Scatter kept tokens into the (E, cap, d) dispatch table (unfilled
    slots read a zero pad row; the sentinel index e*cap is dropped)."""
    import jax.numpy as jnp

    n, d = xt.shape
    k = slot.shape[1]
    tok = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None],
                           (n, k)).reshape(-1)
    scatter_idx = jnp.where(keep.reshape(-1), slot.reshape(-1), e * cap)
    slot_tok = jnp.full((e * cap,), n, jnp.int32) \
        .at[scatter_idx].set(tok, mode="drop")
    xpad = jnp.concatenate([xt, jnp.zeros((1, d), xt.dtype)], axis=0)
    return jnp.take(xpad, slot_tok, axis=0).reshape(e, cap, d)


def _combine_slots(flat_out, slot, keep, gate):
    """Gather each kept (token, rank) choice's slot output, weighted by
    its gate; dropped choices contribute zero."""
    import jax.numpy as jnp

    total = flat_out.shape[0]
    idx = jnp.minimum(slot, total - 1)                     # (n, k)
    picked = jnp.take(flat_out, idx.reshape(-1), axis=0) \
        .reshape(idx.shape + (flat_out.shape[-1],))        # (n, k, d)
    w = (keep.astype(flat_out.dtype) * gate.astype(flat_out.dtype))
    return (picked * w[..., None]).sum(axis=1)


def _expert_ffn(xd, w1, b1, w2, b2):
    """The relu expert FFN over an (E, C, d) slot table."""
    import jax.numpy as jnp

    h = jnp.einsum("ecd,edh->ech", xd, w1) + b1[:, None, :]
    h = jnp.maximum(h, 0.0)
    return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]


def _aux_terms(probs, choice, e):
    """Local (frac, imp) means for the Switch balance loss: f_e = mean
    routed fraction per choice rank, P_e = mean router probability."""
    import jax
    import jax.numpy as jnp

    k = choice.shape[1]
    oh = jax.nn.one_hot(choice, e, dtype=probs.dtype).sum(1)   # (n, E)
    return oh.mean(0) / k, probs.mean(0)


def _capacity(capacity_factor, k, group_tokens, e, dropless):
    if dropless:
        return group_tokens * k
    return max(1, int(np.ceil(capacity_factor * k * group_tokens / e)))


# ---------------------------------------------------------------------------
# the three dispatch shapes
# ---------------------------------------------------------------------------

def _moe_forward(x, wg, w1, b1, w2, b2, num_experts, num_experts_per_tok=1):
    """Dense one-hot dispatch -> (y, aux_loss): every expert sees the
    masked token batch (the E×-compute oracle the sparse paths beat)."""
    import jax
    import jax.numpy as jnp

    e = num_experts
    k = min(num_experts_per_tok, e)
    orig_shape = x.shape
    d = x.shape[-1]
    xt = x.reshape(-1, d)                       # (N, d) tokens

    probs = jax.nn.softmax(xt @ wg, axis=-1)    # (N, E) router
    choice, gate = _route(probs, k)             # (N, k) each
    onehot_k = jax.nn.one_hot(choice, e, dtype=xt.dtype)   # (N, k, E)
    dispatch = onehot_k.sum(1)                  # (N, E) 0/1 mask
    combine = (onehot_k * gate[..., None].astype(xt.dtype)).sum(1)

    # dense dispatch: every expert sees the masked token batch; the
    # (E, ...) weight axis is what shards on the 'expert' mesh axis
    xe = jnp.einsum("nd,ne->end", xt, dispatch)  # (E, N, d)
    h = jnp.einsum("end,edh->enh", xe, w1) + b1[:, None, :]
    h = jnp.maximum(h, 0.0)                     # relu expert FFN
    ye = jnp.einsum("enh,ehd->end", h, w2) + b2[:, None, :]
    y = jnp.einsum("end,ne->nd", ye, combine)   # gated combine

    # Switch load-balance loss: E * sum_e f_e * P_e
    frac, imp = _aux_terms(probs, choice, e)
    aux_loss = (frac * imp).sum() * e
    return y.reshape(orig_shape), aux_loss


def _moe_forward_sparse(x, wg, w1, b1, w2, b2, num_experts,
                        capacity_factor, mesh=None, num_experts_per_tok=1,
                        num_groups=1, dropless=False):
    """Capacity-based sparse dispatch: per-step FLOPs FLAT in num_experts.

    Tokens split into ``num_groups`` contiguous groups; within each group
    every (token, rank-k choice) takes the next capacity slot of its
    chosen expert — C = ceil(cf*k*N_g/E) slots per (group, expert) — and
    choices past capacity are DROPPED (Switch semantics; the residual
    connection around the MoE layer carries them) unless ``dropless``
    stretches C to the group's worst case with a padding mask.  Dispatch
    and combine are gathers over static slot tables — no (N, E) one-hot
    matmuls, so the expert FFN compute is ~2*cf*k*N*(dh+hd) regardless
    of E, where the dense oracle pays E times that.

    ``num_groups`` exists because group g IS device g of the sharded
    all-to-all path (`_moe_forward_sparse_sharded`): called with
    ``num_groups = data_par * expert_par`` this single-device reference
    reproduces the sharded program's drop set token for token — the
    parity the tier-1 suite asserts.  The default (1) is the historical
    global-cumsum semantics.

    Under a mesh with an 'expert' axis (but taken only when the explicit
    shard_map path's divisibility guards fail) the expert-major tensors
    carry sharding constraints so GSPMD may synthesize the exchange.
    """
    import jax
    import jax.numpy as jnp

    e = num_experts
    k = min(num_experts_per_tok, e)
    orig_shape = x.shape
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    n = xt.shape[0]
    g = num_groups
    assert n % g == 0, "token count %d not divisible into %d groups" % (n, g)
    ng = n // g
    cap = _capacity(capacity_factor, k, ng, e, dropless)

    probs = jax.nn.softmax(xt @ wg, axis=-1)
    choice_all, gate_all = _route(probs, k)

    def pack_group(xtg, choiceg, gateg):
        _, keep, slot = _slot_assign(choiceg, e, cap)
        xd = _pack_slots(xtg, slot, keep, e, cap)
        return xd, keep, slot

    xd_g, keep_g, slot_g = jax.vmap(pack_group)(
        xt.reshape(g, ng, d), choice_all.reshape(g, ng, k),
        gate_all.reshape(g, ng, k))             # (g, E, cap, d), ...

    if mesh is not None and dict(mesh.shape).get("expert", 1) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        espec = NamedSharding(mesh, P(None, "expert"))
        xd_g = jax.lax.with_sharding_constraint(xd_g, espec)
    ye_g = jax.vmap(_expert_ffn, in_axes=(0, None, None, None, None))(
        xd_g, w1, b1, w2, b2)
    if mesh is not None and dict(mesh.shape).get("expert", 1) > 1:
        ye_g = jax.lax.with_sharding_constraint(ye_g, espec)

    # combine: each kept (token, rank) reads back its slot; drops emit 0
    yt = jax.vmap(_combine_slots)(
        ye_g.reshape(g, e * cap, d), slot_g, keep_g,
        gate_all.reshape(g, ng, k)).reshape(n, d)

    frac, imp = _aux_terms(probs, choice_all, e)
    aux_loss = (frac * imp).sum() * e
    return yt.reshape(orig_shape), aux_loss


def _moe_forward_sparse_sharded(x, wg, w1, b1, w2, b2, num_experts,
                                capacity_factor, mesh,
                                num_experts_per_tok=1, dropless=False):
    """Explicit expert-parallel dispatch: a ``shard_map`` program over the
    mesh in which the token exchange is two ``jax.lax.all_to_all`` calls.

    Per device (tokens sharded over ('data', 'expert'), weights over
    'expert' with the hidden dim Megatron-split over 'model' when it
    divides):

    1. route the n_loc local tokens (top-k, renormalized gates) and pack
       them into per-(destination-expert) capacity slots (E, C_loc, d),
       C_loc = ceil(cf*k*n_loc/E);
    2. ``all_to_all`` over 'expert' (split the expert dim, concat the
       capacity dim): each device now holds its OWN experts' full slot
       tables (E/ep, ep*C_loc, d), source-device-major in the capacity
       dim;
    3. run the local experts' FFNs — hidden dim sharded over 'model'
       with one psum, the Megatron pair;
    4. ``all_to_all`` back (split capacity, concat experts) and combine
       each kept token's k slots with its gates.

    Gradients differentiate through the region (the op-level custom_vjp
    below), so the backward program contains the same two exchanges
    reversed — d(combine) all-to-alls out to the experts, the FFN
    backward runs local, and d(dispatch) all-to-alls home — which is
    what the collective-budget pass pins in benchmarks/budgets.json.

    Token-identical (outputs, grads, drop set) to
    ``_moe_forward_sparse(..., num_groups=data_par*expert_par)``: group
    ordering, slot layout and capacity quotas match by construction
    (shared `_slot_assign`/`_pack_slots`/`_combine_slots` helpers).
    """
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map

    e = num_experts
    k = min(num_experts_per_tok, e)
    orig_shape = x.shape
    d = x.shape[-1]
    axes = dict(mesh.shape)
    dp = axes.get("data", 1)
    ep = axes["expert"]
    mp = axes.get("model", 1)
    h_dim = w1.shape[-1]

    xt = x.reshape(-1, d)
    n = xt.shape[0]
    n_loc = n // (dp * ep)
    cap = _capacity(capacity_factor, k, n_loc, e, dropless)
    model_ax = "model" if (mp > 1 and h_dim % mp == 0) else None
    # tokens shard over every axis that exists of (data, expert) —
    # hand-built meshes without a 'data' name still dispatch
    tok_axes = tuple(a for a in ("data", "expert") if a in axes)

    def local_moe(xt, wg, w1, b1, w2, b2):
        import jax.numpy as jnp

        probs = jax.nn.softmax(xt @ wg, axis=-1)
        choice, gate = _route(probs, k)
        _, keep, slot = _slot_assign(choice, e, cap)
        xd = _pack_slots(xt, slot, keep, e, cap)       # (E, C_loc, d)

        # dispatch: expert dim splits across the axis, capacity dims
        # concat source-device-major -> (E/ep, ep*C_loc, d) local tables
        xs = lax.all_to_all(xd, "expert", split_axis=0, concat_axis=1,
                            tiled=True)
        h = jnp.einsum("ecd,edh->ech", xs, w1) + b1[:, None, :]
        h = jnp.maximum(h, 0.0)
        ye = jnp.einsum("ech,ehd->ecd", h, w2)
        if model_ax is not None:
            ye = lax.psum(ye, model_ax)                # Megatron row-psum
        ye = ye + b2[:, None, :]
        # combine exchange: capacity splits back to source devices,
        # expert dim concats home -> (E, C_loc, d)
        ys = lax.all_to_all(ye, "expert", split_axis=1, concat_axis=0,
                            tiled=True)
        yt = _combine_slots(ys.reshape(e * cap, d), slot, keep, gate)

        frac, imp = _aux_terms(probs, choice, e)
        frac = lax.pmean(frac, tok_axes)
        imp = lax.pmean(imp, tok_axes)
        aux = (frac * imp).sum() * e
        return yt, aux

    tok_spec = tok_axes
    fn = shard_map(
        local_moe, mesh=mesh,
        in_specs=(P(tok_spec, None), P(None, None),
                  P("expert", None, model_ax), P("expert", model_ax),
                  P("expert", model_ax, None), P("expert", None)),
        out_specs=(P(tok_spec, None), P()),
        check_vma=False)
    yt, aux = fn(xt, wg, w1, b1, w2, b2)
    return yt.reshape(orig_shape), aux


def _sharded_ok(x, num_experts, mesh):
    """The explicit all-to-all path's static divisibility guards: an
    'expert' axis > 1, experts divisible over it, and the flattened
    token count divisible over (data x expert).  Indivisible configs
    degrade to the GSPMD-hint sparse path, never to wrong numbers."""
    if mesh is None:
        return False
    axes = dict(mesh.shape)
    ep = axes.get("expert", 1)
    if ep <= 1 or num_experts % ep != 0:
        return False
    n = 1
    for dim in x.shape[:-1]:
        n *= dim
    return n % (axes.get("data", 1) * ep) == 0


def register_all():
    import jax

    _wrapped = {}

    def _moe_with_aux_grad(num_experts, coeff, capacity_factor, mesh,
                           num_experts_per_tok, dropless):
        """custom_vjp wrapper: forward value is y alone; backward is the
        vjp of (y + coeff * aux_loss), i.e. training minimizes
        task_loss + coeff * balance_loss with exact gradients.  For the
        sharded sparse path the vjp differentiates through the shard_map
        region, so the backward program carries the two all-to-all
        exchanges in reverse."""
        # key by the mesh's VALUE (axes + device ids), not id(): id-keying
        # grows the cache (and pins a Mesh) for every rebind in a
        # long-running job; equal meshes share one traced closure
        mesh_key = None if mesh is None else (
            tuple(mesh.shape.items()),
            tuple(d.id for d in mesh.devices.flat))
        key = (num_experts, coeff, capacity_factor, mesh_key,
               num_experts_per_tok, dropless)
        fn = _wrapped.get(key)
        if fn is not None:
            return fn

        def fwd_impl(x, wg, w1, b1, w2, b2):
            if capacity_factor > 0 or dropless:
                if _sharded_ok(x, num_experts, mesh):
                    MOE_PATH["last"] = "sparse_a2a"
                    return _moe_forward_sparse_sharded(
                        x, wg, w1, b1, w2, b2, num_experts,
                        capacity_factor, mesh,
                        num_experts_per_tok=num_experts_per_tok,
                        dropless=dropless)
                MOE_PATH["last"] = "sparse"
                return _moe_forward_sparse(
                    x, wg, w1, b1, w2, b2, num_experts, capacity_factor,
                    mesh, num_experts_per_tok=num_experts_per_tok,
                    dropless=dropless)
            MOE_PATH["last"] = "dense"
            return _moe_forward(x, wg, w1, b1, w2, b2, num_experts,
                                num_experts_per_tok=num_experts_per_tok)

        @jax.custom_vjp
        def moe(x, wg, w1, b1, w2, b2):
            y, _ = fwd_impl(x, wg, w1, b1, w2, b2)
            return y

        def fwd(x, wg, w1, b1, w2, b2):
            y, _ = fwd_impl(x, wg, w1, b1, w2, b2)
            return y, (x, wg, w1, b1, w2, b2)

        def bwd(res, dy):
            import jax.numpy as jnp

            def total(x, wg, w1, b1, w2, b2):
                y, aux = fwd_impl(x, wg, w1, b1, w2, b2)
                return y, aux

            (_, aux), vjp = jax.vjp(total, *res)
            # cotangents must match the primal dtypes (aux follows inputs)
            return vjp((dy, jnp.asarray(coeff, dtype=aux.dtype)))

        moe.defvjp(fwd, bwd)
        _wrapped[key] = moe
        return moe

    def fcompute(attrs, inputs, aux, octx):
        from .. import config as _config

        # runtime knobs override the symbol's attributes at trace time
        # (flip routing/capacity/overflow without editing the model)
        k = int(_config.get("MXNET_MOE_TOPK")) \
            or int(attrs.get("num_experts_per_tok", 1))
        cf = float(_config.get("MXNET_MOE_CAPACITY")) \
            or float(attrs["capacity_factor"])
        dropless = bool(_config.get("MXNET_MOE_DROPLESS")) \
            or attrs.get("overflow", "drop") == "dropless"
        fn = _moe_with_aux_grad(attrs["num_experts"],
                                float(attrs["aux_loss_coeff"]),
                                cf, octx.mesh, k, dropless)
        return [fn(*inputs)], []

    register_op(OpDef(
        "MoEFFN", fcompute,
        schema=ParamSchema(
            Param("num_experts", int, required=True),
            Param("hidden_size", int, required=True),
            Param("aux_loss_coeff", float, default=0.01,
                  doc="weight of the Switch load-balancing loss folded "
                      "into the backward pass; 0 disables"),
            Param("capacity_factor", float, default=0.0,
                  doc="> 0 enables SPARSE capacity-based dispatch: each "
                      "expert processes at most ceil(cf*k*N_g/E) tokens "
                      "per token group (overflow drops, Switch "
                      "semantics, unless overflow='dropless') and the "
                      "per-step FLOPs are flat in num_experts; under an "
                      "'expert' mesh the dispatch is an explicit "
                      "all-to-all shard_map program (docs/moe.md); 0 "
                      "keeps the dense all-expert oracle"),
            Param("num_experts_per_tok", int, default=1,
                  doc="top-k routing: experts per token (gates "
                      "renormalized over the chosen k when k > 1; 1 = "
                      "classic switch top-1 with the raw probability "
                      "gate).  MXNET_MOE_TOPK overrides at trace time"),
            Param("overflow", str, default="drop",
                  doc="sparse-path overflow policy: 'drop' (Switch "
                      "semantics — past-capacity tokens emit zero and "
                      "ride the residual) or 'dropless' (capacity "
                      "stretches to the per-device worst case with a "
                      "padding mask, no drops ever).  "
                      "MXNET_MOE_DROPLESS=1 forces 'dropless'"),
        ),
        num_inputs=6,
        arguments=["data", "gate_weight", "expert1_weight",
                   "expert1_bias", "expert2_weight", "expert2_bias"],
        infer_shape=_moe_shape,
        mesh_axes={"expert1_weight": "expert", "expert1_bias": "expert",
                   "expert2_weight": "expert", "expert2_bias": "expert"},
        doc="Top-k-routed mixture-of-experts feed-forward.  "
            "Leapfrog op (SURVEY §2.5: expert parallelism 'not present'): "
            "expert-stacked weights (E, ...) shard on the 'expert' mesh "
            "axis; capacity_factor > 0 under an 'expert' mesh dispatches "
            "through the explicit all-to-all shard_map program; the "
            "Switch balance loss rides the backward pass "
            "(aux_loss_coeff)."),
        aliases=("_contrib_MoEFFN",))
