"""The operator registry.

TPU-native analog of the reference's two op registries (legacy
``MXNET_REGISTER_OP_PROPERTY`` `include/mxnet/operator.h:166` and NNVM
``NNVM_REGISTER_OP`` `include/mxnet/op_attr_types.h:59`), unified into one:
an :class:`OpDef` bundles

* a declarative parameter schema (`attrs.ParamSchema`, the dmlc::Parameter
  analog),
* ``fcompute`` — a pure JAX function ``(attrs, inputs, aux, octx) ->
  (outputs, new_aux)``; JAX tracing replaces the reference's separate
  CPU/GPU kernels, and jax AD replaces hand-written backward passes
  (loss-style ops install ``jax.custom_vjp`` internally),
* shape/type inference (explicit fn for ops whose *parameter* shapes must be
  deduced from data shapes; abstract-eval fallback otherwise),
* argument/output/aux naming for Symbol binding.

Every imperative invoke and every executor node dispatches through here.
Op-level fusion comes from caching ``jax.jit`` per (op, attrs, is_train):
this is the analog of the reference's engine pushing one compiled kernel
per op (`src/c_api/c_api_ndarray.cc:233` PushFCompute).
"""
from __future__ import annotations

import functools

from .attrs import FrozenAttrs, ParamSchema
from .base import MXNetError

__all__ = ["OpDef", "register", "get_op", "list_ops", "invoke", "OpContext"]

_OPS = {}


class OpContext:
    """Per-invocation execution context: train flag + PRNG key +
    whether the enclosing executor runs over a device mesh (ops with
    GSPMD-opaque fast paths, e.g. pallas kernels, bail out when set).
    ``mesh`` carries the executor's Mesh (or None) for ops that place
    sharding constraints themselves — e.g. sparse MoE dispatch pinning
    its expert-major tensors to the 'expert' axis."""

    __slots__ = ("is_train", "rng", "mesh_active", "mesh")

    def __init__(self, is_train=False, rng=None, mesh_active=False,
                 mesh=None):
        self.is_train = is_train
        self.rng = rng
        self.mesh_active = mesh_active
        self.mesh = mesh


def _default_arg_names(n):
    if n == 1:
        return ["data"]
    if n == 2:
        return ["lhs", "rhs"]
    return ["arg%d" % i for i in range(n)]


class OpDef:
    """A registered operator."""

    def __init__(
        self,
        name,
        fcompute,
        schema=None,
        num_inputs=1,
        num_outputs=1,
        num_visible_outputs=None,
        arguments=None,
        outputs=None,
        aux=None,
        infer_shape=None,
        infer_type=None,
        needs_rng=False,
        needs_train=False,
        key_var_num_args=None,
        hint=None,
        doc="",
        visible=True,
        mesh_axes=None,
        user_defined=False,
    ):
        self.name = name
        self.fcompute = fcompute
        self.schema = schema or ParamSchema()
        self.num_inputs = num_inputs  # int or callable(attrs) -> int
        self.num_outputs = num_outputs  # int or callable(attrs) -> int
        self.num_visible_outputs = num_visible_outputs  # defaults to num_outputs
        self._arguments = arguments
        self._outputs = outputs
        self._aux = aux
        self.infer_shape_fn = infer_shape
        self.infer_type_fn = infer_type
        self.needs_rng = needs_rng
        self.needs_train = needs_train
        self.key_var_num_args = key_var_num_args
        self.hint = hint or name.lstrip("_").lower()
        self.doc = doc
        self.visible = visible
        # runtime-registered user kernels (mx.rtc): exempt from the
        # first-party registry-coverage sweep
        self.user_defined = user_defined
        # {argument_name: mesh_axis} — weights whose leading dim belongs on
        # a named mesh axis (e.g. MoE expert stacks on 'expert'); the mesh
        # executor reads this to shard the bound variables (op-level
        # metadata, not parameter-name matching)
        self.mesh_axes = dict(mesh_axes or {})

    # -- introspection -----------------------------------------------------
    def n_inputs(self, attrs):
        n = self.num_inputs
        return n(attrs) if callable(n) else n

    def n_outputs(self, attrs):
        n = self.num_outputs
        return n(attrs) if callable(n) else n

    def n_visible_outputs(self, attrs):
        n = self.num_visible_outputs
        if n is None:
            return self.n_outputs(attrs)
        return n(attrs) if callable(n) else n

    def list_arguments(self, attrs):
        if self._arguments is not None:
            a = self._arguments
            return list(a(attrs)) if callable(a) else list(a)
        return _default_arg_names(self.n_inputs(attrs))

    def list_outputs(self, attrs):
        if self._outputs is not None:
            o = self._outputs
            return list(o(attrs)) if callable(o) else list(o)
        n = self.n_outputs(attrs)
        return ["output"] if n == 1 else ["output%d" % i for i in range(n)]

    def list_aux(self, attrs):
        if self._aux is None:
            return []
        a = self._aux
        return list(a(attrs)) if callable(a) else list(a)

    def parse_attrs(self, raw):
        return raw if isinstance(raw, FrozenAttrs) else self.schema.parse(raw)

    # -- shape/type inference ----------------------------------------------
    def infer_shape(self, attrs, in_shapes, aux_shapes=None):
        """Returns (in_shapes, out_shapes, aux_shapes); fills unknown inputs.

        Mirrors the nnvm InferShape pass contract
        (`src/executor/graph_executor.cc:425`).
        """
        if self.infer_shape_fn is not None:
            return self.infer_shape_fn(attrs, in_shapes, aux_shapes)
        if any(s is None for s in in_shapes):
            raise MXNetError(
                "Op %s cannot infer missing input shapes (got %s)" % (self.name, in_shapes)
            )
        out_shapes = self._abstract_eval_shapes(attrs, in_shapes)
        return in_shapes, out_shapes, aux_shapes or []

    def _abstract_eval_shapes(self, attrs, in_shapes, dtype="float32"):
        import jax
        import jax.numpy as jnp

        ins = [jax.ShapeDtypeStruct(tuple(s), jnp.float32) for s in in_shapes]

        def fn(*xs):
            octx = OpContext(is_train=False, rng=jax.random.PRNGKey(0) if self.needs_rng else None)
            outs, _ = self.fcompute(attrs, list(xs), [], octx)
            return outs

        outs = jax.eval_shape(fn, *ins)
        return [tuple(o.shape) for o in outs]

    def __repr__(self):
        return "OpDef(%s)" % self.name


def simple_compute(fn, num_outputs=1):
    """Adapt ``fn(attrs, *inputs) -> array|tuple`` to canonical fcompute."""

    def fcompute(attrs, inputs, aux, octx):
        out = fn(attrs, *inputs)
        if not isinstance(out, (tuple, list)):
            out = [out]
        return list(out), list(aux)

    return fcompute


def register(name, aliases=(), simple=True, **kwargs):
    """Decorator registering a compute function under ``name`` (+aliases)."""

    def deco(fn):
        fcompute = simple_compute(fn) if simple else fn
        opdef = OpDef(name, fcompute, **kwargs)
        _register_opdef(opdef, aliases)
        return fn

    return deco


def _register_opdef(opdef, aliases=()):
    _OPS[opdef.name] = opdef
    for a in aliases:
        _OPS[a] = opdef
    return opdef


def register_op(opdef, aliases=()):
    return _register_opdef(opdef, aliases)


def get_op(name):
    op = _OPS.get(name)
    if op is None:
        raise MXNetError("Operator %s is not registered" % name)
    return op


def has_op(name):
    return name in _OPS


def list_ops():
    return sorted(_OPS.keys())


# ---------------------------------------------------------------------------
# Cached jit dispatch — the imperative fast path.
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _jitted(opdef, attrs, is_train, n_aux, with_rng):
    import jax

    def run(inputs, aux, rng):
        octx = OpContext(is_train=is_train, rng=rng)
        outs, new_aux = opdef.fcompute(attrs, list(inputs), list(aux), octx)
        return list(outs), list(new_aux)

    return jax.jit(run)


_DUMMY_KEY = None


def _dummy_key():
    global _DUMMY_KEY
    if _DUMMY_KEY is None:
        import jax

        _DUMMY_KEY = jax.random.PRNGKey(0)
    return _DUMMY_KEY


def invoke(opdef, inputs, attrs=None, is_train=False, rng=None, aux=()):
    """Execute an op on raw jax arrays. Returns (outputs, new_aux).

    The analog of MXImperativeInvoke (`src/c_api/c_api_ndarray.cc:322`):
    dispatch is async (XLA), results are futures the same way engine-tracked
    NDArrays are.
    """
    attrs = opdef.parse_attrs(attrs or {})
    if rng is None and opdef.needs_rng:
        from . import random as _rnd

        rng = _rnd.split_key()
    if rng is None:
        # unused placeholder, keeps the jit signature static without paying a
        # per-call PRNGKey device allocation
        rng = _dummy_key()
    fn = _jitted(opdef, attrs, bool(is_train), len(aux), opdef.needs_rng)
    from . import profiler as _prof

    if _prof.is_running():
        # per-op dispatch span, the engine OprExecStat analog
        with _prof.Scope(opdef.name, "imperative"):
            return fn(list(inputs), list(aux), rng)
    return fn(list(inputs), list(aux), rng)
