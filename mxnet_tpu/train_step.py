"""CompiledTrainStep — the whole training step as ONE donated XLA program.

TPU-native analog of the reference's bulk-exec segments
(`src/executor/graph_executor.cc:678-756`), taken to its conclusion: where
the reference fuses forward/backward node sequences into single engine ops
but leaves the optimizer as separate per-parameter kernels
(`python/mxnet/optimizer.py` dispatching `sgd_mom_update` etc.), here
forward + backward + optimizer + aux-state update compile into a single
``jax.jit`` with ``donate_argnums`` on parameters / optimizer slots / aux —
XLA reuses their buffers in place, so the steady-state step does no
allocation and no host round-trips.

Mixed precision: master weights and optimizer slots stay float32 on device;
when ``compute_dtype`` (e.g. bfloat16) is set, parameters and input data are
cast once at program entry, the graph (matmuls/convs on the MXU) runs in the
compute dtype, and gradients are cast back to float32 before the optimizer.
Ops with precision-critical internals (BatchNorm statistics, softmax)
compute in float32 regardless.

State lives here as jax arrays, not NDArrays — Module flushes it back into
the executor's NDArray buffers only at eval/checkpoint boundaries.
"""
from __future__ import annotations

import logging
import pickle
import time

import numpy as np

from . import obs as _obs
from .base import MXNetError

__all__ = ["CompiledTrainStep", "CompiledEvalStep"]


def _weak_prober(step):
    """A roofline static-cost prober that does NOT pin the step object
    (and transitively its executor group + master weights) in the
    process-global accounting: once the step is collected, the prober
    resolves to None and the program's row simply keeps no statics."""
    import weakref

    ref = weakref.ref(step)

    def prober():
        live = ref()
        return live.roofline_static() if live is not None else None

    return prober


class CompiledEvalStep:
    """Forward-only executor program with device-side metric accumulation.

    The eval/score counterpart of the train loop's device metrics (ROADMAP
    PR-3 open item): one jitted program runs the inference forward AND
    folds the metric's ``device_update`` into donated ``(sum, count)``
    accumulator state, so ``score()`` performs no per-batch device→host
    transfer — the classic path pays 2 (label + pred materialization in
    ``metric.update``) per batch.  Reading the metric drains lazily via
    the ``DeviceMetricAccumulator`` hooks, exactly like the train side;
    :meth:`finish` uninstalls them (folding what's pending) when the eval
    pass ends.

    Raises ``MXNetError`` from the constructor when this metric/graph
    combination can't accumulate on device (host path is the fallback);
    the first ``run`` validates the trace with ``jax.eval_shape`` and
    raises likewise before anything is donated.
    """

    def __init__(self, exec_group, metric):
        from .metric import DeviceMetricAccumulator

        # retrace instrumentation (analysis.RetracePass): the python body
        # below runs only while jax traces it, so this counter is the
        # ground truth for "the eval program traced exactly once" (the
        # eval_shape validation probe shares the jit trace cache, so it
        # IS that one trace).  artifact() lowering sets _probing so probe
        # re-traces don't count as cache misses.
        self.trace_count = 0
        self._probing = False
        exe = exec_group.exec_
        self._group = exec_group
        self._exec = exe
        self._data_names = list(exec_group.data_names)
        self._label_names = [n for n in exec_group.label_names
                             if n in exe.arg_dict]
        if len(self._label_names) != len(exec_group.label_names):
            # the program only sees labels the graph consumes; extra
            # iterator labels would shift the host pairing (same rule as
            # CompiledTrainStep.attach_metric)
            raise MXNetError("graph does not consume every label input; "
                             "metric pairing would differ from the host "
                             "path")
        self._param_names = [n for n in exe._arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        try:
            self._acc = DeviceMetricAccumulator(metric)
        except ValueError as exc:
            raise MXNetError(str(exc))
        self._acc.install()
        self._validated = False

        import jax

        acc = self._acc
        label_names = self._label_names
        param_names = self._param_names

        def step(params, aux, mstate, data, rng):
            if not self._probing:
                self.trace_count += 1
            env = dict(zip(param_names, params))
            env.update(data)
            arg_vals = [env[n] for n in exe._arg_names]
            outs, _ = exe._fwd_impl(arg_vals, aux, rng, False)
            labels = [data[n] for n in label_names]
            return acc.update(mstate, labels, list(outs))

        self._fn = jax.jit(step, donate_argnums=(2,))
        self._last_args = None   # aval snapshot for artifact probes
        self._snap_traces = -1   # trace_count the snapshot was taken at
        self._static_registered = False  # roofline prober armed once

    def _place(self, arr, name):
        import jax

        from . import ndarray as _nd

        group = self._group
        dst = group.exec_.arg_dict.get(name)
        v = arr.data if isinstance(arr, _nd.NDArray) else np.asarray(arr)
        if dst is not None and v.dtype != dst.data.dtype:
            v = v.astype(dst.data.dtype)
        if group._mesh is not None:
            return jax.device_put(v, group._input_sharding(name))
        return jax.device_put(v, group.contexts[0].jax_device)

    # telemetry: the roofline row this program's dispatch wall accrues to
    telemetry_name = "eval_step"

    def run(self, data_batch):
        """Accumulate one batch on device.  No host transfer happens here;
        the metric's accumulator state is donated through the program.
        Dispatch wall time feeds the per-program roofline table
        (``obs.programs``) — host-side only, the program is untouched."""
        if not _obs.enabled():
            return self._run_impl(data_batch)
        if not self._static_registered:
            self._static_registered = True
            _obs.programs.register_static(self.telemetry_name,
                                          _weak_prober(self))
        t0 = time.perf_counter()
        w0 = time.time()
        try:
            return self._run_impl(data_batch)
        finally:
            dt = time.perf_counter() - t0
            _obs.programs.note(self.telemetry_name, dt)
            _obs.timeline.add_span(self.telemetry_name, w0, dt,
                                   cat="program")

    def _run_impl(self, data_batch):
        from . import random as _rnd

        exe = self._exec
        data = {}
        for name, arr in zip(self._group.data_names, data_batch.data):
            data[name] = self._place(arr, name)
        if data_batch.label:
            for name, arr in zip(self._group.label_names, data_batch.label):
                if name in self._label_names:
                    data[name] = self._place(arr, name)
        missing = [n for n in self._data_names + self._label_names
                   if n not in data]
        if missing:
            raise MXNetError("eval batch is missing inputs %s" % missing)
        params = [exe.arg_dict[n].data for n in self._param_names]
        aux = [exe.aux_dict[n].data for n in exe._aux_names]
        rng = _rnd.split_key()
        if not self._validated:
            import jax

            # trace-only probe: a metric mirror this graph rejects must
            # fail BEFORE the donated accumulator state is consumed.  It
            # COUNTS as the program's one trace — eval_shape on a jitted
            # fn populates the same trace cache the real call hits.
            jax.eval_shape(self._fn, params, aux, self._acc.state, data,
                           rng)
            self._validated = True
        if self._last_args is None or self._snap_traces != self.trace_count:
            # aval snapshot for artifact probes — (re)built only when no
            # snapshot exists or the program re-traced, not per batch
            import jax
            import jax.tree_util as jtu

            from .analysis.artifact import aval_of

            def _bare(x):
                # accumulator scalars stay sharding-free: they are
                # re-seeded uncommitted after drains and relocate with
                # the program
                return jax.ShapeDtypeStruct(x.shape, x.dtype)

            self._last_args = (
                jtu.tree_map(aval_of, params), jtu.tree_map(aval_of, aux),
                jtu.tree_map(_bare, self._acc.state),
                jtu.tree_map(aval_of, data), aval_of(rng))
            self._snap_traces = self.trace_count
        self._acc.commit(self._fn(params, aux, self._acc.state, data, rng))

    def finish(self):
        """Fold pending device sums into the host metric and detach the
        hooks — call when the eval pass ends (or falls back mid-way)."""
        self._acc.uninstall()

    def rearm(self):
        """Re-install the metric hooks for another eval pass over the same
        compiled program (fit's per-epoch validation reuses one step
        instead of recompiling every epoch)."""
        self._acc.install()
        return self

    def artifact(self, name="eval_step"):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        eval program at the last-run shapes (None before the first
        ``run``).  Same probe economics as ``compiled_hlo``: avals only,
        throwaway compile, trace flagged as non-counting."""
        import jax.tree_util as jtu

        from .analysis.artifact import artifact_from_jit

        if self._last_args is None:
            return None
        params, aux, mstate, data, rng = self._last_args
        donated = len(jtu.tree_leaves(mstate))
        count = self.trace_count
        self._probing = True
        try:
            return artifact_from_jit(
                self._fn, (params, aux, mstate, data, rng), name=name,
                donated_leaves=donated, trace_count=count,
                expected_traces=1,
                metric=type(self._acc.metric).__name__)
        finally:
            self._probing = False

    def roofline_static(self):
        """Static FLOPs + traffic bytes of the eval program at the
        last-run shapes (None before the first ``run``) — the lazy
        roofline join, trace+lower only, probe-flagged so it never
        counts as a retrace."""
        from .analysis.cost import program_cost

        if self._last_args is None:
            return None
        self._probing = True
        try:
            return program_cost(self._fn, self._last_args)
        finally:
            self._probing = False


class CompiledTrainStep:
    """One master-weight store + per-executor-group compiled step programs.

    Bucketed training shares a single instance across all bucket modules:
    each bucket's shape-specialized executor gets its own jitted program
    (``_entry_for``), but every program reads and donates the same
    params/slots/aux dicts — the analog of the reference's shared memory
    pools across bucket executors (bucketing_module.py:18-120) extended to
    the fused update path.
    """

    def __init__(self, exec_group, optimizer, compute_dtype=None):
        import jax.numpy as jnp

        kernel = optimizer.fused_kernel()
        if kernel is None:
            raise MXNetError("optimizer %s has no fused kernel"
                             % type(optimizer).__name__)
        self._make_slots, self._opt_apply = kernel
        self._optimizer = optimizer
        self._group = exec_group
        self._exec = exec_group.exec_

        exe = self._exec
        self._data_names = list(exec_group.data_names)
        self._label_names = [n for n in exec_group.label_names
                             if n in exe.arg_dict]
        self._param_names = [n for n in exe._arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        # only params with a gradient request get optimizer updates; fixed
        # params ride along as forward inputs
        self._grad_names = [n for n in self._param_names
                            if exe.grad_req.get(n, "null") == "write"]
        unsupported = [n for n in self._param_names
                       if exe.grad_req.get(n, "null") not in ("null", "write")]
        if unsupported:
            raise MXNetError("fused train step supports grad_req "
                             "null/write only; got add for %s" % unsupported)
        self._aux_names = list(exe._aux_names)
        # optimizer bookkeeping (update counts, lr_mult) is keyed by the
        # param's index in the executor group, matching the eager path
        self._grad_indices = [exec_group.param_names.index(n)
                              for n in self._grad_names]

        if compute_dtype in (None, "", "float32", np.float32):
            self._cdtype = None
        else:
            self._cdtype = jnp.dtype(compute_dtype)

        # own copies: the first donated step invalidates its input buffers,
        # and the executor's NDArrays must keep theirs
        self.params = {n: jnp.copy(exe.arg_dict[n].data)
                       for n in self._param_names}
        self.aux = {n: jnp.copy(exe.aux_dict[n].data) for n in self._aux_names}
        self.reset_slots()
        # compiled programs keyed by executor identity (the value holds a
        # strong ref to the executor so a GC'd id can't alias a new one);
        # a reshape rebuilds group.exec_, so the stale program is skipped
        # device-side metric accumulation: when a DeviceMetricAccumulator is
        # attached, its state rides the program as EXTRA DONATED STATE and
        # the per-step device->host output read disappears (metric.py).
        # _metric_traced_ids tracks which executors' programs have traced
        # the metric successfully — per executor, because a shared store
        # compiles one program per bucket and a later bucket's graph may
        # still reject the metric's device mirror
        self._metric_acc = None
        self._metric_traced_ids = set()
        self._metric_rejected = None  # metric whose device mirror failed
        # retrace instrumentation (analysis.RetracePass): the step body
        # increments trace_count only while jax traces it; every program
        # (re)build bumps programs_built, so trace_count > programs_built
        # means a jit cache miss at an already-built signature — dtype /
        # weak-type drift.  compiled_hlo/artifact lowerings set _probing
        # and don't count (the metric eval_shape probe does: it shares
        # the trace cache the real call hits).
        self.trace_count = 0
        self.programs_built = 0
        self._probing = False
        self._fns = {}
        self._fn = self._build(exec_group)
        self._fns[id(exec_group.exec_)] = (self._fn, exec_group.exec_)
        self.num_steps = 0
        self._hyper_cache = None
        self._static_registered = False  # roofline prober armed once
        # lifecycle state is a property of the shared store, not of any one
        # module (several bucket modules may view this step)
        self.step_stale = False   # executor buffers newer than the store
        self.exec_stale = False   # store newer than executor buffers
        self.opt_owner = "eager"  # who holds live optimizer slots

    def compatible(self, group):
        """Whether a (bucket) executor group can train through this store.

        Requires every master param/aux to be the *same shared buffer* as
        the primary executor's (shared binding shares identity when shapes
        match), and no extra trainable params.  Buckets with shape-varying
        params (the reference lets those be per-bucket copies) must use the
        eager path instead."""
        exe = group.exec_
        prim = self._exec
        for n in self._param_names:
            if exe.arg_dict.get(n) is not prim.arg_dict[n]:
                return False
        for n in self._aux_names:
            if exe.aux_dict.get(n) is not prim.aux_dict[n]:
                return False
        data_like = set(group.data_names) | set(group.label_names)
        for n in exe._arg_names:
            if n not in data_like and n not in self._param_names:
                return False
        return True

    def _entry_for(self, group):
        """The compiled step program for a (bucket) executor group, built on
        first use.  The group must expose the same parameter set — shared
        binding guarantees it for BucketingModule."""
        exe = group.exec_
        hit = self._fns.get(id(exe))
        if hit is not None and hit[1] is exe:
            return hit[0]
        if not self.compatible(group):
            raise MXNetError(
                "bucket executor's parameter set is not shared with the "
                "master store; demote this bucket to the eager path")
        fn = self._build(group)
        self._fns[id(exe)] = (fn, exe)
        return fn

    # ------------------------------------------------------------------
    # device-side metrics
    # ------------------------------------------------------------------
    def attach_metric(self, metric):
        """Fold ``metric``'s accumulation into the step program as donated
        state.  Returns True when armed; False when this metric (or this
        graph's label routing) can't accumulate on device — the caller then
        stays on the host ``update_metric`` path.  Idempotent per metric."""
        from .metric import DeviceMetricAccumulator

        if self._metric_acc is not None and self._metric_acc.metric is metric:
            return True
        if metric is self._metric_rejected:
            return False  # its device mirror already failed to trace once
        if not DeviceMetricAccumulator.supported(metric):
            return False
        # the step only sees labels the graph consumes; if the iterator
        # feeds extra labels the host pairing would differ — stay on host
        if len(self._label_names) != len(self._group.label_names):
            return False
        self.detach_metric()
        self._metric_acc = DeviceMetricAccumulator(metric)
        self._metric_acc.install()
        self._metric_traced_ids = set()
        self._fns = {}  # program signature changed: recompile per executor
        return True

    def detach_metric(self):
        """Drain pending device accumulation and drop the metric from the
        program (fused->eager handoff, monitor installation, re-init)."""
        if self._metric_acc is None:
            return
        self._metric_acc.uninstall()
        self._metric_acc = None
        self._metric_traced_ids = set()
        self._fns = {}

    # ------------------------------------------------------------------
    def _build(self, group):
        import jax
        import jax.numpy as jnp

        exe = group.exec_
        cdtype = self._cdtype
        data_names = self._data_names
        grad_names = self._grad_names
        aux_names = self._aux_names
        opt_apply = self._opt_apply
        label_names = self._label_names
        macc = self._metric_acc

        def cast(v):
            if cdtype is not None and jnp.issubdtype(v.dtype, jnp.floating):
                return v.astype(cdtype)
            if v.dtype == jnp.uint8:
                # uint8 data = image bytes shipped compact (4x less h2d;
                # ImageIter dtype="uint8"): cast on DEVICE to the compute
                # dtype.  Integer label/id inputs keep their dtype — they
                # arrive as s32/f32, never u8.
                return v.astype(cdtype if cdtype is not None
                                else jnp.float32)
            return v

        def step(params, slots, aux, mstate, data, lrs, wds, rescale, clip,
                 extra, rng):
            if not self._probing:
                self.trace_count += 1
            castp = {n: cast(v) for n, v in params.items()}
            # labels keep their dtype (integer class ids beyond bf16's exact
            # range must survive); only data inputs are cast
            datac = {n: (cast(v) if n in data_names else v)
                     for n, v in data.items()}

            def fwd(gvals):
                env = dict(castp)
                env.update(zip(grad_names, gvals))
                env.update(datac)
                outs, new_aux = exe._run_graph(env, aux, rng, True)
                return outs, [new_aux[n] for n in aux_names]

            gvals = [castp[n] for n in grad_names]
            outs, vjp_fn, new_aux_vals = jax.vjp(fwd, gvals, has_aux=True)
            cts = [jnp.ones_like(o) for o in outs]
            (grads,) = vjp_fn(cts)

            new_params = dict(params)
            new_slots = {}
            for i, n in enumerate(grad_names):
                g = grads[i].astype(params[n].dtype)
                w, s = opt_apply(params[n], g, slots[n],
                                 lrs[i], wds[i], rescale, clip, extra)
                # float32 hyper scalars promote fp16/bf16 masters; cast the
                # update back so param dtypes are stable across steps
                new_params[n] = w.astype(params[n].dtype)
                new_slots[n] = tuple(
                    s_new.astype(s_old.dtype)
                    for s_new, s_old in zip(s, slots[n]))
            new_aux = {n: v.astype(aux[n].dtype)
                       for n, v in zip(aux_names, new_aux_vals)}
            if macc is not None:
                # metric accumulation reads the SAME outputs/labels the host
                # path would; it feeds nothing back into the training math
                labels = [data[n] for n in label_names]
                mstate = macc.update(mstate, labels, list(outs))
            return new_params, new_slots, new_aux, outs, mstate

        self.programs_built += 1
        return jax.jit(step, donate_argnums=(0, 1, 2, 3))

    # telemetry: the roofline row this program's dispatch wall accrues to
    # (one shared store = one row, however many bucket executors)
    telemetry_name = "train_step"

    # ------------------------------------------------------------------
    def run(self, data_batch, group=None):
        """Execute one full training step; returns output jnp arrays.

        ``group`` selects the (bucket) executor whose graph to run; the
        master weights/slots are this store's regardless.  Dispatch wall
        time feeds the per-program roofline table (``obs.programs``) —
        host-side timing only, the compiled program is byte-identical
        with telemetry on or off (tests/test_obs.py pins it).
        """
        if not _obs.enabled():
            return self._run_impl(data_batch, group)
        if not self._static_registered:
            self._static_registered = True
            _obs.programs.register_static(self.telemetry_name,
                                          _weak_prober(self))
        t0 = time.perf_counter()
        w0 = time.time()
        try:
            return self._run_impl(data_batch, group)
        finally:
            dt = time.perf_counter() - t0
            _obs.programs.note(self.telemetry_name, dt)
            _obs.timeline.add_span(self.telemetry_name, w0, dt,
                                   cat="program")

    def _run_impl(self, data_batch, group=None):
        from . import random as _rnd

        group = group if group is not None else self._group
        fn = self._entry_for(group)
        label_names = [n for n in group.label_names
                       if n in group.exec_.arg_dict]
        data = {}
        for name, arr in zip(group.data_names, data_batch.data):
            data[name] = self._place(arr, name, group)
        if label_names and data_batch.label:
            # zip the *unfiltered* group label list so an unconsumed early
            # label cannot shift later labels onto the wrong arrays; names
            # the symbol doesn't take are skipped in-loop (same alignment
            # rule as DataParallelExecutorGroup.forward)
            for name, arr in zip(group.label_names, data_batch.label):
                if name in label_names:
                    data[name] = self._place(arr, name, group)

        lrs, wds, rescale, clip = self._optimizer.fused_hyper(self._grad_indices)
        extra = self._optimizer.fused_extra()
        # keep hyper-params resident on device across steps: with a constant
        # schedule this is one transfer total instead of one per step
        cached = self._hyper_cache
        if cached is not None and np.array_equal(cached[0], lrs) \
                and np.array_equal(cached[1], wds) \
                and cached[2] == rescale and cached[3] == clip \
                and np.array_equal(cached[4], extra):
            lrs, wds, rescale, clip, extra = cached[5]
        else:
            import jax

            where = group._rep_sharding if group._mesh is not None \
                else group.contexts[0].jax_device
            dev = tuple(jax.device_put(v, where)
                        for v in (lrs, wds, rescale, clip, extra))
            self._hyper_cache = (lrs, wds, rescale, clip, extra, dev)
            lrs, wds, rescale, clip, extra = dev
        rng = _rnd.split_key()
        acc = self._metric_acc
        mstate = acc.state if acc is not None else ()
        if acc is not None and id(group.exec_) not in self._metric_traced_ids:
            # validate the metric's device mirror by TRACING ONLY
            # (eval_shape executes nothing, so no donated buffer is at
            # stake); a mirror that can't trace against this graph — shape
            # pairing, unsupported op, ... — demotes the metric to the
            # host path instead of failing the step.  Real execution
            # errors below propagate untouched.
            import jax

            # (the probe trace is the program's one trace — eval_shape on
            # a jitted fn populates the cache the real call below hits)
            try:
                jax.eval_shape(fn, self.params, self.slots, self.aux,
                               mstate, data, lrs, wds, rescale, clip,
                               extra, rng)
                self._metric_traced_ids.add(id(group.exec_))
            except Exception as exc:
                logging.getLogger(__name__).info(
                    "device metric accumulation unavailable (%s); metric "
                    "stays on the host path", exc)
                self._metric_rejected = acc.metric  # don't re-attach
                self.detach_metric()
                acc, mstate = None, ()
                fn = self._entry_for(group)
        self.params, self.slots, self.aux, outs, mstate = fn(
            self.params, self.slots, self.aux, mstate, data, lrs, wds,
            rescale, clip, extra, rng)
        if acc is not None:
            acc.commit(mstate)
        self.num_steps += 1
        return outs

    def _abstract_args(self, group):
        """Aval pytree of the step program's arguments, rebuilt from the
        live master store and the executor's bound input buffers (None
        before the first ``run``).  Shared by the ``compiled_hlo`` and
        ``artifact`` probes so nothing extra is retained on the hot path.
        """
        import jax

        from . import random as _rnd
        from .analysis.artifact import aval_of as _aval

        if self._hyper_cache is None:
            return None  # never run: no hyper avals to rebuild

        params = {n: _aval(v) for n, v in self.params.items()}
        slots = {n: tuple(_aval(s) for s in v)
                 for n, v in self.slots.items()}
        aux = {n: _aval(v) for n, v in self.aux.items()}
        exe = group.exec_
        label_names = [n for n in group.label_names if n in exe.arg_dict]
        data = {}
        for name in list(group.data_names) + label_names:
            v = exe.arg_dict[name].data
            if group._mesh is not None:
                sharding = group._input_sharding(name)
            else:
                sharding = v.sharding
            data[name] = jax.ShapeDtypeStruct(v.shape, v.dtype,
                                              sharding=sharding)
        lrs, wds, rescale, clip, extra = map(_aval, self._hyper_cache[5])
        import jax.tree_util as jtu

        # metric accumulator avals carry NO sharding: after a drain the
        # accumulator is re-seeded as uncommitted default-device scalars,
        # which the real call relocates freely — snapshotting that
        # placement into a committed aval would clash with mesh-sharded
        # params at lower() time
        mstate = () if self._metric_acc is None or \
            self._metric_acc.state is None \
            else jtu.tree_map(lambda x: jax.ShapeDtypeStruct(x.shape,
                                                             x.dtype),
                              self._metric_acc.state)
        # peek the key chain for its aval — a probe must not advance the
        # global RNG (split_key() here would shift every later step's
        # randomness and break bit-reproducibility around the probe)
        rng = _aval(_rnd._key())
        return (params, slots, aux, mstate, data, lrs, wds, rescale, clip,
                extra, rng)

    def compiled_hlo(self, group=None):
        """Optimized-HLO text of the fused train-step program (None before
        the first ``run``).

        Same probe surface as ``Executor.compiled_hlo`` — feed it to
        ``parallel.hlo_stats.collective_stats`` — but over the program
        that actually trains: forward + backward + optimizer in the one
        donated jit.  The lowering compiles a throwaway copy of the
        program (cached jit executables are keyed by concrete arrays, not
        avals), so this is a probe, not a free read.
        """
        group = group if group is not None else self._group
        args = self._abstract_args(group)
        if args is None:
            return None
        fn = self._entry_for(group)
        self._probing = True
        try:
            return fn.lower(*args).compile().as_text()
        finally:
            self._probing = False

    def artifact(self, name="train_step", group=None):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        fused step — jaxpr + lowered StableHLO + compiled HLO + the
        donation/retrace/dtype metadata the analysis passes check (None
        before the first ``run``)."""
        import jax.tree_util as jtu

        from .analysis.artifact import artifact_from_jit

        group = group if group is not None else self._group
        args = self._abstract_args(group)
        if args is None:
            return None
        fn = self._entry_for(group)
        params, slots, aux, mstate = args[0], args[1], args[2], args[3]
        donated = len(jtu.tree_leaves((params, slots, aux, mstate)))
        mesh_shape = dict(group._mesh.shape) if group._mesh is not None \
            else None
        count, built = self.trace_count, self.programs_built
        self._probing = True
        try:
            return artifact_from_jit(
                fn, args, name=name, donated_leaves=donated,
                compute_dtype=str(self._cdtype) if self._cdtype is not None
                else None,
                mesh_shape=mesh_shape, trace_count=count,
                expected_traces=built, num_steps=self.num_steps)
        finally:
            self._probing = False

    def roofline_static(self, group=None):
        """Static FLOPs + traffic bytes of the fused step program at the
        live shapes (None before the first ``run``) — the lazy roofline
        join for ``obs.programs``.  Trace+lower only (no compile, no
        execution), probe-flagged so it never counts as a retrace."""
        from .analysis.cost import program_cost

        group = group if group is not None else self._group
        args = self._abstract_args(group)
        if args is None:
            return None
        fn = self._entry_for(group)
        self._probing = True
        try:
            return program_cost(fn, args)
        finally:
            self._probing = False

    def _place(self, arr, name, group=None):
        import jax

        group = group if group is not None else self._group
        dst = group.exec_.arg_dict.get(name)
        v = arr.data
        if dst is not None and v.dtype != dst.data.dtype:
            v = v.astype(dst.data.dtype)
        if group._mesh is not None:
            # per-input rule: honors seq-axis (time) sharding from layouts
            return jax.device_put(v, group._input_sharding(name))
        return jax.device_put(v, group.contexts[0].jax_device)

    # ------------------------------------------------------------------
    # state exchange with the NDArray world
    # ------------------------------------------------------------------
    def flush_to_executor(self):
        """Write master params/aux back into the executor's NDArray buffers
        (copies — the step will donate its own buffers next run)."""
        import jax.numpy as jnp

        exe = self._exec
        for n in self._param_names:
            exe.arg_dict[n]._set_data(
                jnp.copy(self.params[n]).astype(exe.arg_dict[n].data.dtype))
        for n in self._aux_names:
            exe.aux_dict[n]._set_data(
                jnp.copy(self.aux[n]).astype(exe.aux_dict[n].data.dtype))

    def load_from_executor(self):
        """Re-seed step state from the executor (after set_params etc.)."""
        import jax.numpy as jnp

        exe = self._exec
        for n in self._param_names:
            self.params[n] = jnp.copy(exe.arg_dict[n].data)
        for n in self._aux_names:
            self.aux[n] = jnp.copy(exe.aux_dict[n].data)

    def get_states(self):
        """Serialized optimizer slots (save_optimizer_states payload)."""
        host = {n: tuple(np.asarray(s) for s in slots)
                for n, slots in self.slots.items()}
        return pickle.dumps(host)

    def set_states(self, payload):
        """Load optimizer slots.  Accepts both the fused format (keyed by
        param name, numpy tuples) and the eager Updater format (keyed by the
        param's index in the executor group, NDArray-valued)."""
        import jax.numpy as jnp

        host = pickle.loads(payload)
        index_names = {i: n for i, n in enumerate(self._group.param_names)}
        for key, state in host.items():
            name = index_names.get(key, key) if isinstance(key, int) else key
            if name not in self.slots:
                continue
            self.slots[name] = self._state_to_slots(state, jnp)

    @staticmethod
    def _state_to_slots(state, jnp):
        """Eager create_state values -> fused slot tuple: None -> (),
        single array -> 1-tuple, tuple -> tuple (NDArrays unwrapped)."""
        def leaf(v):
            return jnp.asarray(v.data if hasattr(v, "data") else v)

        if state is None:
            return ()
        if isinstance(state, (tuple, list)):
            return tuple(leaf(s) for s in state)
        return (leaf(state),)

    def reset_slots(self):
        """Synthesize fresh (zero-moment) optimizer slots for the CURRENT
        params — a slot-less checkpoint restored into a training module
        must not keep the moments of the weights it replaced."""
        self.slots = {n: self._make_slots(self.params[n])
                      for n in self._grad_names}

    def import_updater_states(self, states, param_names):
        """Seed slots from an eager Updater's state dict (index- or
        name-keyed) when the module switches eager -> fused mid-training."""
        import jax.numpy as jnp

        index_names = {i: n for i, n in enumerate(param_names)}
        for key, state in states.items():
            name = index_names.get(key, key) if isinstance(key, int) else key
            if name in self.slots:
                self.slots[name] = self._state_to_slots(state, jnp)

    def export_updater_states(self, updater, param_names, ctx):
        """Hand the fused slots to an eager Updater (fused -> eager switch:
        install_monitor, manual update() loop) so momentum carries over."""
        import jax.numpy as jnp

        from . import ndarray as _nd

        for idx, name in enumerate(param_names):
            if name not in self.slots:
                continue
            arrays = [_nd.NDArray(jnp.copy(s), ctx)
                      for s in self.slots[name]]
            updater.states[idx] = self._optimizer.pack_state(arrays)
