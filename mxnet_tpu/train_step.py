"""CompiledTrainStep — the whole training step as ONE donated XLA program.

TPU-native analog of the reference's bulk-exec segments
(`src/executor/graph_executor.cc:678-756`), taken to its conclusion: where
the reference fuses forward/backward node sequences into single engine ops
but leaves the optimizer as separate per-parameter kernels
(`python/mxnet/optimizer.py` dispatching `sgd_mom_update` etc.), here
forward + backward + optimizer + aux-state update compile into a single
``jax.jit`` with ``donate_argnums`` on parameters / optimizer slots / aux —
XLA reuses their buffers in place, so the steady-state step does no
allocation and no host round-trips.

Mixed precision: master weights and optimizer slots stay float32 on device;
when ``compute_dtype`` (e.g. bfloat16) is set, parameters and input data are
cast once at program entry, the graph (matmuls/convs on the MXU) runs in the
compute dtype, and gradients are cast back to float32 before the optimizer.
Ops with precision-critical internals (BatchNorm statistics, softmax)
compute in float32 regardless.

State lives here as jax arrays, not NDArrays — Module flushes it back into
the executor's NDArray buffers only at eval/checkpoint boundaries.
"""
from __future__ import annotations

import functools
import logging
import pickle
import time

import numpy as np

from . import obs as _obs
from .base import MXNetError

__all__ = ["CompiledTrainStep", "CompiledEvalStep"]


def _weak_prober(step):
    """A roofline static-cost prober that does NOT pin the step object
    (and transitively its executor group + master weights) in the
    process-global accounting: once the step is collected, the prober
    resolves to None and the program's row simply keeps no statics."""
    import weakref

    ref = weakref.ref(step)

    def prober():
        live = ref()
        return live.roofline_static() if live is not None else None

    return prober


def _weak_update_prober(step):
    """The ``opt_update`` roofline row's static prober: the optimizer
    phase's priced HBM bytes on the path this step ACTUALLY runs
    (``ops.pallas_update.priced_update_cost_for_step``) — so arming
    MXNET_PALLAS_UPDATE visibly moves the row.  FLOPs are zero by
    construction (the phase is pure traffic); both paths' bytes ride
    along so the table's consumer can show the comparison.  Weakly
    bound, same lifetime rule as :func:`_weak_prober`."""
    import weakref

    ref = weakref.ref(step)

    def prober():
        live = ref()
        if live is None:
            return None
        from .ops.pallas_update import priced_update_cost_for_step

        priced = priced_update_cost_for_step(live)
        if priced is None:
            return None
        armed = live._plan is not None
        return {"flops": 0,
                "bytes": priced["fused_bytes" if armed
                                else "per_param_bytes"],
                "update_path": "pallas" if armed else "xla",
                "per_param_bytes": priced["per_param_bytes"],
                "fused_bytes": priced["fused_bytes"]}

    return prober


def _weak_fused_prober(step):
    """The ``lm_fused`` roofline row's static prober: the LN->linear
    segments' priced HBM bytes on the path the step's FusedLNLinear
    nodes CURRENTLY dispatch
    (``ops.fused_lm.priced_fused_cost_for_step``) — so arming
    MXNET_PALLAS_FUSED visibly moves the LM row from the einsum
    engine-op chain's bytes to the fused kernel's.  Zero FLOPs of its
    own (the matmul FLOPs already live in the train_step row); both
    paths' bytes ride along for the table's consumer.  Weakly bound,
    same lifetime rule as :func:`_weak_prober`."""
    import weakref

    ref = weakref.ref(step)

    def prober():
        live = ref()
        if live is None:
            return None
        from .ops.fused_lm import priced_fused_cost_for_step

        priced = priced_fused_cost_for_step(live)
        if priced is None:
            return None
        armed = priced["fused_path"] == "pallas"
        return {"flops": 0,
                "bytes": priced["fused_kernel_bytes" if armed
                                else "fused_einsum_bytes"],
                "fused_path": priced["fused_path"],
                "fused_kernel_bytes": priced["fused_kernel_bytes"],
                "fused_einsum_bytes": priced["fused_einsum_bytes"],
                "fused_segments": priced["segments"]}

    return prober


def _register_step_spec(step):
    """Register a step's :class:`~mxnet_tpu.programs.spec.ProgramSpec`
    with the process-wide program registry — name, donation map, lazy
    abstract args and the retrace counters, registered ONCE per step
    (the registry holds it weakly; the step owns it).  Works for both
    :class:`CompiledTrainStep` (whose donation block widens under an
    armed fused-update plan) and :class:`CompiledEvalStep` (donated
    accumulator state only)."""
    import weakref

    from .programs import registry as _registry
    from .programs.spec import ProgramSpec

    ref = weakref.ref(step)
    is_train = isinstance(step, CompiledTrainStep)

    def abstract():
        live = ref()
        if live is None:
            return None
        if is_train:
            return live._abstract_args(live._group)
        return live._last_args

    # the donation map is fixed at registration: a fused-update plan
    # only arms in __init__, and registration happens at first run()
    if is_train:
        donate = (0, 1, 2, 3, 4) if step._plan is not None \
            else (0, 1, 2, 3)
    else:
        donate = (2,)

    spec = ProgramSpec(
        step.telemetry_name, step._fn, owner=step,
        abstract_args=abstract,
        donate_argnums=donate,
        compute_dtype=lambda: (str(ref()._cdtype)
                               if ref() is not None and is_train
                               and ref()._cdtype is not None else None),
        mesh_shape=lambda: (dict(ref()._group._mesh.shape)
                            if ref() is not None and is_train
                            and ref()._group._mesh is not None else None),
        trace_count=lambda: (ref().trace_count
                             if ref() is not None else None),
        expected_traces=lambda: (ref().programs_built
                                 if ref() is not None and is_train else 1))
    return _registry.register(spec)


class CompiledEvalStep:
    """Forward-only executor program with device-side metric accumulation.

    The eval/score counterpart of the train loop's device metrics (ROADMAP
    PR-3 open item): one jitted program runs the inference forward AND
    folds the metric's ``device_update`` into donated ``(sum, count)``
    accumulator state, so ``score()`` performs no per-batch device→host
    transfer — the classic path pays 2 (label + pred materialization in
    ``metric.update``) per batch.  Reading the metric drains lazily via
    the ``DeviceMetricAccumulator`` hooks, exactly like the train side;
    :meth:`finish` uninstalls them (folding what's pending) when the eval
    pass ends.

    Raises ``MXNetError`` from the constructor when this metric/graph
    combination can't accumulate on device (host path is the fallback);
    the first ``run`` validates the trace with ``jax.eval_shape`` and
    raises likewise before anything is donated.
    """

    def __init__(self, exec_group, metric):
        from .metric import DeviceMetricAccumulator

        # retrace instrumentation (analysis.RetracePass): the python body
        # below runs only while jax traces it, so this counter is the
        # ground truth for "the eval program traced exactly once" (the
        # eval_shape validation probe shares the jit trace cache, so it
        # IS that one trace).  artifact() lowering sets _probing so probe
        # re-traces don't count as cache misses.
        self.trace_count = 0
        self._probing = False
        exe = exec_group.exec_
        self._group = exec_group
        self._exec = exe
        self._data_names = list(exec_group.data_names)
        self._label_names = [n for n in exec_group.label_names
                             if n in exe.arg_dict]
        if len(self._label_names) != len(exec_group.label_names):
            # the program only sees labels the graph consumes; extra
            # iterator labels would shift the host pairing (same rule as
            # CompiledTrainStep.attach_metric)
            raise MXNetError("graph does not consume every label input; "
                             "metric pairing would differ from the host "
                             "path")
        self._param_names = [n for n in exe._arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        try:
            self._acc = DeviceMetricAccumulator(metric)
        except ValueError as exc:
            raise MXNetError(str(exc))
        self._acc.install()
        self._validated = False

        import jax

        acc = self._acc
        label_names = self._label_names
        param_names = self._param_names

        def step(params, aux, mstate, data, rng):
            if not self._probing:
                self.trace_count += 1
            env = dict(zip(param_names, params))
            env.update(data)
            arg_vals = [env[n] for n in exe._arg_names]
            outs, _ = exe._fwd_impl(arg_vals, aux, rng, False)
            labels = [data[n] for n in label_names]
            return acc.update(mstate, labels, list(outs))

        self._fn = jax.jit(step, donate_argnums=(2,))
        self._last_args = None   # aval snapshot for artifact probes
        self._snap_traces = -1   # trace_count the snapshot was taken at
        self._static_registered = False  # roofline prober armed once

    def _place(self, arr, name):
        import jax

        from . import ndarray as _nd

        group = self._group
        dst = group.exec_.arg_dict.get(name)
        v = arr.data if isinstance(arr, _nd.NDArray) else np.asarray(arr)
        if dst is not None and v.dtype != dst.data.dtype:
            v = v.astype(dst.data.dtype)
        if group._mesh is not None:
            return jax.device_put(v, group._input_sharding(name))
        return jax.device_put(v, group.contexts[0].jax_device)

    # telemetry: the roofline row this program's dispatch wall accrues to
    telemetry_name = "eval_step"

    def run(self, data_batch):
        """Accumulate one batch on device.  No host transfer happens here;
        the metric's accumulator state is donated through the program.
        Dispatch wall time feeds the per-program roofline table
        (``obs.programs``) — host-side only, the program is untouched."""
        if not _obs.enabled():
            return self._run_impl(data_batch)
        if not self._static_registered:
            self._static_registered = True
            _obs.programs.register_static(self.telemetry_name,
                                          _weak_prober(self))
            self._program_spec = _register_step_spec(self)
        t0 = time.perf_counter()
        w0 = time.time()
        try:
            return self._run_impl(data_batch)
        finally:
            dt = time.perf_counter() - t0
            _obs.programs.note(self.telemetry_name, dt)
            _obs.timeline.add_span(self.telemetry_name, w0, dt,
                                   cat="program")

    def _run_impl(self, data_batch):
        from . import random as _rnd

        exe = self._exec
        data = {}
        for name, arr in zip(self._group.data_names, data_batch.data):
            data[name] = self._place(arr, name)
        if data_batch.label:
            for name, arr in zip(self._group.label_names, data_batch.label):
                if name in self._label_names:
                    data[name] = self._place(arr, name)
        missing = [n for n in self._data_names + self._label_names
                   if n not in data]
        if missing:
            raise MXNetError("eval batch is missing inputs %s" % missing)
        params = [exe.arg_dict[n].data for n in self._param_names]
        aux = [exe.aux_dict[n].data for n in exe._aux_names]
        rng = _rnd.split_key()
        if not self._validated:
            import jax

            # trace-only probe: a metric mirror this graph rejects must
            # fail BEFORE the donated accumulator state is consumed.  It
            # COUNTS as the program's one trace — eval_shape on a jitted
            # fn populates the same trace cache the real call hits.
            jax.eval_shape(self._fn, params, aux, self._acc.state, data,
                           rng)
            self._validated = True
        if self._last_args is None or self._snap_traces != self.trace_count:
            # aval snapshot for artifact probes — (re)built only when no
            # snapshot exists or the program re-traced, not per batch
            import jax
            import jax.tree_util as jtu

            from .analysis.artifact import aval_of

            def _bare(x):
                # accumulator scalars stay sharding-free: they are
                # re-seeded uncommitted after drains and relocate with
                # the program
                return jax.ShapeDtypeStruct(x.shape, x.dtype)

            self._last_args = (
                jtu.tree_map(aval_of, params), jtu.tree_map(aval_of, aux),
                jtu.tree_map(_bare, self._acc.state),
                jtu.tree_map(aval_of, data), aval_of(rng))
            self._snap_traces = self.trace_count
        self._acc.commit(self._fn(params, aux, self._acc.state, data, rng))

    def finish(self):
        """Fold pending device sums into the host metric and detach the
        hooks — call when the eval pass ends (or falls back mid-way)."""
        self._acc.uninstall()

    def rearm(self):
        """Re-install the metric hooks for another eval pass over the same
        compiled program (fit's per-epoch validation reuses one step
        instead of recompiling every epoch)."""
        self._acc.install()
        return self

    def artifact(self, name="eval_step"):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        eval program at the last-run shapes (None before the first
        ``run``).  Same probe economics as ``compiled_hlo``: avals only,
        throwaway compile, trace flagged as non-counting."""
        import jax.tree_util as jtu

        from .programs.spec import probe_artifact

        if self._last_args is None:
            return None
        params, aux, mstate, data, rng = self._last_args
        return probe_artifact(
            self, self._fn, (params, aux, mstate, data, rng), name,
            donated_leaves=len(jtu.tree_leaves(mstate)),
            trace_count=self.trace_count, expected_traces=1,
            metric=type(self._acc.metric).__name__)

    def roofline_static(self):
        """Static FLOPs + traffic bytes of the eval program at the
        last-run shapes (None before the first ``run``) — the lazy
        roofline join, trace+lower only, probe-flagged so it never
        counts as a retrace."""
        from .programs.spec import probe_cost

        if self._last_args is None:
            return None
        return probe_cost(self, self._fn, self._last_args)


class CompiledTrainStep:
    """One master-weight store + per-executor-group compiled step programs.

    Bucketed training shares a single instance across all bucket modules:
    each bucket's shape-specialized executor gets its own jitted program
    (``_entry_for``), but every program reads and donates the same
    params/slots/aux dicts — the analog of the reference's shared memory
    pools across bucket executors (bucketing_module.py:18-120) extended to
    the fused update path.
    """

    def __init__(self, exec_group, optimizer, compute_dtype=None):
        import jax.numpy as jnp

        # the fused-update plan state must exist before the params/slots
        # properties are first touched below
        self._plan = None
        self._w_slabs = None
        self._slot_slabs = None
        self._wcast = {}
        kernel = optimizer.fused_kernel()
        if kernel is None:
            raise MXNetError("optimizer %s has no fused kernel"
                             % type(optimizer).__name__)
        self._make_slots, self._opt_apply = kernel
        self._optimizer = optimizer
        self._group = exec_group
        self._exec = exec_group.exec_

        exe = self._exec
        self._data_names = list(exec_group.data_names)
        self._label_names = [n for n in exec_group.label_names
                             if n in exe.arg_dict]
        self._param_names = [n for n in exe._arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        # only params with a gradient request get optimizer updates; fixed
        # params ride along as forward inputs
        self._grad_names = [n for n in self._param_names
                            if exe.grad_req.get(n, "null") == "write"]
        unsupported = [n for n in self._param_names
                       if exe.grad_req.get(n, "null") not in ("null", "write")]
        if unsupported:
            raise MXNetError("fused train step supports grad_req "
                             "null/write only; got add for %s" % unsupported)
        self._aux_names = list(exe._aux_names)
        # optimizer bookkeeping (update counts, lr_mult) is keyed by the
        # param's index in the executor group, matching the eager path
        self._grad_indices = [exec_group.param_names.index(n)
                              for n in self._grad_names]

        if compute_dtype in (None, "", "float32", np.float32):
            self._cdtype = None
        else:
            self._cdtype = jnp.dtype(compute_dtype)

        # own copies: the first donated step invalidates its input buffers,
        # and the executor's NDArrays must keep theirs
        self.params = {n: jnp.copy(exe.arg_dict[n].data)
                       for n in self._param_names}
        self.aux = {n: jnp.copy(exe.aux_dict[n].data) for n in self._aux_names}
        self.reset_slots()
        # fused multi-tensor Pallas optimizer update (MXNET_PALLAS_UPDATE,
        # ops/pallas_update.py): when the plan builds — SGD/Adam, f32/bf16
        # trainables, no mesh — the trainable master params and optimizer
        # slots live PERMANENTLY as dtype-homogeneous slabs (plus the
        # compute-dtype `_wcast` recast slabs), donated end to end through
        # the step program: the forward reads slab views, the kernel
        # updates the slabs in place, and nothing repacks per step — the
        # whole point of the HBM diet.  The ``params``/``slots``
        # properties keep the per-name dict surface for everything
        # outside the hot path (checkpointing, probes, benches), packing
        # on assignment and unpacking on read.  plan None = the
        # per-parameter XLA path, unchanged.
        from .ops import pallas_update as _pallas_update

        armed, interpret = _pallas_update.enabled()
        plan = None
        if armed:
            plan = _pallas_update.plan_for(
                optimizer, self._params, self._grad_names, self._cdtype,
                mesh=exec_group._mesh, interpret=interpret)
        _pallas_update.UPDATE_PATH["last"] = \
            "pallas" if plan is not None else "xla"
        if plan is not None:
            self._arm_plan(plan)
        # compiled programs keyed by executor identity (the value holds a
        # strong ref to the executor so a GC'd id can't alias a new one);
        # a reshape rebuilds group.exec_, so the stale program is skipped
        # device-side metric accumulation: when a DeviceMetricAccumulator is
        # attached, its state rides the program as EXTRA DONATED STATE and
        # the per-step device->host output read disappears (metric.py).
        # _metric_traced_ids tracks which executors' programs have traced
        # the metric successfully — per executor, because a shared store
        # compiles one program per bucket and a later bucket's graph may
        # still reject the metric's device mirror
        self._metric_acc = None
        self._metric_traced_ids = set()
        self._metric_rejected = None  # metric whose device mirror failed
        # retrace instrumentation (analysis.RetracePass): the step body
        # increments trace_count only while jax traces it; every program
        # (re)build bumps programs_built, so trace_count > programs_built
        # means a jit cache miss at an already-built signature — dtype /
        # weak-type drift.  compiled_hlo/artifact lowerings set _probing
        # and don't count (the metric eval_shape probe does: it shares
        # the trace cache the real call hits).
        self.trace_count = 0
        self.programs_built = 0
        self._probing = False
        self._fns = {}
        self._fn = self._build(exec_group)
        self._fns[id(exec_group.exec_)] = (self._fn, exec_group.exec_)
        self.num_steps = 0
        self._hyper_cache = None
        self._static_registered = False  # roofline prober armed once
        # lifecycle state is a property of the shared store, not of any one
        # module (several bucket modules may view this step)
        self.step_stale = False   # executor buffers newer than the store
        self.exec_stale = False   # store newer than executor buffers
        self.opt_owner = "eager"  # who holds live optimizer slots

    # ------------------------------------------------------------------
    # master-state surface: per-name dicts outside, slabs inside (plan)
    # ------------------------------------------------------------------
    @property
    def params(self):
        """Master params as a per-name dict.  Under an armed fused-update
        plan the trainables are VIEWS unpacked from the persistent slabs
        (fresh dict per read — mutate via assignment, not item writes);
        otherwise the plain backing dict."""
        if self._plan is None:
            return self._params
        out = dict(self._params)          # fixed (no-grad) params
        out.update(self._plan.unpack_all(self._w_slabs))
        return out

    @params.setter
    def params(self, value):
        if self._plan is None:
            self._params = value
            return
        planned = self._plan.names()
        self._params = {n: v for n, v in value.items() if n not in planned}
        self._w_slabs = self._plan.pack({n: value[n] for n in planned})
        self._wcast = self._plan.cast_slabs(self._w_slabs)

    @property
    def slots(self):
        """Optimizer slots as {name: tuple} — under an armed plan,
        unpacked views of the persistent slot slabs."""
        if self._plan is None:
            return self._slots
        return self._plan.unpack_slots(self._slot_slabs)

    @slots.setter
    def slots(self, value):
        if self._plan is None:
            self._slots = value
            return
        self._slot_slabs = self._plan.pack_slots(value)

    def _arm_plan(self, plan):
        """Move the trainable masters + slots into the plan's persistent
        slabs (and build the compute-dtype recast slabs).  One-time pack
        at arm time; after this the step program reads and donates the
        slabs directly and nothing repacks per step."""
        params, slots = self._params, self._slots
        self._plan = plan
        planned = plan.names()
        self._params = {n: v for n, v in params.items()
                        if n not in planned}
        self._w_slabs = plan.pack({n: params[n] for n in planned})
        self._slot_slabs = plan.pack_slots(slots)
        self._wcast = plan.cast_slabs(self._w_slabs)
        self._slots = {}

    def compatible(self, group):
        """Whether a (bucket) executor group can train through this store.

        Requires every master param/aux to be the *same shared buffer* as
        the primary executor's (shared binding shares identity when shapes
        match), and no extra trainable params.  Buckets with shape-varying
        params (the reference lets those be per-bucket copies) must use the
        eager path instead."""
        exe = group.exec_
        prim = self._exec
        for n in self._param_names:
            if exe.arg_dict.get(n) is not prim.arg_dict[n]:
                return False
        for n in self._aux_names:
            if exe.aux_dict.get(n) is not prim.aux_dict[n]:
                return False
        data_like = set(group.data_names) | set(group.label_names)
        for n in exe._arg_names:
            if n not in data_like and n not in self._param_names:
                return False
        return True

    def _entry_for(self, group):
        """The compiled step program for a (bucket) executor group, built on
        first use.  The group must expose the same parameter set — shared
        binding guarantees it for BucketingModule."""
        exe = group.exec_
        hit = self._fns.get(id(exe))
        if hit is not None and hit[1] is exe:
            return hit[0]
        if not self.compatible(group):
            raise MXNetError(
                "bucket executor's parameter set is not shared with the "
                "master store; demote this bucket to the eager path")
        fn = self._build(group)
        self._fns[id(exe)] = (fn, exe)
        return fn

    # ------------------------------------------------------------------
    # device-side metrics
    # ------------------------------------------------------------------
    def attach_metric(self, metric):
        """Fold ``metric``'s accumulation into the step program as donated
        state.  Returns True when armed; False when this metric (or this
        graph's label routing) can't accumulate on device — the caller then
        stays on the host ``update_metric`` path.  Idempotent per metric."""
        from .metric import DeviceMetricAccumulator

        if self._metric_acc is not None and self._metric_acc.metric is metric:
            return True
        if metric is self._metric_rejected:
            return False  # its device mirror already failed to trace once
        if not DeviceMetricAccumulator.supported(metric):
            return False
        # the step only sees labels the graph consumes; if the iterator
        # feeds extra labels the host pairing would differ — stay on host
        if len(self._label_names) != len(self._group.label_names):
            return False
        self.detach_metric()
        self._metric_acc = DeviceMetricAccumulator(metric)
        self._metric_acc.install()
        self._metric_traced_ids = set()
        self._fns = {}  # program signature changed: recompile per executor
        return True

    def detach_metric(self):
        """Drain pending device accumulation and drop the metric from the
        program (fused->eager handoff, monitor installation, re-init)."""
        if self._metric_acc is None:
            return
        self._metric_acc.uninstall()
        self._metric_acc = None
        self._metric_traced_ids = set()
        self._fns = {}

    # ------------------------------------------------------------------
    def _build(self, group):
        import jax
        import jax.numpy as jnp

        exe = group.exec_
        cdtype = self._cdtype
        data_names = self._data_names
        grad_names = self._grad_names
        aux_names = self._aux_names
        opt_apply = self._opt_apply
        label_names = self._label_names
        macc = self._metric_acc
        plan = self._plan

        def cast(v):
            if cdtype is not None and jnp.issubdtype(v.dtype, jnp.floating):
                return v.astype(cdtype)
            if v.dtype == jnp.uint8:
                # uint8 data = image bytes shipped compact (4x less h2d;
                # ImageIter dtype="uint8"): cast on DEVICE to the compute
                # dtype.  Integer label/id inputs keep their dtype — they
                # arrive as s32/f32, never u8.
                return v.astype(cdtype if cdtype is not None
                                else jnp.float32)
            return v

        if plan is not None:
            # the persistent-slab step: masters and slots arrive AS the
            # donated slabs and leave as the kernel's outputs — nothing
            # packs or unpacks per step.  The forward reads views sliced
            # straight out of the compute slab (wc buckets) or the master
            # slab (master dtype == compute dtype) — slices feed their
            # consumers without materializing.  The ONLY per-step
            # assembly is the gradient slab, and its pack fuses into the
            # backward's own output writes (the convert-before-reshape /
            # excess-precision story in ops/pallas_update.py).
            def step(w_slabs, slot_slabs, aux, wcast, mstate, fixed,
                     data, lrb, wdb, rescale, clip, extra, rng):
                if not self._probing:
                    self.trace_count += 1
                views = {}
                for bk in plan.buckets:
                    src = wcast[bk] if plan.has_wc(bk) else w_slabs[bk]
                    views.update(plan.unpack(bk, src))
                castp = {n: cast(v) for n, v in fixed.items()}
                castp.update(views)
                datac = {n: (cast(v) if n in data_names else v)
                         for n, v in data.items()}

                def fwd(gvals):
                    env = dict(castp)
                    env.update(zip(grad_names, gvals))
                    env.update(datac)
                    outs, new_aux = exe._run_graph(env, aux, rng, True)
                    return outs, [new_aux[n] for n in aux_names]

                gvals = [castp[n] for n in grad_names]
                outs, vjp_fn, new_aux_vals = jax.vjp(fwd, gvals,
                                                     has_aux=True)
                cts = [jnp.ones_like(o) for o in outs]
                (grads,) = vjp_fn(cts)

                g_slabs = plan.pack(dict(zip(grad_names, grads)),
                                    dtype_of_bucket=plan.grad_dtype)
                hyp = jnp.concatenate([
                    jnp.reshape(rescale, (1,)).astype(jnp.float32),
                    jnp.reshape(clip, (1,)).astype(jnp.float32),
                    extra.astype(jnp.float32)])
                new_w, new_slot_slabs, new_wcast = plan.apply(
                    w_slabs, g_slabs, slot_slabs, wcast, lrb, wdb, hyp)
                new_aux = {n: v.astype(aux[n].dtype)
                           for n, v in zip(aux_names, new_aux_vals)}
                if macc is not None:
                    labels = [data[n] for n in label_names]
                    mstate = macc.update(mstate, labels, list(outs))
                return (new_w, new_slot_slabs, new_aux, new_wcast, outs,
                        mstate)

            self.programs_built += 1
            return jax.jit(step, donate_argnums=(0, 1, 2, 3, 4))

        def step(params, slots, aux, mstate, data, lrs, wds, rescale, clip,
                 extra, rng):
            if not self._probing:
                self.trace_count += 1
            castp = {n: cast(v) for n, v in params.items()}
            # labels keep their dtype (integer class ids beyond bf16's exact
            # range must survive); only data inputs are cast
            datac = {n: (cast(v) if n in data_names else v)
                     for n, v in data.items()}

            def fwd(gvals):
                env = dict(castp)
                env.update(zip(grad_names, gvals))
                env.update(datac)
                outs, new_aux = exe._run_graph(env, aux, rng, True)
                return outs, [new_aux[n] for n in aux_names]

            gvals = [castp[n] for n in grad_names]
            outs, vjp_fn, new_aux_vals = jax.vjp(fwd, gvals, has_aux=True)
            cts = [jnp.ones_like(o) for o in outs]
            (grads,) = vjp_fn(cts)

            new_params = dict(params)
            new_slots = {}
            for i, n in enumerate(grad_names):
                g = grads[i].astype(params[n].dtype)
                w, s = opt_apply(params[n], g, slots[n],
                                 lrs[i], wds[i], rescale, clip, extra)
                # float32 hyper scalars promote fp16/bf16 masters; cast the
                # update back so param dtypes are stable across steps
                new_params[n] = w.astype(params[n].dtype)
                new_slots[n] = tuple(
                    s_new.astype(s_old.dtype)
                    for s_new, s_old in zip(s, slots[n]))
            new_aux = {n: v.astype(aux[n].dtype)
                       for n, v in zip(aux_names, new_aux_vals)}
            if macc is not None:
                # metric accumulation reads the SAME outputs/labels the host
                # path would; it feeds nothing back into the training math
                labels = [data[n] for n in label_names]
                mstate = macc.update(mstate, labels, list(outs))
            return new_params, new_slots, new_aux, outs, mstate

        self.programs_built += 1
        return jax.jit(step, donate_argnums=(0, 1, 2, 3))

    # telemetry: the roofline row this program's dispatch wall accrues to
    # (one shared store = one row, however many bucket executors)
    telemetry_name = "train_step"

    # ------------------------------------------------------------------
    def run(self, data_batch, group=None):
        """Execute one full training step; returns output jnp arrays.

        ``group`` selects the (bucket) executor whose graph to run; the
        master weights/slots are this store's regardless.  Dispatch wall
        time feeds the per-program roofline table (``obs.programs``) —
        host-side timing only, the compiled program is byte-identical
        with telemetry on or off (tests/test_obs.py pins it).
        """
        if not _obs.enabled():
            return self._run_impl(data_batch, group)
        if not self._static_registered:
            self._static_registered = True
            _obs.programs.register_static(self.telemetry_name,
                                          _weak_prober(self))
            self._program_spec = _register_step_spec(self)
            # the optimizer phase's own row: zero wall of its own (its
            # dispatch is inside train_step), but its priced bytes make
            # the fused-vs-per-param HBM diet visible per program.  Keyed
            # by this step's telemetry name (canonical step keeps the
            # bare contract name) so benches with several live train
            # steps don't overwrite each other's row
            row = "opt_update" if self.telemetry_name == "train_step" \
                else "%s:opt_update" % self.telemetry_name
            _obs.programs.register_static(row,
                                          _weak_update_prober(self))
            # the LM fused-segment row, only for graphs that have the
            # segments (ResNet-class steps keep their tables clean)
            from .ops.fused_lm import step_has_fused_segments
            if step_has_fused_segments(self):
                frow = "lm_fused" if self.telemetry_name == "train_step" \
                    else "%s:lm_fused" % self.telemetry_name
                _obs.programs.register_static(frow,
                                              _weak_fused_prober(self))
        t0 = time.perf_counter()
        w0 = time.time()
        try:
            return self._run_impl(data_batch, group)
        finally:
            dt = time.perf_counter() - t0
            _obs.programs.note(self.telemetry_name, dt)
            _obs.timeline.add_span(self.telemetry_name, w0, dt,
                                   cat="program")

    def _run_impl(self, data_batch, group=None):
        from . import random as _rnd

        group = group if group is not None else self._group
        fn = self._entry_for(group)
        label_names = [n for n in group.label_names
                       if n in group.exec_.arg_dict]
        data = {}
        for name, arr in zip(group.data_names, data_batch.data):
            data[name] = self._place(arr, name, group)
        if label_names and data_batch.label:
            # zip the *unfiltered* group label list so an unconsumed early
            # label cannot shift later labels onto the wrong arrays; names
            # the symbol doesn't take are skipped in-loop (same alignment
            # rule as DataParallelExecutorGroup.forward)
            for name, arr in zip(group.label_names, data_batch.label):
                if name in label_names:
                    data[name] = self._place(arr, name, group)

        lrs, wds, rescale, clip = self._optimizer.fused_hyper(self._grad_indices)
        extra = self._optimizer.fused_extra()
        plan = self._plan
        # keep hyper-params resident on device across steps: with a constant
        # schedule this is one transfer total instead of one per step
        cached = self._hyper_cache
        if cached is not None and np.array_equal(cached[0], lrs) \
                and np.array_equal(cached[1], wds) \
                and cached[2] == rescale and cached[3] == clip \
                and np.array_equal(cached[4], extra):
            hyper_dev = cached[5]
        else:
            import jax

            where = group._rep_sharding if group._mesh is not None \
                else group.contexts[0].jax_device
            if plan is not None:
                # the fused kernel consumes per-BLOCK lr/wd scalar-
                # prefetch arrays instead of per-param vectors
                lrb, wdb = plan.lr_wd_blocks(
                    dict(zip(self._grad_names, lrs)),
                    dict(zip(self._grad_names, wds)))
                host = (lrb, wdb, rescale, clip, extra)
            else:
                host = (lrs, wds, rescale, clip, extra)
            hyper_dev = jax.tree_util.tree_map(
                lambda v: jax.device_put(v, where), host)
            self._hyper_cache = (lrs, wds, rescale, clip, extra, hyper_dev)
        rng = _rnd.split_key()
        acc = self._metric_acc
        mstate = acc.state if acc is not None else ()

        def dispatch(fn, donated_mstate):
            h0, h1, h2, h3, h4 = hyper_dev
            if plan is not None:
                # the persistent slabs ARE the donated state; the per-name
                # dict surface never enters the hot path.  ``_params``
                # holds only the fixed (no-grad) forward inputs here.
                return fn(self._w_slabs, self._slot_slabs, self.aux,
                          self._wcast, donated_mstate, self._params, data,
                          h0, h1, h2, h3, h4, rng)
            return fn(self.params, self.slots, self.aux, donated_mstate,
                      data, h0, h1, h2, h3, h4, rng)

        if acc is not None and id(group.exec_) not in self._metric_traced_ids:
            # validate the metric's device mirror by TRACING ONLY
            # (eval_shape executes nothing, so no donated buffer is at
            # stake); a mirror that can't trace against this graph — shape
            # pairing, unsupported op, ... — demotes the metric to the
            # host path instead of failing the step.  Real execution
            # errors below propagate untouched.
            import jax

            # (the probe trace is the program's one trace — eval_shape on
            # a jitted fn populates the cache the real call below hits)
            try:
                dispatch(functools.partial(jax.eval_shape, fn), mstate)
                self._metric_traced_ids.add(id(group.exec_))
            except Exception as exc:
                logging.getLogger(__name__).info(
                    "device metric accumulation unavailable (%s); metric "
                    "stays on the host path", exc)
                self._metric_rejected = acc.metric  # don't re-attach
                self.detach_metric()
                acc, mstate = None, ()
                fn = self._entry_for(group)
        if plan is not None:
            (self._w_slabs, self._slot_slabs, self.aux, self._wcast, outs,
             mstate) = dispatch(fn, mstate)
        else:
            self.params, self.slots, self.aux, outs, mstate = \
                dispatch(fn, mstate)
        if acc is not None:
            acc.commit(mstate)
        self.num_steps += 1
        return outs

    def _abstract_args(self, group):
        """Aval pytree of the step program's arguments, rebuilt from the
        live master store and the executor's bound input buffers (None
        before the first ``run``).  Shared by the ``compiled_hlo`` and
        ``artifact`` probes so nothing extra is retained on the hot path.
        """
        import jax

        from . import random as _rnd
        from .analysis.artifact import aval_of as _aval

        if self._hyper_cache is None:
            return None  # never run: no hyper avals to rebuild

        if self._plan is not None:
            # the slab signature: avals of the persistent donated slabs
            # plus the fixed (no-grad) forward inputs
            params = {bk: _aval(v) for bk, v in self._w_slabs.items()}
            slots = {bk: tuple(_aval(s) for s in v)
                     for bk, v in self._slot_slabs.items()}
            fixed = {n: _aval(v) for n, v in self._params.items()}
            wcast = {bk: _aval(v) for bk, v in self._wcast.items()}
        else:
            params = {n: _aval(v) for n, v in self.params.items()}
            slots = {n: tuple(_aval(s) for s in v)
                     for n, v in self.slots.items()}
            fixed = wcast = None
        aux = {n: _aval(v) for n, v in self.aux.items()}
        exe = group.exec_
        label_names = [n for n in group.label_names if n in exe.arg_dict]
        data = {}
        for name in list(group.data_names) + label_names:
            v = exe.arg_dict[name].data
            if group._mesh is not None:
                sharding = group._input_sharding(name)
            else:
                sharding = v.sharding
            data[name] = jax.ShapeDtypeStruct(v.shape, v.dtype,
                                              sharding=sharding)
        import jax.tree_util as jtu

        # under the fused-update plan the hyper device tree is
        # (lrb_dict, wdb_dict, rescale, clip, extra) — per-bucket
        # per-block arrays instead of per-param vectors
        hyper = jtu.tree_map(_aval, self._hyper_cache[5])

        # metric accumulator avals carry NO sharding: after a drain the
        # accumulator is re-seeded as uncommitted default-device scalars,
        # which the real call relocates freely — snapshotting that
        # placement into a committed aval would clash with mesh-sharded
        # params at lower() time
        mstate = () if self._metric_acc is None or \
            self._metric_acc.state is None \
            else jtu.tree_map(lambda x: jax.ShapeDtypeStruct(x.shape,
                                                             x.dtype),
                              self._metric_acc.state)
        # peek the key chain for its aval — a probe must not advance the
        # global RNG (split_key() here would shift every later step's
        # randomness and break bit-reproducibility around the probe)
        rng = _aval(_rnd._key())
        if self._plan is not None:
            return (params, slots, aux, wcast, mstate, fixed, data) + \
                tuple(hyper) + (rng,)
        return (params, slots, aux, mstate, data) + tuple(hyper) + (rng,)

    def compiled_hlo(self, group=None):
        """Optimized-HLO text of the fused train-step program (None before
        the first ``run``).

        Same probe surface as ``Executor.compiled_hlo`` — feed it to
        ``parallel.hlo_stats.collective_stats`` — but over the program
        that actually trains: forward + backward + optimizer in the one
        donated jit.  The lowering compiles a throwaway copy of the
        program (cached jit executables are keyed by concrete arrays, not
        avals), so this is a probe, not a free read.
        """
        from .programs.spec import probing

        group = group if group is not None else self._group
        args = self._abstract_args(group)
        if args is None:
            return None
        fn = self._entry_for(group)
        with probing(self):
            return fn.lower(*args).compile().as_text()

    def artifact(self, name="train_step", group=None):
        """:class:`~mxnet_tpu.analysis.artifact.ProgramArtifact` of the
        fused step — jaxpr + lowered StableHLO + compiled HLO + the
        donation/retrace/dtype metadata the analysis passes check (None
        before the first ``run``)."""
        import jax.tree_util as jtu

        from .programs.spec import probe_artifact

        group = group if group is not None else self._group
        args = self._abstract_args(group)
        if args is None:
            return None
        fn = self._entry_for(group)
        # donated = the leading donate_argnums block: (params, slots, aux,
        # mstate), plus the persistent compute slabs when the fused
        # Pallas update plan is armed
        ndon = 5 if self._plan is not None else 4
        mesh_shape = dict(group._mesh.shape) if group._mesh is not None \
            else None
        # sharding-coverage lint surface: the per-param placement records
        # executor_group._param_sharding stamped at bind time (empty when
        # no tensor-parallel/mesh-axes placement ran — pass then skips)
        coverage = None
        leaves = getattr(group, "_sharding_coverage", None)
        if mesh_shape is not None and leaves:
            coverage = {"mesh": {str(k): int(v)
                                 for k, v in mesh_shape.items()},
                        "leaves": leaves}
        # the artifact-level PATH_TAKEN tripwire, same contract as
        # decode's meta['pallas_decode']: a plan means the config
        # PROMISED the fused multi-tensor update kernel, and the
        # flop-dtype pass errors if no pallas_call lowered into the
        # program (a silent fallback to the per-parameter XLA chain)
        return probe_artifact(
            self, fn, args, name,
            donated_leaves=len(jtu.tree_leaves(args[:ndon])),
            compute_dtype=str(self._cdtype) if self._cdtype is not None
            else None,
            mesh_shape=mesh_shape, trace_count=self.trace_count,
            expected_traces=self.programs_built,
            num_steps=self.num_steps,
            pallas_update=self._plan is not None,
            sharding_coverage=coverage)

    def roofline_static(self, group=None):
        """Static FLOPs + traffic bytes of the fused step program at the
        live shapes (None before the first ``run``) — the lazy roofline
        join for ``obs.programs``.  Trace+lower only (no compile, no
        execution), probe-flagged so it never counts as a retrace."""
        from .programs.spec import probe_cost

        group = group if group is not None else self._group
        args = self._abstract_args(group)
        if args is None:
            return None
        return probe_cost(self, self._entry_for(group), args)

    def _place(self, arr, name, group=None):
        import jax

        group = group if group is not None else self._group
        dst = group.exec_.arg_dict.get(name)
        v = arr.data
        if dst is not None and v.dtype != dst.data.dtype:
            v = v.astype(dst.data.dtype)
        if group._mesh is not None:
            # per-input rule: honors seq-axis (time) sharding from layouts
            return jax.device_put(v, group._input_sharding(name))
        return jax.device_put(v, group.contexts[0].jax_device)

    # ------------------------------------------------------------------
    # state exchange with the NDArray world
    # ------------------------------------------------------------------
    def flush_to_executor(self):
        """Write master params/aux back into the executor's NDArray buffers
        (copies — the step will donate its own buffers next run)."""
        import jax.numpy as jnp

        exe = self._exec
        params = self.params   # one slab unpack, not one per name
        for n in self._param_names:
            exe.arg_dict[n]._set_data(
                jnp.copy(params[n]).astype(exe.arg_dict[n].data.dtype))
        for n in self._aux_names:
            exe.aux_dict[n]._set_data(
                jnp.copy(self.aux[n]).astype(exe.aux_dict[n].data.dtype))

    def load_from_executor(self):
        """Re-seed step state from the executor (after set_params etc.)."""
        import jax.numpy as jnp

        exe = self._exec
        # whole-dict assignment: under an armed fused-update plan the
        # params setter re-packs the slabs and rebuilds the compute-dtype
        # recast slabs (pure cast(master) caches, so restore paths stay
        # bit-identical to an uninterrupted run)
        self.params = {n: jnp.copy(exe.arg_dict[n].data)
                       for n in self._param_names}
        for n in self._aux_names:
            self.aux[n] = jnp.copy(exe.aux_dict[n].data)

    def get_states(self):
        """Serialized optimizer slots (save_optimizer_states payload)."""
        host = {n: tuple(np.asarray(s) for s in slots)
                for n, slots in self.slots.items()}
        return pickle.dumps(host)

    def set_states(self, payload):
        """Load optimizer slots.  Accepts both the fused format (keyed by
        param name, numpy tuples) and the eager Updater format (keyed by the
        param's index in the executor group, NDArray-valued)."""
        import jax.numpy as jnp

        host = pickle.loads(payload)
        index_names = {i: n for i, n in enumerate(self._group.param_names)}
        # mutate a snapshot, then assign whole — under an armed plan the
        # ``slots`` getter unpacks a FRESH dict, so item writes on it
        # would be lost; the setter re-packs the slot slabs
        slots = dict(self.slots)
        for key, state in host.items():
            name = index_names.get(key, key) if isinstance(key, int) else key
            if name not in slots:
                continue
            slots[name] = self._state_to_slots(state, jnp)
        self.slots = slots

    @staticmethod
    def _state_to_slots(state, jnp):
        """Eager create_state values -> fused slot tuple: None -> (),
        single array -> 1-tuple, tuple -> tuple (NDArrays unwrapped)."""
        def leaf(v):
            return jnp.asarray(v.data if hasattr(v, "data") else v)

        if state is None:
            return ()
        if isinstance(state, (tuple, list)):
            return tuple(leaf(s) for s in state)
        return (leaf(state),)

    def reset_slots(self):
        """Synthesize fresh (zero-moment) optimizer slots for the CURRENT
        params — a slot-less checkpoint restored into a training module
        must not keep the moments of the weights it replaced."""
        params = self.params   # one slab unpack, not one per name
        self.slots = {n: self._make_slots(params[n])
                      for n in self._grad_names}

    def import_updater_states(self, states, param_names):
        """Seed slots from an eager Updater's state dict (index- or
        name-keyed) when the module switches eager -> fused mid-training."""
        import jax.numpy as jnp

        index_names = {i: n for i, n in enumerate(param_names)}
        # snapshot-then-assign: see set_states
        slots = dict(self.slots)
        for key, state in states.items():
            name = index_names.get(key, key) if isinstance(key, int) else key
            if name in slots:
                slots[name] = self._state_to_slots(state, jnp)
        self.slots = slots

    def export_updater_states(self, updater, param_names, ctx):
        """Hand the fused slots to an eager Updater (fused -> eager switch:
        install_monitor, manual update() loop) so momentum carries over."""
        import jax.numpy as jnp

        from . import ndarray as _nd

        slots = self.slots   # one slab unpack, not one per name
        for idx, name in enumerate(param_names):
            if name not in slots:
                continue
            arrays = [_nd.NDArray(jnp.copy(s), ctx)
                      for s in slots[name]]
            updater.states[idx] = self._optimizer.pack_state(arrays)
