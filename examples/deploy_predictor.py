"""Train -> checkpoint -> Predictor -> StableHLO deployment walkthrough.

The inference path of docs/deployment.md as a runnable script:
  python examples/deploy_predictor.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.predictor import Predictor, load_exported


def main():
    # 1. train a small classifier
    rng = np.random.RandomState(0)
    x = rng.normal(size=(256, 16)).astype(np.float32)
    y = (x[:, :8].sum(1) > x[:, 8:].sum(1)).astype(np.float32)

    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    mod = mx.mod.Module(net)
    mod.fit(mx.io.NDArrayIter(x, y, batch_size=32),
            optimizer="adam", optimizer_params={"learning_rate": 5e-3},
            initializer=mx.initializer.Xavier(), num_epoch=5)

    prefix = os.path.join(tempfile.mkdtemp(), "clf")
    mod.save_checkpoint(prefix, 5)
    print("checkpoint:", prefix + "-symbol.json", "+", prefix + "-0005.params")

    # 2. standalone predictor from the checkpoint (no Module machinery)
    pred = Predictor.from_checkpoint(prefix, 5, {"data": (32, 16)})
    probs = pred.forward(data=x[:32])[0].asnumpy()
    acc = (probs.argmax(1) == y[:32]).mean()
    print("predictor accuracy on train head: %.2f" % acc)

    # 3. internal-layer taps (MXPredCreatePartialOut analog)
    taps = Predictor.from_checkpoint(prefix, 5, {"data": (4, 16)},
                                     output_names=["fc1"])
    print("fc1 activations:", taps.forward(data=x[:4])[0].shape)

    # 4. StableHLO artifact: weights captured, runnable by any XLA runtime
    blob_path = prefix + ".shlo"
    pred.export(blob_path)
    run = load_exported(blob_path)
    out = np.asarray(run(x[:32])[0])
    # the artifact may execute on a different device than the Predictor's
    # ctx (e.g. TPU vs CPU) where matmul precision differs (bf16 vs fp32) —
    # compare decisions plus a loose numeric tolerance
    same_cls = (out.argmax(1) == probs.argmax(1)).all()
    close = np.allclose(out, probs, rtol=2e-2, atol=2e-2)
    print("stablehlo artifact: %d bytes, matches predictor: %s "
          "(same classes: %s)"
          % (os.path.getsize(blob_path), close, same_cls))


if __name__ == "__main__":
    main()
