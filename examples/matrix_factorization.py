#!/usr/bin/env python
"""Matrix-factorization recommender (reference: example/recommenders).

Capability parity with `example/recommenders/matrix_fact.py`: user/item
Embeddings -> elementwise product -> sum = predicted rating, trained with
LinearRegressionOutput under RMSE — through the legacy FeedForward API the
reference uses, on synthetic MovieLens-shaped data (hermetic, no
downloads).

Run: python examples/matrix_factorization.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import argparse
import logging

import numpy as np


def build(num_users, num_items, factors):
    import mxnet_tpu as mx

    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    score = mx.sym.Variable("score_label")
    u = mx.sym.Embedding(user, input_dim=num_users, output_dim=factors,
                         name="user_embed")
    v = mx.sym.Embedding(item, input_dim=num_items, output_dim=factors,
                         name="item_embed")
    dot = mx.sym.sum(u * v, axis=1)
    return mx.sym.LinearRegressionOutput(dot, score, name="score")


def synthetic_ratings(num_users, num_items, factors, n, seed=0):
    """Low-rank ground truth + noise: learnable, MovieLens-shaped."""
    rng = np.random.RandomState(seed)
    U = rng.normal(0, 0.6, (num_users, factors)).astype(np.float32)
    V = rng.normal(0, 0.6, (num_items, factors)).astype(np.float32)
    users = rng.randint(0, num_users, n).astype(np.float32)
    items = rng.randint(0, num_items, n).astype(np.float32)
    scores = (U[users.astype(int)] * V[items.astype(int)]).sum(1)
    scores += rng.normal(0, 0.1, n).astype(np.float32)
    return users, items, scores


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--users", type=int, default=200)
    ap.add_argument("--items", type=int, default=300)
    ap.add_argument("--factors", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=40)
    args = ap.parse_args()

    import mxnet_tpu as mx

    logging.basicConfig(level=logging.INFO)
    users, items, scores = synthetic_ratings(args.users, args.items,
                                             args.factors, 8000)
    split = 7000
    train_it = mx.io.NDArrayIter(
        {"user": users[:split], "item": items[:split]},
        {"score_label": scores[:split]}, batch_size=250, shuffle=True)
    val_it = mx.io.NDArrayIter(
        {"user": users[split:], "item": items[split:]},
        {"score_label": scores[split:]}, batch_size=250)

    net = build(args.users, args.items, args.factors)
    # legacy estimator API, as the reference example uses
    model = mx.model.FeedForward(
        symbol=net, ctx=mx.cpu(), num_epoch=args.epochs,
        optimizer="adam", learning_rate=0.05,
        initializer=mx.initializer.Normal(0.1))
    model.fit(X=train_it, eval_data=val_it, eval_metric="rmse")

    val_it.reset()
    preds = model.predict(val_it)
    rmse = float(np.sqrt(np.mean(
        (preds.ravel()[:len(scores) - split] - scores[split:]) ** 2)))
    print("final val RMSE: %.3f (noise floor ~0.1)" % rmse)


if __name__ == "__main__":
    main()
