"""Sequence-parallel attention LM — the long-context training recipe.

The reference's long-sequence story is bucketing (docs/how_to/bucketing.md);
the TPU build shards the TIME axis across the mesh's 'seq' axis instead:
declare the input layout ('NT'), pick a mesh with seq>1, and the executor
shards the batch (B on 'data', T on 'seq') while GSPMD inserts the
attention collectives.  For explicit-collective ring attention (memory-
optimal, no full K/V on any chip) see mxnet_tpu.parallel.ring.

Run on 8 virtual devices:
    python examples/attention_lm_seq_parallel.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

try:
    # 8 virtual CPU devices — must happen before backend init; harmless to
    # skip when the backend is already up with >=8 real devices
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except RuntimeError:
    pass
if len(jax.devices()) < 8:
    raise SystemExit("need 8 devices (set jax_num_cpu_devices before "
                     "importing jax elsewhere)")

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.io import DataDesc
from mxnet_tpu.parallel import MeshConfig


def attention_lm(vocab, embed=64, heads=4):
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    net = sym.Embedding(data, input_dim=vocab, output_dim=embed,
                        name="embed")
    q = sym.FullyConnected(net, num_hidden=embed, flatten=False, name="q")
    k = sym.FullyConnected(net, num_hidden=embed, flatten=False, name="k")
    v = sym.FullyConnected(net, num_hidden=embed, flatten=False, name="v")
    att = sym.dot_product_attention(q, k, v, num_heads=heads, causal=True)
    net = sym.FullyConnected(sym.Reshape(att, shape=(-1, embed)),
                             num_hidden=vocab, name="head")
    return sym.SoftmaxOutput(net, sym.Reshape(label, shape=(-1,)),
                             name="softmax")


def main():
    vocab, batch, seq_len = 31, 8, 64
    rng = np.random.RandomState(0)
    # deterministic affine next-token chain
    x = np.zeros((512, seq_len), np.float32)
    x[:, 0] = rng.randint(1, vocab, size=512)
    for i in range(1, seq_len):
        x[:, i] = (x[:, i - 1] * 7 + 5) % vocab
    y = np.roll(x, -1, axis=1)
    y[:, -1] = (x[:, -1] * 7 + 5) % vocab

    data_desc = DataDesc("data", (batch, seq_len), layout="NT")
    label_desc = DataDesc("softmax_label", (batch, seq_len), layout="NT")

    mod = mx.mod.Module(attention_lm(vocab),
                        context=[mx.cpu(i) for i in range(8)],
                        mesh_config=MeshConfig(data=2, seq=4))
    mod.bind(data_shapes=[data_desc], label_shapes=[label_desc])
    it = mx.io.NDArrayIter(x, y, batch_size=batch)
    mod.fit(it, optimizer="adam", optimizer_params={"learning_rate": 5e-3},
            initializer=mx.initializer.Xavier(), num_epoch=3,
            eval_metric=mx.metric.Perplexity(ignore_label=None),
            batch_end_callback=mx.callback.Speedometer(batch, 20))
    print("mesh:", dict(mod._exec_group._mesh.shape))
    print("data sharding:",
          mod._exec_group.exec_.arg_dict["data"].data.sharding.spec)


if __name__ == "__main__":
    main()
