#!/usr/bin/env python
"""GPipe-pipelined attention language model via PipelineModule.

The user-facing pipeline-parallel workflow (the TPU leapfrog of the
reference's group2ctx model parallelism, docs/how_to/model_parallel_lstm.md):
describe ONE transformer block as a Symbol, a head Symbol ending in a loss,
and train with the ordinary ``Module.fit`` loop — the module stacks the
block ``num_stages`` times, shards the stack on the 'pipe' mesh axis, and
compiles the GPipe fill-drain schedule + backward + optimizer update into
one donated XLA program (mxnet_tpu/module/pipeline_module.py).

Run on the virtual CPU mesh:
    python examples/pipeline_lm.py --stages 4 --devices 8
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import argparse
import logging

import numpy as np


def build_stage(hidden, heads):
    """One pre-norm self-attention + FFN residual block (stateless)."""
    import mxnet_tpu as mx

    x = mx.sym.Variable("data")                      # (mb, T, E)
    q = mx.sym.FullyConnected(x, num_hidden=hidden, flatten=False, name="q")
    k = mx.sym.FullyConnected(x, num_hidden=hidden, flatten=False, name="k")
    v = mx.sym.FullyConnected(x, num_hidden=hidden, flatten=False, name="v")
    a = mx.sym.dot_product_attention(q, k, v, num_heads=heads, causal=True,
                                     name="attn")
    o = mx.sym.FullyConnected(a, num_hidden=hidden, flatten=False, name="o")
    h = x + o
    f1 = mx.sym.FullyConnected(h, num_hidden=hidden * 4, flatten=False,
                               name="ffn1")
    f1 = mx.sym.Activation(f1, act_type="relu")
    f2 = mx.sym.FullyConnected(f1, num_hidden=hidden, flatten=False,
                               name="ffn2")
    return h + f2


def build_embed(vocab, hidden):
    import mxnet_tpu as mx

    tok = mx.sym.Variable("data")                    # (mb, T) int ids
    return mx.sym.Embedding(tok, input_dim=vocab, output_dim=hidden,
                            name="embed")


def build_head(vocab):
    import mxnet_tpu as mx

    h = mx.sym.Variable("data")                      # (B, T, E)
    logits = mx.sym.FullyConnected(h, num_hidden=vocab, flatten=False,
                                   name="decode")
    return mx.sym.SoftmaxOutput(logits, preserve_shape=True, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--micro", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=5)
    args = ap.parse_args()

    import jax

    if len(jax.devices()) < args.devices:
        # backend already initialized (device query above): reset it first
        from jax._src import api

        api.clear_backends()
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.devices)

    import mxnet_tpu as mx
    from mxnet_tpu.io import NDArrayIter

    logging.basicConfig(level=logging.INFO)

    # toy corpus: next-token prediction on random sequences with structure
    rng = np.random.RandomState(0)
    n = args.batch * 8
    base = rng.randint(0, args.vocab // 2, (n, args.seq_len + 1))
    base[:, 1:] = (base[:, :-1] + 1) % args.vocab    # learnable transition
    data = base[:, :-1].astype(np.float32)
    label = base[:, 1:].astype(np.float32)   # (B, T): preserve_shape softmax
    it = NDArrayIter({"data": data}, {"softmax_label": label},
                     batch_size=args.batch)

    pipe = mx.mod.PipelineModule(
        build_stage(args.hidden, args.heads), build_head(args.vocab),
        num_stages=args.stages, num_microbatches=args.micro,
        embed_symbol=build_embed(args.vocab, args.hidden),
        context=[mx.cpu(i) for i in range(args.devices)])
    pipe.fit(it, optimizer="adam", optimizer_params={"learning_rate": 3e-3},
             initializer=mx.initializer.Xavier(), num_epoch=args.epochs,
             eval_metric=mx.metric.Perplexity(ignore_label=None))
    it.reset()
    print("final:", pipe.score(it, mx.metric.Perplexity(ignore_label=None)))


if __name__ == "__main__":
    main()
