#!/usr/bin/env python
"""Bucketed LSTM language model (reference: example/rnn/lstm_bucketing.py).

Trains on a synthetic integer-sequence corpus when no PTB file is given.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import argparse
import logging

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.models.lstm_lm import sym_gen_factory


def synthetic_corpus(n_sent=2000, vocab=500, seed=0):
    rng = np.random.RandomState(seed)
    sents = []
    for _ in range(n_sent):
        length = rng.randint(5, 40)
        # Markov-ish chains so there is something to learn
        start = rng.randint(1, vocab)
        s = [start]
        for _ in range(length - 1):
            s.append((s[-1] * 31 + 7) % vocab or 1)
        sents.append(s)
    return sents


def tokenize(fname, vocab=None):
    sentences = []
    vocab = vocab if vocab is not None else {"<pad>": 0}
    with open(fname) as f:
        for line in f:
            words = line.split() + ["<eos>"]
            ids = []
            for w in words:
                if w not in vocab:
                    vocab[w] = len(vocab)
                ids.append(vocab[w])
            sentences.append(ids)
    return sentences, vocab


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--train-file", default=None)
    parser.add_argument("--num-hidden", type=int, default=200)
    parser.add_argument("--num-embed", type=int, default=200)
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--buckets", default="10,20,30,40")
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--fused", action="store_true", default=True)
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO)

    if args.train_file:
        sentences, vocab = tokenize(args.train_file)
        vocab_size = len(vocab) + 1
    else:
        sentences = synthetic_corpus()
        vocab_size = 512

    buckets = [int(b) for b in args.buckets.split(",")]
    data_train = mx.rnn.BucketSentenceIter(sentences, args.batch_size,
                                           buckets=buckets, invalid_label=0)

    sym_gen, cells = sym_gen_factory(num_hidden=args.num_hidden,
                                     num_layers=args.num_layers,
                                     num_embed=args.num_embed,
                                     vocab_size=vocab_size, fused=args.fused)
    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=data_train.default_bucket_key,
                                 context=mx.tpu())
    mod.fit(data_train, eval_metric=mx.metric.Perplexity(ignore_label=0),
            initializer=mx.initializer.Xavier(),
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20),
            num_epoch=args.num_epochs)


if __name__ == "__main__":
    main()
