"""Ring attention: explicit-collective long-context training.

The memory-optimal distributed attention path (parallel/ring.py): each
device of the 'seq' mesh axis holds one sequence block of Q/K/V; K/V
blocks rotate via ``lax.ppermute`` while a streaming softmax accumulates
each device's attention over every block.  On TPU the per-hop compute is
the Pallas flash kernel (the fused kernel IS the distributed path); the
custom VJP runs a backward ring, so the whole thing trains.

No reference analog: 2017-era MXNet scales sequence length by bucketing
alone (SURVEY §2.5).  At T=8192 blocks the alternatives don't even fit —
dense attention's (B·H, T, T) logits and the streaming math's autodiff
backward both exceed HBM; the kernel path is the only trainable one
(benchmarks/ROOFLINE.md, round 5).

The rings are double-buffered by default (each hop's K/V fetch issues
before the hop's kernel, so TPU's async collective-permutes overlap the
flash compute); MXNET_RING_DOUBLE_BUFFER=0 restores the serial schedule
— bit-identical results either way (docs/long_context.md).

Run (virtual 8-CPU mesh, interpreter-mode kernels):
    python examples/ring_attention_long_context.py
On a real TPU mesh, drop the jax.config lines and interpret=None picks
the compiled kernel automatically.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# old-jax fallback for the 8-virtual-device mesh (no ``jax_num_cpu_devices``
# option there): the XLA flag must be in place before backend init, and env
# mutation only works before jax is imported
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

import jax

# must happen before backend init; on a TPU machine the platform is
# already fixed and these raise — that's fine, we keep the real chip
try:
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mxnet_tpu.parallel.compat import shard_map

from mxnet_tpu.parallel.ring import ring_attention, dense_attention, RING_PATH


def main():
    b, t, heads, hd = 2, 1024, 2, 64
    e = heads * hd
    on_tpu = jax.default_backend() == "tpu"
    seq_par = min(4, len(jax.devices()))   # one real chip -> 1-hop ring
    # TPU matmuls default to bf16 precision; the f32 CPU reference is
    # tighter
    tol = 2e-2 if on_tpu else 2e-4

    mesh = Mesh(np.array(jax.devices()[:seq_par]), ("seq",))
    rng = np.random.RandomState(0)
    q, k, v = [rng.normal(size=(b, t, e)).astype(np.float32)
               for _ in range(3)]

    # each device sees only its (b, t/seq_par, e) block; causal masking
    # uses global block offsets, so the result equals dense attention on
    # the gathered sequence
    ring = shard_map(
        lambda q_, k_, v_: ring_attention(
            q_, k_, v_, axis_name="seq", num_heads=heads, causal=True,
            use_flash=True, interpret=not on_tpu),
        mesh=mesh, in_specs=(P(None, "seq", None),) * 3,
        out_specs=P(None, "seq", None), check_vma=False)

    out = np.asarray(jax.jit(ring)(q, k, v))
    ref = np.asarray(dense_attention(q, k, v, num_heads=heads, causal=True))
    err = float(np.abs(out - ref).max())
    print("ring(%d devices) vs dense: max|diff| = %.2e (path: %s)"
          % (seq_par, err, RING_PATH["last"]))
    assert err < tol

    # and it TRAINS: gradients through the backward ring
    def loss(q_, k_, v_):
        return (jax.jit(ring)(q_, k_, v_) ** 2).sum()

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for name, g in (("dq", gq), ("dk", gk), ("dv", gv)):
        assert np.isfinite(np.asarray(g)).all()
    print("backward ring OK: grad norms dq=%.3f dk=%.3f dv=%.3f"
          % tuple(float(jnp.abs(g).max()) for g in (gq, gk, gv)))


if __name__ == "__main__":
    main()
