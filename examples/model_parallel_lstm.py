#!/usr/bin/env python
"""Model-parallel LSTM language model.

Capability parity with the reference's example/model-parallel-lstm
(`lstm.py:48-112`): each LSTM layer is pinned to its own device through
``AttrScope(ctx_group=...)`` + ``bind(group2ctx=...)``, so a deep recurrent
net whose layers don't fit one accelerator spreads across several, and the
async dispatch overlaps the per-layer stages.

Run on the virtual CPU mesh for a quick check:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/model_parallel_lstm.py --num-layers 4
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import argparse
import logging

import numpy as np

import mxnet_tpu as mx


def build_lstm(seq_len, vocab, num_embed, num_hidden, num_layers, devices):
    """Unrolled multi-layer LSTM LM; layer i carries ctx_group 'layer<i>'
    plus an embed/decode group, each mappable to a device."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    with mx.AttrScope(ctx_group="embed"):
        hidden = mx.sym.Embedding(data, input_dim=vocab,
                                  output_dim=num_embed, name="embed")
    for layer in range(num_layers):
        with mx.AttrScope(ctx_group="layer%d" % layer):
            cell = mx.rnn.LSTMCell(num_hidden, prefix="lstm_l%d_" % layer)
            hidden, _ = cell.unroll(seq_len, inputs=hidden,
                                    layout="NTC", merge_outputs=True)
    with mx.AttrScope(ctx_group="decode"):
        pred = mx.sym.Reshape(hidden, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="decode")
        flat_label = mx.sym.Reshape(label, shape=(-1,))
        net = mx.sym.SoftmaxOutput(pred, flat_label, name="softmax")

    group2ctx = {"embed": devices[0], "decode": devices[-1]}
    for layer in range(num_layers):
        group2ctx["layer%d" % layer] = devices[layer % len(devices)]
    return net, group2ctx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=12)
    ap.add_argument("--vocab", type=int, default=200)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-batches", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import jax

    n_dev = max(1, len(jax.devices()))
    # mx.cpu on the CPU platform; mx.tpu otherwise (it resolves to whatever
    # accelerator platform JAX exposes, falling back to the default)
    make_ctx = mx.cpu if jax.devices()[0].platform == "cpu" else mx.tpu
    devices = [make_ctx(i) for i in range(min(n_dev, args.num_layers + 2))]
    logging.info("placing %d LSTM layers over %d device(s)",
                 args.num_layers, len(devices))

    net, group2ctx = build_lstm(args.seq_len, args.vocab, args.num_embed,
                                args.num_hidden, args.num_layers, devices)

    shapes = {"data": (args.batch_size, args.seq_len),
              "softmax_label": (args.batch_size, args.seq_len)}
    exe = net.simple_bind(devices[0], grad_req="write",
                          group2ctx=group2ctx, **shapes)

    init = mx.initializer.Xavier()
    for name, arr in exe.arg_dict.items():
        if name not in shapes:
            init(name, arr)
    opt = mx.optimizer.SGD(learning_rate=args.lr, rescale_grad=1.0 /
                           (args.batch_size * args.seq_len))
    updater = mx.optimizer.get_updater(opt)

    rng = np.random.RandomState(0)

    def markov_batch():
        """Deterministic token chains (learnable next-token structure)."""
        x = np.empty(shapes["data"], np.float32)
        x[:, 0] = rng.randint(1, args.vocab, args.batch_size)
        for t in range(1, args.seq_len):
            x[:, t] = (x[:, t - 1] * 31 + 7) % args.vocab
            x[:, t][x[:, t] == 0] = 1
        return x

    losses = []
    for step in range(args.num_batches):
        x = markov_batch()
        y = np.roll(x, -1, axis=1)
        exe.arg_dict["data"][:] = x
        exe.arg_dict["softmax_label"][:] = y
        exe.forward(is_train=True)
        exe.backward()
        for i, name in enumerate(net.list_arguments()):
            if name in shapes:
                continue
            updater(i, exe.grad_dict[name], exe.arg_dict[name])
        prob = exe.outputs[0].asnumpy()
        nll = -np.log(np.maximum(
            prob[np.arange(prob.shape[0]), y.reshape(-1).astype(int)],
            1e-10)).mean()
        losses.append(nll)
        if step % 10 == 0:
            logging.info("batch %3d  nll %.4f", step, nll)
    logging.info("nll first->last: %.4f -> %.4f", losses[0], losses[-1])
    assert losses[-1] < losses[0], "model-parallel LSTM failed to learn"
    print("model-parallel LSTM OK: nll %.4f -> %.4f"
          % (losses[0], losses[-1]))


if __name__ == "__main__":
    main()
