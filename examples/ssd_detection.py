"""SSD-style detection training — the MultiBox workload class end to end.

Mirrors the reference's example/ssd pipeline shape: ImageDetIter feeds
(image, padded-box-label) batches; MultiBoxPrior generates anchors;
MultiBoxTarget matches anchors to ground truth producing classification +
localization targets; the loss combines softmax (classes) and smooth-L1
(offsets); MultiBoxDetection decodes predictions + NMS at inference.

Runs on synthetic shapes data (colored rectangles on noise) so it is
hermetic:  python examples/ssd_detection.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu import recordio
from mxnet_tpu import symbol as sym
from mxnet_tpu.image import ImageDetIter


def make_dataset(path_prefix, n=64, size=32, seed=0):
    """Images with one axis-aligned bright rectangle; class = its color."""
    rng = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(path_prefix + ".idx",
                                     path_prefix + ".rec", "w")
    for i in range(n):
        img = rng.randint(0, 60, size=(size, size, 3), dtype=np.uint8)
        cls = rng.randint(0, 3)
        w, h = rng.randint(size // 4, size // 2, 2)
        x0 = rng.randint(0, size - w)
        y0 = rng.randint(0, size - h)
        img[y0:y0 + h, x0:x0 + w, cls] = 230
        box = [cls, x0 / size, y0 / size, (x0 + w) / size, (y0 + h) / size]
        label = np.concatenate([[2, 5], box]).astype(np.float32)
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, label, i, 0), img, img_fmt=".png",
            quality=3))
    rec.close()


def ssd_symbol(num_classes=3, sizes=(0.3, 0.6), ratios=(1.0, 2.0, 0.5)):
    data = sym.Variable("data")
    label = sym.Variable("label")
    # tiny backbone
    net = sym.Convolution(data, num_filter=16, kernel=(3, 3), pad=(1, 1),
                          name="c1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.Convolution(net, num_filter=32, kernel=(3, 3), pad=(1, 1),
                          name="c2")
    net = sym.Activation(net, act_type="relu")
    feat = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")

    num_anchors = len(sizes) + len(ratios) - 1
    anchors = sym.MultiBoxPrior(feat, sizes=list(sizes), ratios=list(ratios))
    cls_pred = sym.Convolution(feat, num_filter=num_anchors
                               * (num_classes + 1), kernel=(3, 3),
                               pad=(1, 1), name="cls_pred")
    loc_pred = sym.Convolution(feat, num_filter=num_anchors * 4,
                               kernel=(3, 3), pad=(1, 1), name="loc_pred")
    # (B, A*(C+1), H, W) -> (B, C+1, A*H*W): class-first for softmax axis 1
    cls_pred = sym.Reshape(sym.transpose(cls_pred, axes=(0, 2, 3, 1)),
                           shape=(0, -1, num_classes + 1))
    cls_pred = sym.transpose(cls_pred, axes=(0, 2, 1))
    loc_pred = sym.Flatten(sym.transpose(loc_pred, axes=(0, 2, 3, 1)))

    loc_target, loc_mask, cls_target = sym.MultiBoxTarget(
        anchors, label, cls_pred, name="target")
    cls_loss = sym.SoftmaxOutput(cls_pred, cls_target,
                                 multi_output=True, use_ignore=True,
                                 ignore_label=-1, name="cls_prob")
    loc_diff = loc_mask * (loc_pred - loc_target)
    loc_loss = sym.MakeLoss(sym.smooth_l1(loc_diff, scalar=1.0),
                            grad_scale=1.0, name="loc_loss")
    det = sym.MultiBoxDetection(cls_loss, loc_pred, anchors,
                                name="detection")
    return sym.Group([cls_loss, loc_loss,
                      sym.BlockGrad(cls_target), sym.BlockGrad(det)])


def main():
    tmp = tempfile.mkdtemp()
    prefix = os.path.join(tmp, "shapes")
    make_dataset(prefix, n=64)
    it = ImageDetIter(batch_size=8, data_shape=(3, 32, 32),
                      path_imgrec=prefix + ".rec",
                      path_imgidx=prefix + ".idx", shuffle=True,
                      rand_mirror=True, label_name="label", seed=0)

    mod = mx.mod.Module(ssd_symbol(), data_names=("data",),
                        label_names=("label",), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 2e-3})

    for epoch in range(3):
        it.reset()
        n_batches = 0
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            n_batches += 1
        print("epoch %d: %d batches trained" % (epoch, n_batches))

    # inference: decoded detections [cls, score, x0, y0, x1, y1]
    it.reset()
    batch = it.next()
    mod.forward(batch, is_train=False)
    det = mod.get_outputs()[3].asnumpy()
    kept = det[0][det[0, :, 0] >= 0]
    print("detections for image 0 (cls, score, box):")
    for row in kept[:5]:
        print("  cls=%d score=%.2f box=(%.2f, %.2f, %.2f, %.2f)"
              % (int(row[0]), row[1], row[2], row[3], row[4], row[5]))


if __name__ == "__main__":
    main()
