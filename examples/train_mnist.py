#!/usr/bin/env python
"""Train LeNet/MLP on MNIST (reference: example/image-classification/train_mnist.py).

Uses the idx files if present in --data-dir, else the deterministic
synthetic dataset.  Runs on one TPU chip by default; --cpus N uses a
virtual CPU mesh for data parallelism.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import argparse
import logging

import mxnet_tpu as mx
from mxnet_tpu import models


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--network", default="lenet", choices=["lenet", "mlp"])
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--num-epochs", type=int, default=5)
    parser.add_argument("--data-dir", default=".")
    parser.add_argument("--kv-store", default="local")
    parser.add_argument("--gpus", default=None,
                        help="comma-separated device ids, e.g. 0 or 0,1,2,3")
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO)

    if args.network == "lenet":
        net = models.get_lenet(num_classes=10)
        flat = False
    else:
        net = models.get_mlp(num_classes=10)
        flat = True

    train = mx.io.MNISTIter(
        image="%s/train-images-idx3-ubyte" % args.data_dir,
        label="%s/train-labels-idx1-ubyte" % args.data_dir,
        batch_size=args.batch_size, flat=flat, seed=0)
    val = mx.io.MNISTIter(
        image="%s/t10k-images-idx3-ubyte" % args.data_dir,
        label="%s/t10k-labels-idx1-ubyte" % args.data_dir,
        batch_size=args.batch_size, flat=flat, seed=1)

    if args.gpus:
        ctx = [mx.tpu(int(i)) for i in args.gpus.split(",")]
    else:
        ctx = mx.tpu()

    mod = mx.mod.Module(net, context=ctx)
    mod.fit(train, eval_data=val,
            initializer=mx.initializer.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            kvstore=args.kv_store,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50),
            num_epoch=args.num_epochs)
    print("final validation:", mod.score(val, "acc"))


if __name__ == "__main__":
    main()
