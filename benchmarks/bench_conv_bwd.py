"""Per-shape conv forward/dgrad/wgrad throughput on the bench chip.

ROOFLINE.md names conv backward (~29 TFLOP/s on early-stage shapes) as the
floor-blocker for ResNet-50 training; round 4 never measured WHICH conv
shapes are slow or what lever moves them.  This benchmark times every
distinct ResNet-50 convolution — forward, input-gradient (dgrad), and
weight-gradient (wgrad) separately — and sweeps the cheap levers per
shape:

  * layout: NCHW vs NHWC
  * f32 accumulation vs bf16 inputs (the default)
  * channel-padded stage-1 (cin 3 -> 8) for conv0

Each op is timed inside ONE jit program that runs it K times in a
fori_loop with an iteration-dependent input perturbation (no CSE, no
per-call dispatch overhead — the tunnel costs ~4ms/call).

Usage: python bench_conv_bwd.py [--quick]
"""
import argparse
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

N = int(os.environ.get("N", "256"))

# (name, cin, cout, k, stride, hin)  — every distinct RN50 conv at bs=256
SHAPES = [
    ("conv0_7x7s2", 3, 64, 7, 2, 224),
    ("s0_1x1_64_64", 64, 64, 1, 1, 56),
    ("s0_3x3_64_64", 64, 64, 3, 1, 56),
    ("s0_1x1_64_256", 64, 256, 1, 1, 56),
    ("s0_1x1_256_64", 256, 64, 1, 1, 56),
    ("s1_3x3s2_128", 128, 128, 3, 2, 56),
    ("s1_3x3_128", 128, 128, 3, 1, 28),
    ("s1_1x1_128_512", 128, 512, 1, 1, 28),
    ("s1_1x1_512_128", 512, 128, 1, 1, 28),
    ("s1_sc_256_512s2", 256, 512, 1, 2, 56),
    ("s2_3x3s2_256", 256, 256, 3, 2, 28),
    ("s2_3x3_256", 256, 256, 3, 1, 14),
    ("s2_1x1_256_1024", 256, 1024, 1, 1, 14),
    ("s2_1x1_1024_256", 1024, 256, 1, 1, 14),
    ("s3_3x3s2_512", 512, 512, 3, 2, 14),
    ("s3_3x3_512", 512, 512, 3, 1, 7),
    ("s3_1x1_512_2048", 512, 2048, 1, 1, 7),
    ("s3_1x1_2048_512", 2048, 512, 1, 1, 7),
]


def conv_fn(layout, stride, pad):
    dn = ("NCHW", "OIHW", "NCHW") if layout == "NCHW" \
        else ("NHWC", "HWIO", "NHWC")

    def f(x, w):
        return lax.conv_general_dilated(
            x, w, (stride, stride), [(pad, pad)] * 2,
            dimension_numbers=dn)
    return f


MIN_ROTATE_BYTES = 256 << 20     # defeat VMEM residency (v5e VMEM 128MB)


def timed_loop(op, args, iters=96, base_iters=16, reps=5):
    """Per-op time of `op` inside one jit, measured DIFFERENTIALLY.

    Methodology (each piece is load-bearing on this rig):
      * The operands rotate through R copies sized past VMEM, indexed
        i % R with a dynamic slice that fuses into the consumer's read —
        otherwise XLA's memory-space assignment pins a single operand in
        VMEM for the whole loop and reports VMEM-fed throughput the real
        model never sees.
      * The first operand also gets an additive per-iteration shift: a
        scalar MULTIPLY would commute through the linear conv and hoist
        it out of the loop entirely (measured: 10000+ "TF/s").
      * The reported time is (T(iters) - T(base_iters)) / (iters - base),
      	which cancels the tunnel's 50-150ms jittering round-trip
        constant; a plain T/iters is noise at these op sizes.
      * float() readback is the sync — block_until_ready has been
        observed returning early through the tunnel.
    """
    total = sum(int(np.prod(a.shape)) * a.dtype.itemsize for a in args)
    # no small cap: r_copies * total must EXCEED VMEM or small shapes get
    # pinned resident and report VMEM-fed throughput
    r_copies = max(2, int(np.ceil(MIN_ROTATE_BYTES / max(total, 1))))
    r_copies = min(r_copies, 64)
    big = [jnp.stack([a + jnp.asarray(k * 1e-6, a.dtype)
                      for k in range(r_copies)]) for a in args]

    def make(n_iters):
        def body(*ops):
            def step(i, acc):
                idx = lax.rem(i, r_copies)
                sel = [lax.dynamic_index_in_dim(o, idx, 0, keepdims=False)
                       for o in ops]
                x0 = sel[0] + (1e-6 * i.astype(jnp.float32)) \
                    .astype(sel[0].dtype)
                out = op(x0, *sel[1:])
                return acc + out.astype(jnp.float32).sum()
            return lax.fori_loop(0, n_iters, step, jnp.float32(0.0))
        return jax.jit(body)

    f_hi, f_lo = make(iters), make(base_iters)
    float(f_hi(*big))
    float(f_lo(*big))
    # MEDIAN of the differentials: the tunnel's round-trip jitter makes a
    # single difference occasionally negative; min-of-n biases toward
    # those outliers, the median doesn't
    diffs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(f_lo(*big))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(f_hi(*big))
        t_hi = time.perf_counter() - t0
        diffs.append((t_hi - t_lo) / (iters - base_iters))
    return max(float(np.median(diffs)), 1e-9)


def flops_of(cin, cout, k, stride, hin):
    hout = (hin + 2 * (k // 2) - k) // stride + 1
    return 2.0 * N * cout * cin * k * k * hout * hout


def bench_shape(name, cin, cout, k, stride, hin, layout="NCHW",
                dtype=jnp.bfloat16):
    rng = np.random.RandomState(0)
    pad = k // 2
    hout = (hin + 2 * pad - k) // stride + 1
    if layout == "NCHW":
        x = jnp.asarray(rng.rand(N, cin, hin, hin), dtype)
        w = jnp.asarray(rng.rand(cout, cin, k, k), dtype)
        dy_shape = (N, cout, hout, hout)
    else:
        x = jnp.asarray(rng.rand(N, hin, hin, cin), dtype)
        w = jnp.asarray(rng.rand(k, k, cin, cout), dtype)
        dy_shape = (N, hout, hout, cout)
    dy = jnp.asarray(rng.rand(*dy_shape), dtype)
    f = conv_fn(layout, stride, pad)
    fl = flops_of(cin, cout, k, stride, hin)

    t_fwd = timed_loop(lambda x_, w_: f(x_, w_), (x, w))

    def dgrad(dy_, x_, w_):
        _, vjp = jax.vjp(lambda xx: f(xx, w_), x_)
        return vjp(dy_)[0]

    def wgrad(dy_, x_, w_):
        _, vjp = jax.vjp(lambda ww: f(x_, ww), w_)
        return vjp(dy_)[0]

    t_dg = timed_loop(dgrad, (dy, x, w))
    t_wg = timed_loop(wgrad, (dy, x, w))
    return fl, t_fwd, t_dg, t_wg


# the measured floor-blockers (NCHW table, round 5): early-stage shapes
# whose small channel counts underfill the 128x128 MXU
WORST = ["conv0_7x7s2", "s0_1x1_64_64", "s0_3x3_64_64", "s0_1x1_64_256",
         "s0_1x1_256_64", "s1_sc_256_512s2"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="only the 4 heaviest shapes")
    ap.add_argument("--worst", action="store_true",
                    help="only the measured floor-blocker shapes")
    ap.add_argument("--pad-conv0", action="store_true",
                    help="also bench conv0 with cin padded 3 -> 8 "
                         "(TF/s reported on the PADDED flops; compare "
                         "the ms columns against conv0_7x7s2)")
    ap.add_argument("--layout", default="NCHW", choices=["NCHW", "NHWC"])
    args = ap.parse_args()

    shapes = SHAPES
    if args.quick:
        shapes = [s for s in SHAPES if s[0] in
                  ("conv0_7x7s2", "s0_3x3_64_64", "s0_1x1_256_64",
                   "s1_3x3_128")]
    if args.worst:
        shapes = [s for s in SHAPES if s[0] in WORST]
    if args.pad_conv0:
        shapes = list(shapes) + [("conv0_pad8", 8, 64, 7, 2, 224),
                                 ("conv0_pad4", 4, 64, 7, 2, 224)]

    print("%-18s %7s | %7s %6s | %7s %6s | %7s %6s   (%s, bf16)"
          % ("shape", "GFLOP", "fwd ms", "TF/s", "dgrad", "TF/s",
             "wgrad", "TF/s", args.layout), flush=True)
    tot = {"fwd": 0.0, "dg": 0.0, "wg": 0.0}
    for name, cin, cout, k, s, hin in shapes:
        fl, tf, td, tw = bench_shape(name, cin, cout, k, s, hin,
                                     layout=args.layout)
        print("%-18s %7.1f | %7.3f %6.1f | %7.3f %6.1f | %7.3f %6.1f"
              % (name, fl / 1e9, tf * 1e3, fl / tf / 1e12,
                 td * 1e3, fl / td / 1e12, tw * 1e3, fl / tw / 1e12),
              flush=True)
        tot["fwd"] += tf
        tot["dg"] += td
        tot["wg"] += tw
    print("unique-shape totals (x1 each): fwd %.2f ms, dgrad %.2f ms, "
          "wgrad %.2f ms" % (tot["fwd"] * 1e3, tot["dg"] * 1e3,
                             tot["wg"] * 1e3), flush=True)


if __name__ == "__main__":
    main()
