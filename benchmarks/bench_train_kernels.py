#!/usr/bin/env python
"""Benchmark: the LM training step's Pallas kernels, off/on x
default/autotuned blocks.

The ISSUE-16 acceptance surface for the 0.15-MFU plateau: one attention
LM trained fwd+bwd+update under a 2x2 grid —

* ``kernels=off``  — the stock einsum/XLA graph (the baseline row);
* ``kernels=on``   — ``MXNET_PALLAS_FUSED`` (LN->linear epilogue
  segments), ``MXNET_PALLAS_ATTENTION`` (flash attention) and
  ``MXNET_PALLAS_UPDATE`` (fused multi-tensor optimizer) all armed;
* ``blocks=default``   — each kernel's module-constant block shapes;
* ``blocks=autotuned`` — ``MXNET_PALLAS_TUNE`` armed against a fresh
  tuning-cache directory, so every kernel's block shape resolves
  through an on-device sweep (:mod:`mxnet_tpu.ops.tuning`) and the
  winners persist for the timed window.

Mirrors bench.py's contract: ONE json line on stdout —
``{"metric": "lm_train_kernels_tokens_per_sec", "value", "unit",
"vs_baseline", ...}`` — where ``vs_baseline`` is the armed+autotuned
config's tokens/s over the all-off default config on the same chips.
Extras carry the full grid (per-config tokens/s, wall, dispatch paths,
sweep probe counts) and the per-program ``mfu_table`` rows, including
each config's ``lm_fused`` row so the kernel-vs-einsum HBM pricing
travels with the measurement.  Per-config detail goes to stderr, one
json per run.

Env knobs: BENCH_T, BENCH_BATCH, BENCH_EMBED, BENCH_FFN, BENCH_HEADS,
BENCH_VOCAB, BENCH_LAYERS, BENCH_ITERS, BENCH_DTYPE.

``--smoke``: the tier-1 CI entry — tiny dims on CPU with
``MXNET_PALLAS_INTERPRET``, deterministic assertions only (the
dispatch tripwires and the priced-bytes ordering; interpret-mode wall
clock is not a measurement).
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SMOKE = "--smoke" in sys.argv

if SMOKE:
    os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np

import bench as _bench


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import config as _config
    from mxnet_tpu import ndarray as nd
    from mxnet_tpu import obs
    from mxnet_tpu.io import DataBatch, DataDesc
    from mxnet_tpu.models import attention_lm
    from mxnet_tpu.ops import tuning
    from mxnet_tpu.ops.fused_lm import FUSED_PATH

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    interp = not on_tpu  # CPU/GPU harness: kernels run in interpret mode

    t = int(os.environ.get("BENCH_T",
                           "128" if SMOKE else "2048" if on_tpu else "128"))
    b = int(os.environ.get("BENCH_BATCH", "2" if SMOKE else "8"))
    e = int(os.environ.get("BENCH_EMBED",
                           "64" if SMOKE else "1024" if on_tpu else "64"))
    ffn = int(os.environ.get("BENCH_FFN",
                             "128" if SMOKE else "4096" if on_tpu else "128"))
    heads = int(os.environ.get("BENCH_HEADS", "2" if SMOKE else "8"))
    vocab = int(os.environ.get("BENCH_VOCAB",
                               "32" if SMOKE else
                               "8192" if on_tpu else "64"))
    layers = int(os.environ.get("BENCH_LAYERS", "1" if SMOKE else "4"))
    n_iters = int(os.environ.get("BENCH_ITERS",
                                 "1" if SMOKE else "10" if on_tpu else "2"))
    dtype = os.environ.get("BENCH_DTYPE",
                           "bfloat16" if on_tpu else "float32")
    warmup = 3 if on_tpu else 1

    # m = B*T must satisfy pallas_fused.supported's m % 256 gate or the
    # whole grid degenerates to einsum-gated
    assert (b * t) % 256 == 0, (b, t)

    rng = np.random.RandomState(0)
    x = rng.randint(0, vocab, size=(b, t)).astype(np.float32)
    y = np.concatenate([x[:, 1:], np.zeros((b, 1), np.float32)], axis=1)

    ctx = mx.tpu(0) if on_tpu else mx.cpu()
    peak, kind = _bench._peak_for(jax.devices()[0])

    # separate cache dirs per blocks-mode: default runs must never read
    # the autotuned runs' persisted winners (tuning.resolve consults the
    # disk cache even when the sweep is not armed)
    cache_default = tempfile.mkdtemp(prefix="lmk_default_")
    cache_tuned = tempfile.mkdtemp(prefix="lmk_tuned_")

    def measure(kernels_on, autotuned):
        name = "lmk_%s_%s" % ("on" if kernels_on else "off",
                              "tuned" if autotuned else "default")
        overrides = {
            "MXNET_PALLAS_FUSED": kernels_on,
            "MXNET_PALLAS_ATTENTION": kernels_on,
            "MXNET_PALLAS_UPDATE": kernels_on,
            "MXNET_PALLAS_INTERPRET": kernels_on and interp,
            "MXNET_PALLAS_TUNE": autotuned,
            "MXNET_PROGRAM_CACHE": cache_tuned if autotuned
            else cache_default,
        }
        tuning.reset_memo()
        probes_before = tuning.PROBE_COUNT["n"]
        with _config.overrides(**overrides):
            net = attention_lm.get_symbol(
                vocab_size=vocab, seq_len=t, num_layers=layers, embed=e,
                heads=heads, ffn_hidden=ffn)
            mod = mx.mod.Module(net, context=ctx, compute_dtype=dtype)
            data_desc = DataDesc("data", (b, t), layout="NT")
            label_desc = DataDesc("softmax_label", (b, t), layout="NT")
            mod.bind(data_shapes=[data_desc], label_shapes=[label_desc])
            mod.init_params(mx.initializer.Xavier(rnd_type="gaussian"))
            mod.init_optimizer(optimizer="sgd",
                               optimizer_params={"learning_rate": 0.01})
            batch = DataBatch([nd.array(x)], [nd.array(y)],
                              provide_data=[data_desc],
                              provide_label=[label_desc])

            def sync():
                import jax.numpy as jnp

                if mod._fused_step is not None:
                    src = next(iter(mod._fused_step.params.values()))
                else:
                    src = mod._exec_group.param_arrays[-1].data
                return float(jnp.sum(src.astype(jnp.float32)))

            FUSED_PATH["last"] = None
            for _ in range(warmup):
                mod.forward_backward(batch)
                mod.update()
            sync()
            if mod._fused_step is not None:
                # rename the roofline rows so each grid config keeps its
                # own train_step / opt_update / lm_fused join
                mod._fused_step.telemetry_name = name
                mod._fused_step._static_registered = False
            tic = time.time()
            for _ in range(n_iters):
                mod.forward_backward(batch)
                mod.update()
            sync()
            dt = time.time() - tic
            rows = [r for r in obs.mfu_table(peak)
                    if r["program"].startswith(name)]

        return {"config": name,
                "tokens_per_sec": round(b * t * n_iters / dt, 1),
                "wall_s": round(dt, 4),
                "fused_path": FUSED_PATH["last"],
                "tune_probes": tuning.PROBE_COUNT["n"] - probes_before,
                "mfu_table": rows}

    grid = [measure(kernels_on, autotuned)
            for kernels_on in (False, True)
            for autotuned in (False, True)]
    for row in grid:
        print(json.dumps(row), file=sys.stderr, flush=True)

    by_name = {r["config"]: r for r in grid}
    base = by_name["lmk_off_default"]
    best = by_name["lmk_on_tuned"]

    # deterministic halves: dispatch tripwires and priced-bytes ordering
    assert base["fused_path"] == "einsum", base
    assert best["fused_path"] == "pallas", best
    assert best["tune_probes"] > 0, best
    assert by_name["lmk_on_default"]["tune_probes"] == 0, by_name
    fused_rows = [r for r in best["mfu_table"]
                  if r["program"].endswith("lm_fused")]
    assert fused_rows and fused_rows[0]["fused_path"] == "pallas", fused_rows
    assert fused_rows[0]["fused_kernel_bytes"] \
        < fused_rows[0]["fused_einsum_bytes"], fused_rows

    ratio = best["tokens_per_sec"] / base["tokens_per_sec"]
    print(_bench.contract_line(
        "lm_train_kernels_tokens_per_sec",
        best["tokens_per_sec"], "tok/s", round(ratio, 3),
        vs_einsum_default=round(ratio, 3),
        device_kind=kind, smoke=SMOKE, interpret=interp,
        dims={"b": b, "t": t, "embed": e, "ffn": ffn, "heads": heads,
              "vocab": vocab, "layers": layers, "iters": n_iters,
              "dtype": dtype},
        grid={r["config"]: {"tokens_per_sec": r["tokens_per_sec"],
                            "wall_s": r["wall_s"],
                            "fused_path": r["fused_path"],
                            "tune_probes": r["tune_probes"]}
              for r in grid},
        lm_fused=fused_rows[0],
        mfu_table=[r for g in grid for r in g["mfu_table"]]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
