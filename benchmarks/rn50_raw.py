"""Raw-JAX ResNet-50 v2 fwd+bwd+SGD, NCHW vs NHWC, to find the chip ceiling."""
import os
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

N = int(os.environ.get("N", "256"))
LAYOUT = os.environ.get("LAYOUT", "NHWC")
CAXIS = 1 if LAYOUT == "NCHW" else 3
DN = ("NCHW", "OIHW", "NCHW") if LAYOUT == "NCHW" else ("NHWC", "HWIO", "NHWC")

S2D = os.environ.get("S2D", "0") == "1"  # space-to-depth conv0 (MLPerf trick)
# pad conv0's input channels 3 -> PAD0 with zeros (weights for the pad
# channels are zero and see zero inputs, so the math is exact); isolated
# per-shape timing says the 3-channel conv0 underfills the MXU
PAD0 = int(os.environ.get("PAD0", "0"))

rng = np.random.RandomState(0)
params = {}
FLOPS = [0]


def conv_w(name, cin, cout, k):
    shape = (cout, cin, k, k) if LAYOUT == "NCHW" else (k, k, cin, cout)
    params[name] = jnp.asarray(rng.normal(0, 0.05, shape), jnp.float32)


def bn_w(name, c):
    params[name + "_g"] = jnp.ones((c,), jnp.float32)
    params[name + "_b"] = jnp.zeros((c,), jnp.float32)


def conv(p, name, x, k, s):
    w = p[name].astype(jnp.bfloat16)
    pad = k // 2
    cin = w.shape[1] if LAYOUT == "NCHW" else w.shape[2]
    cout = w.shape[0] if LAYOUT == "NCHW" else w.shape[3]
    h = x.shape[2 if LAYOUT == "NCHW" else 1]
    ho = (h + 2 * pad - k) // s + 1
    FLOPS[0] += 2 * N * cout * cin * k * k * ho * ho
    return lax.conv_general_dilated(x, w, (s, s), [(pad, pad)] * 2,
                                    dimension_numbers=DN)


BN_MODE = os.environ.get("BN", "naive")
REMAT = os.environ.get("REMAT", "0") == "1"


def bn_relu(p, name, x, relu=True):
    if BN_MODE == "none":
        return jnp.maximum(x, 0) if relu else x
    red = tuple(i for i in range(4) if i != CAXIS)
    bshape = tuple(x.shape[CAXIS] if i == CAXIS else 1 for i in range(4))
    if BN_MODE == "onepass":
        # sum and sumsq in one fused reduction pass (var = E[x^2]-E[x]^2)
        x32 = x.astype(jnp.float32)
        m = jnp.mean(x32, axis=red)
        v = jnp.maximum(jnp.mean(jnp.square(x32), axis=red) - jnp.square(m),
                        0.0)
    else:
        x32 = x.astype(jnp.float32) if BN_MODE != "bf16" else x
        m = jnp.mean(x32, axis=red)
        v = jnp.var(x32, axis=red)
        if BN_MODE == "bf16":
            m, v = m.astype(jnp.float32), v.astype(jnp.float32)
    inv = lax.rsqrt(v + 2e-5)
    scale = (inv * p[name + "_g"]).astype(x.dtype).reshape(bshape)
    shift = (p[name + "_b"] - m * inv * p[name + "_g"]).astype(x.dtype).reshape(bshape)
    y = x * scale + shift
    return jnp.maximum(y, 0) if relu else y


UNITS = [3, 4, 6, 3]
FILTERS = [256, 512, 1024, 2048]

# build params
if S2D:
    conv_w("conv0", 12, 64, 4)  # 2x2 space-to-depth: 224x224x3 -> 112x112x12
elif PAD0:
    conv_w("conv0", PAD0, 64, 7)
else:
    conv_w("conv0", 3, 64, 7)
bn_w("bn0", 64)
cin = 64
for si, (u, f) in enumerate(zip(UNITS, FILTERS)):
    mid = f // 4
    for ui in range(u):
        nm = f"s{si}u{ui}"
        bn_w(nm + "_bn1", cin)
        conv_w(nm + "_c1", cin, mid, 1)
        bn_w(nm + "_bn2", mid)
        conv_w(nm + "_c2", mid, mid, 3)
        bn_w(nm + "_bn3", mid)
        conv_w(nm + "_c3", mid, f, 1)
        if ui == 0:
            conv_w(nm + "_sc", cin, f, 1)
        cin = f
bn_w("bn_final", 2048)
params["fc_w"] = jnp.asarray(rng.normal(0, 0.01, (2048, 1000)), jnp.float32)
params["fc_b"] = jnp.zeros((1000,), jnp.float32)


def forward(p, x, y):
    if S2D:
        # x arrives pre-space-to-depth'd as (N,112,112,12); 4x4/s2 conv == 7x7/s2
        # on the original image up to the (negligible) 8th tap row/col
        h = conv(p, "conv0", x, 4, 1)
    else:
        h = conv(p, "conv0", x, 7, 2)
    h = bn_relu(p, "bn0", h)
    # maxpool 3x3 s2
    pads = [(0, 0)] * 4
    pads[2 if LAYOUT == "NCHW" else 1] = (1, 1)
    pads[3 if LAYOUT == "NCHW" else 2] = (1, 1)
    win = [1, 1, 3, 3] if LAYOUT == "NCHW" else [1, 3, 3, 1]
    st = [1, 1, 2, 2] if LAYOUT == "NCHW" else [1, 2, 2, 1]
    h = lax.reduce_window(h, -jnp.inf, lax.max, win, st, pads)
    from jax.ad_checkpoint import checkpoint_name

    def unit(h, nm, s, first):
        a1 = bn_relu(p, nm + "_bn1", h)
        c1 = checkpoint_name(conv(p, nm + "_c1", a1, 1, 1), "conv")
        a2 = bn_relu(p, nm + "_bn2", c1)
        c2 = checkpoint_name(conv(p, nm + "_c2", a2, 3, s), "conv")
        a3 = bn_relu(p, nm + "_bn3", c2)
        c3 = conv(p, nm + "_c3", a3, 1, 1)
        sc = conv(p, nm + "_sc", a1, 1, s) if first else h
        return c3 + sc

    if REMAT:
        unit = jax.checkpoint(
            unit, policy=jax.checkpoint_policies.save_only_these_names("conv"),
            static_argnums=(1, 2, 3))

    cin = 64
    for si, (u, f) in enumerate(zip(UNITS, FILTERS)):
        mid = f // 4
        for ui in range(u):
            nm = f"s{si}u{ui}"
            s = 2 if (ui == 0 and si > 0) else 1
            h = unit(h, nm, s, ui == 0)
            cin = f
    h = bn_relu(p, "bn_final", h)
    h = jnp.mean(h.astype(jnp.float32), axis=tuple(i for i in range(1, 4) if i != CAXIS))
    logits = h @ p["fc_w"] + p["fc_b"]
    lse = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, y[:, None], axis=1)[:, 0]
    return jnp.mean(lse - ll)


MODE = os.environ.get("MODE", "train")
FUSED = os.environ.get("FUSED", "0") == "1"  # pallas fused BN+ReLU+1x1conv


def _channel_stats(x2d):
    x32 = x2d.astype(jnp.float32)
    return jnp.sum(x32, axis=0), jnp.sum(jnp.square(x32), axis=0)


def _bn_coeffs(p, name, s1, s2, count):
    mean = s1 / count
    var = jnp.maximum(s2 / count - jnp.square(mean), 0.0)
    inv = lax.rsqrt(var + 2e-5)
    g = p[name + "_g"]
    return inv * g, p[name + "_b"] - mean * inv * g


def forward_fused(p, x, y):
    """NHWC trunk where BN statistics flow through matmul epilogues and
    BN-apply+ReLU rides the 1x1-conv prologues (ops/pallas_fused kernels)."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from mxnet_tpu.ops import pallas_fused as pf

    assert LAYOUT == "NHWC" and not S2D
    h = conv(p, "conv0", x, 7, 2)
    h = bn_relu(p, "bn0", h)
    h = lax.reduce_window(h, -jnp.inf, lax.max, [1, 3, 3, 1], [1, 2, 2, 1],
                          [(0, 0), (1, 1), (1, 1), (0, 0)])

    hs1, hs2 = _channel_stats(h.reshape(-1, h.shape[-1]))
    for si, (u, f) in enumerate(zip(UNITS, FILTERS)):
        mid = f // 4
        for ui in range(u):
            nm = f"s{si}u{ui}"
            s = 2 if (ui == 0 and si > 0) else 1
            b, hh, ww, c = h.shape
            m = b * hh * ww
            sc1, sh1 = _bn_coeffs(p, nm + "_bn1", hs1, hs2, m)
            h2d = h.reshape(m, c)
            w1 = p[nm + "_c1"].reshape(c, mid).astype(jnp.bfloat16)
            c1, c1s1, c1s2 = pf.fused_scale_relu_matmul(h2d, sc1, sh1, w1)
            sc2, sh2 = _bn_coeffs(p, nm + "_bn2", c1s1, c1s2, m)
            a2 = jnp.maximum(c1.astype(jnp.float32) * sc2 + sh2, 0.0)
            a2 = a2.astype(h.dtype).reshape(b, hh, ww, mid)
            c2 = conv(p, nm + "_c2", a2, 3, s)
            ho, wo = c2.shape[1], c2.shape[2]
            m2 = b * ho * wo
            c2d = c2.reshape(m2, mid)
            c2s1, c2s2 = _channel_stats(c2d)
            sc3, sh3 = _bn_coeffs(p, nm + "_bn3", c2s1, c2s2, m2)
            if ui == 0:
                scd = h2d if s == 1 else h[:, ::2, ::2, :].reshape(m2, c)
                wsc = p[nm + "_sc"].reshape(c, f).astype(jnp.bfloat16)
                res, _, _ = pf.fused_scale_relu_matmul(scd, sc1, sh1, wsc)
            else:
                res = h2d
            w3 = p[nm + "_c3"].reshape(mid, f).astype(jnp.bfloat16)
            out, hs1, hs2 = pf.fused_scale_relu_matmul(
                c2d, sc3, sh3, w3, residual=res)
            h = out.reshape(b, ho, wo, f)
    scf, shf = _bn_coeffs(p, "bn_final", hs1, hs2,
                          h.shape[0] * h.shape[1] * h.shape[2])
    hf = jnp.maximum(h.astype(jnp.float32) * scf + shf, 0.0)
    hv = jnp.mean(hf, axis=(1, 2))
    logits = hv @ p["fc_w"] + p["fc_b"]
    lse = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, y[:, None], axis=1)[:, 0]
    return jnp.mean(lse - ll)


def train(p, mom, x, y):
    fwd = forward_fused if FUSED else forward
    if MODE == "fwd":
        return p, mom, fwd(p, x, y)
    loss, g = jax.value_and_grad(fwd)(p, x, y)
    newp, newm = {}, {}
    for k in p:
        m = 0.9 * mom[k] + g[k]
        newm[k] = m
        newp[k] = p[k] - 0.1 * m
    return newp, newm, loss


mom = {k: jnp.zeros_like(v) for k, v in params.items()}
cin0 = PAD0 if PAD0 else 3
if LAYOUT == "NCHW":
    x = np.zeros((N, cin0, 224, 224), np.float32)
    x[:, :3] = rng.rand(N, 3, 224, 224)
    x = jnp.asarray(x, jnp.bfloat16)
elif S2D:
    x = jnp.asarray(rng.rand(N, 112, 112, 12), jnp.bfloat16)
else:
    x = np.zeros((N, 224, 224, cin0), np.float32)
    x[..., :3] = rng.rand(N, 224, 224, 3)
    x = jnp.asarray(x, jnp.bfloat16)
y = jnp.asarray(rng.randint(0, 1000, (N,)), jnp.int32)

f = jax.jit(train, donate_argnums=(0, 1))
if os.environ.get("COST", "0") == "1":
    compiled = f.lower(params, mom, x, y).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    print("raw", {k: ca.get(k) for k in ("flops", "bytes accessed")},
          flush=True)
    hlo_out = os.environ.get("HLO_OUT")
    if hlo_out:
        with open(hlo_out, "w") as fh:
            fh.write(compiled.as_text())
    raise SystemExit
t0 = time.time()
params, mom, loss = f(params, mom, x, y)
float(loss)
print(f"compile+first: {time.time()-t0:.1f}s, flops/step counted={FLOPS[0]/1e12:.2f}T (fwd only)", flush=True)
t0 = time.time()
iters = 20
for _ in range(iters):
    params, mom, loss = f(params, mom, x, y)
float(loss)
dt = (time.time() - t0) / iters
tf = 3 * FLOPS[0] / dt / 1e12
print(f"{LAYOUT} N={N}: {dt*1e3:.1f} ms/step, {N/dt:.0f} img/s, "
      f"{tf:.1f} TFLOP/s, MFU {tf/197*100:.1f}%", flush=True)
