"""PTB-shaped bucketed LSTM throughput: fused shared-store vs eager path.

Measures tokens/sec through BucketingModule.fit on a synthetic corpus with
PTB-like bucket structure (buckets 10/20/30/40, vocab 10k, 2-layer LSTM 200
hidden — the reference example/rnn/lstm_bucketing.py configuration scaled to
bench quickly).  Run:  python benchmarks/bench_bucketing.py [--eager]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--eager", action="store_true",
                    help="disable the fused train step")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--sentences", type=int, default=2000)
    args = ap.parse_args()
    if args.eager:
        os.environ["MXNET_FUSED_TRAIN_STEP"] = "0"

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import symbol as sym
    from mxnet_tpu import rnn as rnn_mod

    vocab, embed_dim, hidden, batch = 10000, 200, 200, 32
    buckets = [10, 20, 30, 40]

    rng = np.random.RandomState(0)
    sentences = []
    for _ in range(args.sentences):
        length = rng.randint(5, 41)
        sentences.append(rng.randint(1, vocab, size=length).tolist())
    it = rnn_mod.BucketSentenceIter(sentences, batch_size=batch,
                                    buckets=buckets, seed=0)

    def sym_gen(seq_len):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        net = sym.Embedding(data, input_dim=vocab, output_dim=embed_dim,
                            name="embed")
        for i in range(2):
            cell = mx.rnn.LSTMCell(hidden, prefix="l%d_" % i)
            net, _ = cell.unroll(seq_len, inputs=net, merge_outputs=True)
        pred = sym.FullyConnected(sym.Reshape(net, shape=(-1, hidden)),
                                  num_hidden=vocab, name="fc")
        flat = sym.Reshape(label, shape=(-1,))
        out = sym.SoftmaxOutput(pred, flat, use_ignore=True, ignore_label=-1,
                                name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=it.default_bucket_key)

    tokens_per_epoch = sum(min(len(s), buckets[-1]) for s in sentences)
    epoch_times = []

    t_wall = time.perf_counter()

    def batch_cb(param):
        pass

    class EpochTimer:
        def __init__(self):
            self.t0 = time.perf_counter()

        def __call__(self, epoch, *a):
            now = time.perf_counter()
            epoch_times.append(now - self.t0)
            self.t0 = now

    mod.fit(it, optimizer="adam", optimizer_params={"learning_rate": 0.001},
            initializer=mx.initializer.Xavier(), num_epoch=args.epochs,
            eval_metric=mx.metric.Perplexity(ignore_label=-1),
            epoch_end_callback=EpochTimer())
    wall = time.perf_counter() - t_wall

    # first epoch pays compilation; steady state = later epochs
    steady = epoch_times[1:] or epoch_times
    tok_s = tokens_per_epoch / (sum(steady) / len(steady))
    mode = "eager" if args.eager else "fused"
    print({"mode": mode, "tokens_per_sec": round(tok_s, 1),
           "epoch_times_s": [round(t, 2) for t in epoch_times],
           "wall_s": round(wall, 1)})


if __name__ == "__main__":
    main()
