"""Probe: conv layout strategies on the TPU chip.

Times fwd+bwd of a ResNet-50-ish conv/BN/relu stack under three layouts:
  nchw      - lax.conv with NCHW/OIHW dims (current ops/nn.py behavior)
  nhwc_wrap - NCHW graph, each conv locally transposes to NHWC and back
  nhwc_full - whole stack natively NHWC/HWIO

Run on the bench chip to decide how ops/nn.py should lay out convs.

``--kv`` probes KV CACHE POOL layouts instead (the ROADMAP's
wire-the-probe clause): decode attention over a paged (P, page_tokens,
E) pool is timed with the pool ``device_put`` under each candidate
``major_to_minor`` permutation, and the winner prints as the
``MXNET_KV_LAYOUT`` value to export — decode.DecodePredictor applies it
to every pool at allocation (``ops.attention.apply_kv_layout``).
Backends that refuse a layout request (XLA:CPU) report it and keep the
native row-major; the knob is then best left empty.

Output contract: ONE bench.contract_line json per probed layout on
stdout (winner flagged with ``"winner": true``); the human-readable
table goes to stderr.  The ``--kv`` winner is also INGESTED into the
persistent tuning cache (:mod:`mxnet_tpu.ops.tuning`, op
``"kv_layout"``), where :func:`mxnet_tpu.ops.attention.apply_kv_layout`
consults it whenever ``MXNET_KV_LAYOUT`` is unset — probe once on the
bench chip, every later process on the same device generation places
its pools with the winning layout.
"""
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench as _bench

# (in_ch, out_ch, spatial, stride, n_blocks) rough resnet50 stage shapes
STAGES = [
    (64, 64, 56, 1, 3),
    (256, 128, 28, 2, 4),
    (512, 256, 14, 2, 6),
    (1024, 512, 7, 2, 3),
]
BATCH = 256
DTYPE = jnp.bfloat16


def make_params(mode, key):
    params = []
    prev = STAGES[0][0]
    for (cin, cout, sp, st, nb) in STAGES:
        for b in range(nb):
            ci = prev
            prev = cout
            if mode == "nhwc_full":
                w = jax.random.normal(key, (3, 3, ci, cout), DTYPE) * 0.05
            else:
                w = jax.random.normal(key, (cout, ci, 3, 3), DTYPE) * 0.05
            gamma = jnp.ones((cout,), jnp.float32)
            beta = jnp.zeros((cout,), jnp.float32)
            params.append((w, gamma, beta))
    return params


def bn(x, gamma, beta, caxis):
    red = tuple(i for i in range(x.ndim) if i != caxis)
    bshape = tuple(x.shape[caxis] if i == caxis else 1 for i in range(x.ndim))
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=red)
    var = jnp.var(x32, axis=red)
    inv = lax.rsqrt(var.reshape(bshape) + 1e-5)
    out = (x32 - mean.reshape(bshape)) * inv * gamma.reshape(bshape) \
        + beta.reshape(bshape)
    return out.astype(x.dtype)


def stack(mode, params, x):
    i = 0
    for (cin, cout, sp, st, nb) in STAGES:
        for b in range(nb):
            w, gamma, beta = params[i]
            i += 1
            stride = (st, st) if b == 0 else (1, 1)
            if mode == "nchw":
                x = lax.conv_general_dilated(
                    x, w, stride, ((1, 1), (1, 1)),
                    dimension_numbers=("NCHW", "OIHW", "NCHW"))
                x = bn(x, gamma, beta, 1)
            elif mode == "nhwc_wrap":
                xt = jnp.transpose(x, (0, 2, 3, 1))
                wt = jnp.transpose(w, (2, 3, 1, 0))
                xt = lax.conv_general_dilated(
                    xt, wt, stride, ((1, 1), (1, 1)),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
                x = jnp.transpose(xt, (0, 3, 1, 2))
                x = bn(x, gamma, beta, 1)
            else:  # nhwc_full
                x = lax.conv_general_dilated(
                    x, w, stride, ((1, 1), (1, 1)),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
                x = bn(x, gamma, beta, 3)
            x = jnp.maximum(x, 0)
    return x


def loss_fn(mode, params, x):
    out = stack(mode, params, x)
    return jnp.sum(out.astype(jnp.float32))


def bench(mode, iters=10):
    key = jax.random.PRNGKey(0)
    params = make_params(mode, key)
    if mode == "nhwc_full":
        x = jax.random.normal(key, (BATCH, 56, 56, 64), DTYPE)
    else:
        x = jax.random.normal(key, (BATCH, 64, 56, 56), DTYPE)

    grad = jax.jit(jax.grad(functools.partial(loss_fn, mode), argnums=0))

    def fence(g):
        # tunneled platform: block_until_ready returns early; a value fetch
        # is the only reliable sync
        return float(jnp.sum(g[0][0].astype(jnp.float32)))

    g = grad(params, x)
    fence(g)
    tic = time.time()
    for _ in range(iters):
        g = grad(params, x)
    fence(g)
    dt = (time.time() - tic) / iters
    print("%-10s %7.2f ms/step  %7.1f img/s" % (mode, dt * 1e3, BATCH / dt),
          file=sys.stderr)
    return dt


def _kv_place(buf, order):
    """device_put ``buf`` with the requested major_to_minor order (None =
    backend native).  Raises if the backend refuses the layout."""
    if order is None:
        return jax.device_put(buf, jax.devices()[0])
    from jax.experimental.layout import DeviceLocalLayout, Layout
    from jax.sharding import SingleDeviceSharding

    return jax.device_put(buf, Layout(
        DeviceLocalLayout(major_to_minor=tuple(order)),
        SingleDeviceSharding(jax.devices()[0])))


def bench_kv(iters=30):
    """Time one paged decode-attention step per candidate pool layout.

    Serving-shaped dims: B slots of a T-token cache in page_tokens pages,
    decode batch = slots (the bandwidth-bound shape the fused kernel and
    the einsum path both stream).  The SAME jitted program runs for every
    candidate; only the pool's device layout changes, so the delta IS the
    layout.  Prints the winner as an ``export MXNET_KV_LAYOUT=...`` line
    (empty = native wins or the backend refuses overrides), emits one
    contract_line json per candidate on stdout, and ingests the winner
    into the persistent tuning cache (op ``"kv_layout"``) so
    ``apply_kv_layout`` finds it with the knob unset."""
    from mxnet_tpu.ops import attention as attn
    from mxnet_tpu.ops import tuning

    b, t_cache, e, heads, pt = 8, 2048, 1024, 8, 16
    m = t_cache // pt
    pages = b * m + 1
    rng = np.random.RandomState(0)
    kp = jnp.asarray(rng.randn(pages, pt, e).astype(np.float32))
    vp = jnp.asarray(rng.randn(pages, pt, e).astype(np.float32))
    table = jnp.asarray(
        1 + (np.arange(b)[:, None] * m + np.arange(m)[None, :]), jnp.int32)
    lens = jnp.full((b,), t_cache, jnp.int32)
    q = jnp.asarray(rng.randn(b, 1, e).astype(np.float32))

    fn = jax.jit(lambda q_, k_, v_, t_, l_: attn.paged_attend(
        q_, k_, v_, t_, l_, num_heads=heads))

    candidates = [("native", None), ("0,1,2", (0, 1, 2)),
                  ("1,0,2", (1, 0, 2)), ("2,1,0", (2, 1, 0)),
                  ("0,2,1", (0, 2, 1))]
    results = []
    for name, order in candidates:
        try:
            kpl, vpl = _kv_place(kp, order), _kv_place(vp, order)
        except Exception as exc:
            print("%-8s unsupported on this backend (%s)"
                  % (name, str(exc)[:80]), file=sys.stderr)
            continue
        out = fn(q, kpl, vpl, table, lens)
        float(jnp.sum(out))                       # sync fence
        tic = time.time()
        for _ in range(iters):
            out = fn(q, kpl, vpl, table, lens)
        float(jnp.sum(out))
        dt = (time.time() - tic) / iters
        gbps = 2 * pages * pt * e * 4 / dt / 1e9
        print("%-8s %8.3f ms/step  %8.1f GB/s pool-stream"
              % (name, dt * 1e3, gbps), file=sys.stderr)
        results.append((dt, name, gbps))
    if results:
        base_dt = results[0][0]
        best_dt, best, _ = min(results)
        for dt, name, gbps in results:
            print(_bench.contract_line(
                "kv_layout_%s_ms" % name.replace(",", ""),
                round(dt * 1e3, 4), "ms", round(base_dt / dt, 3),
                layout=name, pool_stream_gbps=round(gbps, 1),
                winner=name == best))
        print("winner: %s" % best, file=sys.stderr)
        print("export MXNET_KV_LAYOUT=%s"
              % ("" if best == "native" else best), file=sys.stderr)
        # ingest: apply_kv_layout consults this entry whenever the knob
        # is unset, keyed by pool rank + dtype on this device generation
        key = tuning.put(
            "kv_layout", tuning.shape_class_for(rank=kp.ndim),
            kp.dtype.name,
            {"kv_layout": "" if best == "native" else best},
            version=1,
            extra={"probed": [{"layout": n, "ms": round(d * 1e3, 4)}
                              for d, n, _ in results]})
        print("tuning cache: kv_layout winner persisted (%s)" % key,
              file=sys.stderr)


if __name__ == "__main__":
    print("device:", jax.devices()[0].device_kind, file=sys.stderr)
    if "--kv" in sys.argv:
        bench_kv()
    else:
        timings = [(bench(mode), mode)
                   for mode in ("nchw", "nhwc_wrap", "nhwc_full")]
        base_dt = timings[0][0]
        best = min(timings)[1]
        for dt, mode in timings:
            print(_bench.contract_line(
                "conv_layout_%s_ms" % mode, round(dt * 1e3, 2), "ms",
                round(base_dt / dt, 3), layout=mode,
                images_per_sec=round(BATCH / dt, 1),
                winner=mode == best))
