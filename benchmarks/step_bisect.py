"""Bisect the fused ResNet-50 train step: where does the time go?

Times (a) forward-only, (b) forward+backward, (c) full fused step, and dumps
XLA cost_analysis flops for each to compare against the analytic 4.1 GFLOP
fwd / 12.3 GFLOP step per image.
"""
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu.io import DataBatch
from mxnet_tpu.models import resnet

BATCH = 256


def fence(tree):
    leaf = jax.tree_util.tree_leaves(tree)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def timeit(fn, *args, iters=10):
    out = fn(*args)
    fence(out)
    tic = time.time()
    for _ in range(iters):
        out = fn(*args)
    fence(out)
    return (time.time() - tic) / iters


def main():
    ctx = mx.tpu()
    net = resnet.get_symbol(1000, 50, (3, 224, 224))
    mod = mx.mod.Module(net, context=ctx, compute_dtype="bfloat16")
    mod.bind(data_shapes=[("data", (BATCH, 3, 224, 224))],
             label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          factor_type="in", magnitude=2))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                         "wd": 1e-4})
    step = mod._fused_step
    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (BATCH, 3, 224, 224)).astype(np.float32),
                 ctx=ctx)
    y = nd.array(rng.randint(0, 1000, (BATCH,)).astype(np.float32), ctx=ctx)
    batch = DataBatch([x], [y])

    # --- full fused step ---
    dt = timeit(lambda: (step.run(batch), step.params)[1])
    print("full step      : %7.2f ms  %7.1f img/s" % (dt * 1e3, BATCH / dt))

    # --- pieces, built from the executor's pure functions ---
    exe = step._exec
    cdtype = jnp.bfloat16
    params = {n: (v.astype(cdtype)
                  if jnp.issubdtype(v.dtype, jnp.floating) else v)
              for n, v in step.params.items()}
    aux = dict(step.aux)
    data = {"data": x.data.astype(cdtype), "softmax_label": y.data}
    key = jax.random.PRNGKey(0)

    grad_names = step._grad_names

    def fwd_only(params, data, aux):
        env = dict(params)
        env.update(data)
        outs, new_aux = exe._run_graph(env, aux, key, True)
        return outs

    f = jax.jit(fwd_only)
    dt = timeit(f, params, data, aux)
    print("forward only   : %7.2f ms  %7.1f img/s" % (dt * 1e3, BATCH / dt))
    ca = f.lower(params, data, aux).compile().cost_analysis()
    print("  fwd flops: %.2f G (expect ~%.0f G)"
          % (ca["flops"] / 1e9, 4.1 * BATCH))

    def fwd_bwd(params, data, aux):
        def loss(gvals):
            env = dict(params)
            env.update(zip(grad_names, gvals))
            env.update(data)
            outs, new_aux = exe._run_graph(env, aux, key, True)
            return outs, [new_aux[n] for n in step._aux_names]

        gvals = [params[n] for n in grad_names]
        outs, vjp_fn, new_aux = jax.vjp(loss, gvals, has_aux=True)
        cts = [jnp.ones_like(o) for o in outs]
        (grads,) = vjp_fn(cts)
        return grads

    g = jax.jit(fwd_bwd)
    dt = timeit(g, params, data, aux)
    print("fwd+bwd        : %7.2f ms  %7.1f img/s" % (dt * 1e3, BATCH / dt))
    ca = g.lower(params, data, aux).compile().cost_analysis()
    print("  step flops: %.2f G (expect ~%.0f G)"
          % (ca["flops"] / 1e9, 12.3 * BATCH))

    cstep = step._fn.lower(step.params, step.slots, step.aux, data,
                           np.zeros(len(grad_names), np.float32),
                           np.zeros(len(grad_names), np.float32),
                           np.float32(1), np.float32(-1), key) \
        .compile().cost_analysis()
    print("full-step flops: %.2f G  bytes accessed: %s GB"
          % (cstep["flops"] / 1e9,
             round(cstep.get("bytes accessed", 0) / 1e9, 2)))


if __name__ == "__main__":
    main()
