"""Framework-ism isolation probe for the framework-vs-raw step residual.

Round-4 located a ~9% gap between the executor-generated fused step and
``rn50_raw.py`` and bisected what it is NOT (wd, bn_data alone, layout,
dispatch).  This probe isolates it the other way: start from the raw
program and ADD each framework behavior — input BatchNorm with trainable
beta (bn_data), BN moving-stat aux updates, SoftmaxOutput semantics (full
probability output + custom (p-onehot) backward), the framework's
custom_vjp BN (centered one-pass stats + cond cancellation guard + hand
backward) — measuring each addition's cost in the same clean program.

Usage: python rn50_vars.py [variant ...]   (default: the full matrix)
Variants: base, bn_data, aux, smout, bn_custom, all
"""
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

N = int(os.environ.get("N", "256"))
UNITS = [3, 4, 6, 3]
FILTERS = [256, 512, 1024, 2048]
EPS = 2e-5

rng = np.random.RandomState(0)


def build_params(bn_data):
    params = {}
    aux = {}

    def conv_w(name, cin, cout, k):
        params[name] = jnp.asarray(
            rng.normal(0, 0.05, (cout, cin, k, k)), jnp.float32)

    def bn_w(name, c):
        params[name + "_g"] = jnp.ones((c,), jnp.float32)
        params[name + "_b"] = jnp.zeros((c,), jnp.float32)
        aux[name + "_mm"] = jnp.zeros((c,), jnp.float32)
        aux[name + "_mv"] = jnp.ones((c,), jnp.float32)

    if bn_data:
        bn_w("bn_data", 3)
    conv_w("conv0", 3, 64, 7)
    bn_w("bn0", 64)
    cin = 64
    for si, (u, f) in enumerate(zip(UNITS, FILTERS)):
        mid = f // 4
        for ui in range(u):
            nm = f"s{si}u{ui}"
            bn_w(nm + "_bn1", cin)
            conv_w(nm + "_c1", cin, mid, 1)
            bn_w(nm + "_bn2", mid)
            conv_w(nm + "_c2", mid, mid, 3)
            bn_w(nm + "_bn3", mid)
            conv_w(nm + "_c3", mid, f, 1)
            if ui == 0:
                conv_w(nm + "_sc", cin, f, 1)
            cin = f
    bn_w("bn_final", 2048)
    params["fc_w"] = jnp.asarray(rng.normal(0, 0.01, (2048, 1000)),
                                 jnp.float32)
    params["fc_b"] = jnp.zeros((1000,), jnp.float32)
    return params, aux


def conv(p, name, x, k, s):
    w = p[name].astype(jnp.bfloat16)
    pad = k // 2
    return lax.conv_general_dilated(
        x, w, (s, s), [(pad, pad)] * 2,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def _stats_onepass(x32):
    m = jnp.mean(x32, axis=(0, 2, 3))
    v = jnp.maximum(jnp.mean(jnp.square(x32), axis=(0, 2, 3))
                    - jnp.square(m), 0.0)
    return m, v


def _bn_custom_core(nocond=False, nocenter=False, autodiff=False):
    """The framework's _bn_train_core formulation (ops/nn.py): centered
    one-pass stats + cond cancellation guard, hand-written backward.
    ``nocond`` drops the guard, ``nocenter`` additionally drops the
    center subtraction, ``autodiff`` keeps the stats formulation but lets
    XLA derive the backward — cost-isolation knobs.  The SGCOND env flag
    is a separate whole-variant override (centered stats + stop-gradient
    cond correction + autodiff backward); combining it with
    nocond/nocenter would measure the sg path under those rows' labels,
    so that combination raises — run SGCOND=1 only against plain
    ``bn_custom`` rows.  ``autodiff`` takes precedence over SGCOND (its
    branch returns first) and keeps its own correct label."""

    if SGCOND and (nocond or nocenter):
        raise ValueError("SGCOND=1 replaces the whole stats/backward "
                         "formulation; combining it with nocond/nocenter "
                         "variants would print mislabeled rows")

    def centered_stats(x, center):
        """Shared one-pass centered moments + cancellation predicate —
        ONE copy, so sg-cond rows measure the same formulation as the
        custom-vjp rows."""
        bshape = (1, x.shape[1], 1, 1)
        x32 = x.astype(jnp.float32)
        if nocenter:
            xc = x32
            center = jnp.zeros_like(center)
        else:
            xc = x32 - center.reshape(bshape)
        mc = jnp.mean(xc, axis=(0, 2, 3))
        var_fast = jnp.maximum(jnp.mean(jnp.square(xc), axis=(0, 2, 3))
                               - jnp.square(mc), 0.0)
        mc2 = jnp.square(mc)
        bad = jnp.any((var_fast <= 1e-5 * mc2) & (1e-7 * mc2 > EPS))
        return mc + center, var_fast, bad

    def stats(x, center):
        bshape = (1, x.shape[1], 1, 1)
        mean, var_fast, bad = centered_stats(x, center)
        if nocond:
            return mean, var_fast

        def refine(_):
            m = jax.lax.stop_gradient(mean).reshape(bshape)
            return jnp.mean(jnp.square(x.astype(jnp.float32) - m),
                            axis=(0, 2, 3))

        var = jax.lax.cond(bad, refine, lambda _: var_fast, None)
        return mean, var

    def apply(x, gamma, beta, mean, inv):
        bshape = (1, x.shape[1], 1, 1)
        scale = (inv * gamma).astype(x.dtype)
        shift = (beta - mean * inv * gamma).astype(x.dtype)
        return x * scale.reshape(bshape) + shift.reshape(bshape)

    if autodiff:
        # same stats formulation, XLA-derived backward (full BN
        # semantics: gradients flow through mean/var like the base path)
        def bn_ad(x, gamma, beta, center):
            mean, var = stats(x, center)
            inv = jax.lax.rsqrt(var + EPS)
            return apply(x, gamma, beta, mean, inv), mean, var
        return bn_ad

    if SGCOND:
        # autodiff-safe guard: the cond only contributes a STOP-GRADIENT
        # value correction, so differentiation never enters the branches
        # (no giant branch residuals -> no OOM) while the primal value is
        # still refined on cancellation
        def bn_sg(x, gamma, beta, center):
            bshape = (1, x.shape[1], 1, 1)
            mean, var_fast, bad = centered_stats(x, center)

            def corr(_):
                m = jax.lax.stop_gradient(mean).reshape(bshape)
                true = jnp.mean(
                    jnp.square(x.astype(jnp.float32) - m), axis=(0, 2, 3))
                return jax.lax.stop_gradient(true - var_fast)

            var = var_fast + jax.lax.cond(
                bad, corr, lambda _: jnp.zeros_like(var_fast), None)
            inv = jax.lax.rsqrt(var + EPS)
            return apply(x, gamma, beta, mean, inv), mean, var
        return bn_sg

    @jax.custom_vjp
    def bn(x, gamma, beta, center):
        mean, var = stats(x, center)
        inv = jax.lax.rsqrt(var + EPS)
        return apply(x, gamma, beta, mean, inv), mean, var

    def bn_fwd(x, gamma, beta, center):
        mean, var = stats(x, center)
        inv = jax.lax.rsqrt(var + EPS)
        return (apply(x, gamma, beta, mean, inv), mean, var), \
            (x, gamma, mean, inv)

    def bn_bwd(res, cts):
        x, gamma, mean, inv = res
        dy, dmean_ct, dvar_ct = cts
        bshape = (1, x.shape[1], 1, 1)
        n = x.shape[0] * x.shape[2] * x.shape[3]
        if LEANBWD:
            # dx = A*dy + B*x + C with per-channel coefficients from TWO
            # fused reductions (sum dy, sum dy*x) — no full-size f32
            # xmu/xhat temporaries, dx emitted in the compute dtype
            dy32 = dy.astype(jnp.float32)
            sum_dy = jnp.sum(dy32, axis=(0, 2, 3))
            sum_dyx = jnp.sum(dy32 * x.astype(jnp.float32), axis=(0, 2, 3))
            dbeta = sum_dy
            dgamma = inv * (sum_dyx - mean * sum_dy)
            a = inv * gamma
            b = -(inv * inv) * gamma * dgamma / n + 2.0 * dvar_ct / n
            c = -a * dbeta / n + (inv * inv) * gamma * mean * dgamma / n \
                + dmean_ct / n - 2.0 * dvar_ct * mean / n
            dx = (a.reshape(bshape).astype(x.dtype) * dy
                  + b.reshape(bshape).astype(x.dtype) * x
                  + c.reshape(bshape).astype(x.dtype))
            return dx, dgamma, dbeta, jnp.zeros_like(mean)
        xmu = x.astype(jnp.float32) - mean.reshape(bshape)
        xhat = xmu * inv.reshape(bshape)
        dy32 = dy.astype(jnp.float32)
        dbeta = jnp.sum(dy32, axis=(0, 2, 3))
        dgamma = jnp.sum(dy32 * xhat, axis=(0, 2, 3))
        dx = (inv * gamma).reshape(bshape) \
            * (dy32 - (dbeta / n).reshape(bshape)
               - xhat * (dgamma / n).reshape(bshape))
        dx = dx + (dmean_ct / n).reshape(bshape) \
            + (dvar_ct * 2.0 / n).reshape(bshape) * xmu
        return dx.astype(x.dtype), dgamma, dbeta, jnp.zeros_like(mean)

    bn.defvjp(bn_fwd, bn_bwd)
    return bn


LEANBWD = os.environ.get("LEANBWD", "0") == "1"
SGCOND = os.environ.get("SGCOND", "0") == "1"


def make_forward(cfg):
    bn_data, with_aux, smout, bn_custom = (
        cfg["bn_data"], cfg["aux"], cfg["smout"], cfg["bn_custom"])
    bn_core = _bn_custom_core(cfg.get("nocond", False),
                              cfg.get("nocenter", False),
                              cfg.get("autodiff", False)) \
        if bn_custom else None

    def bn_relu(p, aux_in, aux_out, name, x, relu=True):
        if bn_custom:
            center = jax.lax.stop_gradient(aux_in[name + "_mm"]) \
                if with_aux else jnp.zeros((x.shape[1],), jnp.float32)
            y, m, v = bn_core(x, p[name + "_g"], p[name + "_b"], center)
        else:
            m, v = _stats_onepass(x.astype(jnp.float32))
            inv = lax.rsqrt(v + EPS)
            scale = (inv * p[name + "_g"]).astype(x.dtype)
            shift = (p[name + "_b"] - m * inv * p[name + "_g"]) \
                .astype(x.dtype)
            y = x * scale.reshape(1, -1, 1, 1) + shift.reshape(1, -1, 1, 1)
        if with_aux:
            aux_out[name + "_mm"] = 0.9 * aux_in[name + "_mm"] \
                + 0.1 * jax.lax.stop_gradient(m)
            aux_out[name + "_mv"] = 0.9 * aux_in[name + "_mv"] \
                + 0.1 * jax.lax.stop_gradient(v)
        return jnp.maximum(y, 0) if relu else y

    def forward(p, aux_in, x, y):
        aux_out = {}
        h = x
        if bn_data:
            h = bn_relu(p, aux_in, aux_out, "bn_data", h, relu=False)
        h = conv(p, "conv0", h, 7, 2)
        h = bn_relu(p, aux_in, aux_out, "bn0", h)
        h = lax.reduce_window(h, -jnp.inf, lax.max, [1, 1, 3, 3],
                              [1, 1, 2, 2],
                              [(0, 0), (0, 0), (1, 1), (1, 1)])

        def unit(h, nm, s, first):
            a1 = bn_relu(p, aux_in, aux_out, nm + "_bn1", h)
            c1 = conv(p, nm + "_c1", a1, 1, 1)
            a2 = bn_relu(p, aux_in, aux_out, nm + "_bn2", c1)
            c2 = conv(p, nm + "_c2", a2, 3, s)
            a3 = bn_relu(p, aux_in, aux_out, nm + "_bn3", c2)
            c3 = conv(p, nm + "_c3", a3, 1, 1)
            sc = conv(p, nm + "_sc", a1, 1, s) if first else h
            return c3 + sc

        for si, (u, f) in enumerate(zip(UNITS, FILTERS)):
            for ui in range(u):
                nm = f"s{si}u{ui}"
                s = 2 if (ui == 0 and si > 0) else 1
                h = unit(h, nm, s, ui == 0)
        h = bn_relu(p, aux_in, aux_out, "bn_final", h)
        h = jnp.mean(h.astype(jnp.float32), axis=(2, 3))
        logits = h @ p["fc_w"] + p["fc_b"]
        if smout:
            probs = jax.nn.softmax(logits, axis=-1)
            onehot = jax.nn.one_hot(y, 1000, dtype=jnp.float32)
            # SoftmaxOutput semantics: loss whose dlogits == (p - onehot)/N
            # (valid-normalized), probs staged as a step output
            ll = jnp.take_along_axis(
                jnp.log(jnp.maximum(probs, 1e-30)), y[:, None], axis=1)
            loss = -jnp.mean(ll)
            return loss, (aux_out, probs)
        lse = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, y[:, None], axis=1)[:, 0]
        return jnp.mean(lse - ll), (aux_out, None)

    return forward


def run(tag, cfg, iters=15):
    params, aux = build_params(cfg["bn_data"])
    if not cfg["aux"]:
        aux = {}
    forward = make_forward(cfg)
    mom = {k: jnp.zeros_like(v) for k, v in params.items()}
    x = jnp.asarray(rng.rand(N, 3, 224, 224), jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 1000, (N,)), jnp.int32)

    def train(p, mom, aux_in, x, y):
        (loss, (aux_out, probs)), g = jax.value_and_grad(
            forward, has_aux=True)(p, aux_in, x, y)
        newp, newm = {}, {}
        for k in p:
            m = 0.9 * mom[k] + g[k]
            newm[k] = m
            newp[k] = p[k] - 0.1 * m
        return newp, newm, aux_out, loss, probs

    f = jax.jit(train, donate_argnums=(0, 1, 2))
    params, mom, aux, loss, probs = f(params, mom, aux, x, y)
    float(loss)
    t0 = time.time()
    for _ in range(iters):
        params, mom, aux, loss, probs = f(params, mom, aux, x, y)
    float(loss)
    dt = (time.time() - t0) / iters
    print("%-26s %.1f ms/step  %.0f img/s" % (tag, dt * 1e3, N / dt),
          flush=True)
    return dt


BASE = {"bn_data": False, "aux": False, "smout": False, "bn_custom": False,
        "nocond": False, "nocenter": False, "autodiff": False}

VARIANTS = {
    "base": {},
    "bn_data": {"bn_data": True},
    "aux": {"aux": True},
    "smout": {"smout": True},
    "bn_custom": {"bn_custom": True},
    "bn_custom+aux": {"bn_custom": True, "aux": True},
    "bn_custom_nocond": {"bn_custom": True, "nocond": True},
    "bn_custom_nocenter": {"bn_custom": True, "nocond": True,
                           "nocenter": True},
    "bn_centered_autodiff": {"bn_custom": True, "autodiff": True},
    "all": {"bn_data": True, "aux": True, "smout": True,
            "bn_custom": True},
}

if __name__ == "__main__":
    names = sys.argv[1:] or list(VARIANTS)
    for name in names:
        cfg = dict(BASE)
        cfg.update(VARIANTS[name])
        run(name, cfg)
