#!/usr/bin/env python
"""Input-pipeline benchmark: can ImageIter's decode feed the TPU train rate?

The reference decodes JPEG with multi-threaded C++ workers
(src/io/iter_image_recordio.cc:31-343); here decode is cv2 (GIL-releasing)
under a Python ThreadPool (image.py preprocess_threads).  This benchmark
measures end-to-end iterator throughput — RecordIO read + JPEG decode +
augment + batch assembly — against the measured ResNet-50 train rate, so
the "is the real-data path input-bound?" question has a number.

Run: python benchmarks/bench_input_pipeline.py [--images N] [--batch B]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

TRAIN_RATE_IMG_S = 2464   # bench.py, this repo's round-4 chip measurement


def make_dataset(path_rec, path_idx, n, hw=256):
    import cv2

    from mxnet_tpu import recordio

    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(path_idx, path_rec, "w")
    for i in range(n):
        # realistic JPEG entropy: smoothed noise, quality 90 (im2rec default)
        img = rng.randint(0, 255, (hw, hw, 3), np.uint8)
        img = cv2.blur(img, (4, 4))
        ok, buf = cv2.imencode(".jpg", img,
                               [int(cv2.IMWRITE_JPEG_QUALITY), 90])
        assert ok
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 1000), i, 0), buf.tobytes()))
    w.close()


def bench_iter(path_rec, path_idx, batch, threads, epochs=3):
    import mxnet_tpu as mx

    it = mx.image.ImageIter(
        batch_size=batch, data_shape=(3, 224, 224),
        path_imgrec=path_rec, path_imgidx=path_idx,
        shuffle=True, rand_crop=True, rand_mirror=True, seed=0,
        preprocess_threads=threads)
    n = 0
    # warm epoch (thread pool spin-up, page cache); don't count pad slots
    for b in it:
        n += b.data[0].shape[0] - b.pad
    per_epoch = n
    t0 = time.perf_counter()
    for _ in range(epochs):
        it.reset()
        for b in it:
            pass
    dt = time.perf_counter() - t0
    return per_epoch * epochs / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=512)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--threads", default="1,2,4,8,16")
    args = ap.parse_args()

    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        rec = os.path.join(tmp, "bench.rec")
        idx = os.path.join(tmp, "bench.idx")
        make_dataset(rec, idx, args.images)
        size_mb = os.path.getsize(rec) / 2 ** 20
        print("dataset: %d jpegs, %.1f MB" % (args.images, size_mb),
              flush=True)
        best = 0.0
        for t in [int(x) for x in args.threads.split(",")]:
            rate = bench_iter(rec, idx, args.batch, t)
            best = max(best, rate)
            print("preprocess_threads=%-2d : %7.0f img/s  (%.2fx the "
                  "%d img/s train rate)"
                  % (t, rate, rate / TRAIN_RATE_IMG_S, TRAIN_RATE_IMG_S),
                  flush=True)
        verdict = "input-bound" if best < TRAIN_RATE_IMG_S else "compute-bound"
        print("best decode rate %.0f img/s -> real-data training is %s "
              "on this host" % (best, verdict), flush=True)


if __name__ == "__main__":
    main()
